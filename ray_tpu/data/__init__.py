"""ray_tpu.data: block-parallel datasets with streaming execution.

Parity: reference python/ray/data/__init__.py read APIs (range:*,
from_items, read_*, from_pandas/numpy).
"""

from __future__ import annotations

import builtins as _builtins
import glob as _glob
import math
from typing import Any, Iterable

import numpy as np

import ray_tpu
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import DataIterator, Dataset, GroupedData


def _to_blocks(rows: list, num_blocks: int | None) -> list:
    n = num_blocks or min(DataContext.get_current().default_block_count,
                          max(1, len(rows)))
    per = math.ceil(len(rows) / n) if rows else 0
    blocks = [rows[i * per:(i + 1) * per] for i in _builtins.range(n)]
    return [b for b in blocks if b] or [[]]


def from_items(items: list, *, override_num_blocks: int | None = None) -> Dataset:
    return Dataset(_to_blocks(list(items), override_num_blocks))


def range(n: int, *, override_num_blocks: int | None = None) -> Dataset:  # noqa: A001
    return from_items(list(_builtins.range(n)),
                      override_num_blocks=override_num_blocks)


def range_tensor(n: int, *, shape: tuple = (1,),
                 override_num_blocks: int | None = None) -> Dataset:
    rows = [{"data": np.full(shape, i, dtype=np.int64)}
            for i in _builtins.range(n)]
    return from_items(rows, override_num_blocks=override_num_blocks)


def from_numpy(arr: "np.ndarray", *, column: str = "data",
               override_num_blocks: int | None = None) -> Dataset:
    rows = [{column: a} for a in arr]
    return from_items(rows, override_num_blocks=override_num_blocks)


def from_pandas(df, *, override_num_blocks: int | None = None) -> Dataset:
    rows = df.to_dict("records")
    return from_items(rows, override_num_blocks=override_num_blocks)


def _lazy_read(files: list, read_one, override_num_blocks: int | None
               ) -> Dataset:
    """Deferred ReadTasks: the reads run as cluster tasks when the dataset
    executes (reference: data/datasource read tasks; the driver never
    materializes the input).  Default: one block per file.
    override_num_blocks < len(files) groups files into that many read
    tasks; more blocks than files can't be honored without reading (row
    counts unknown), so the block count stays at len(files) — chain
    .repartition(n) to force it."""
    from ray_tpu.data.dataset import ReadTask

    def read_group(group):
        out = []
        for p in group:
            out.extend(read_one(p))
        return out

    groups = [[p] for p in files]
    if override_num_blocks is not None and 0 < override_num_blocks < len(files):
        n = override_num_blocks
        per = math.ceil(len(files) / n)
        groups = [files[i * per:(i + 1) * per] for i in _builtins.range(n)]
        groups = [g for g in groups if g]
    return Dataset([ReadTask(fn=(lambda g=g: read_group(g)))
                    for g in groups])


def read_text(paths: str | list, *, override_num_blocks: int | None = None
              ) -> Dataset:
    def read_one(p):
        with _open(p) as f:
            return [{"text": line.rstrip("\n")} for line in f]

    return _lazy_read(_expand(paths), read_one, override_num_blocks)


def read_json(paths: str | list, *, lines: bool = True,
              override_num_blocks: int | None = None) -> Dataset:
    def read_one(p, lines=lines):
        import json

        with _open(p) as f:
            if lines:
                return [json.loads(ln) for ln in f if ln.strip()]
            data = json.load(f)
            return data if isinstance(data, list) else [data]

    return _lazy_read(_expand(paths), read_one, override_num_blocks)


def from_arrow(tables, *, override_num_blocks: int | None = None) -> Dataset:
    """Dataset over pyarrow Tables — one block per table (reference:
    ray.data.from_arrow; tables are the reference's native block format)."""
    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    return Dataset(list(tables))


def from_huggingface(hf_dataset, *,
                     override_num_blocks: int | None = None) -> Dataset:
    """Dataset from a Hugging Face `datasets.Dataset` (reference:
    ray.data.from_huggingface). Arrow-backed HF datasets hand over
    their table directly (zero row materialization) — EXCEPT when an
    indices mapping is live (shuffle/select/filter/train_test_split
    apply lazily via _indices; .data.table would leak the unselected
    rows), where rows materialize through the HF API instead."""
    data = getattr(hf_dataset, "data", None)
    table = getattr(data, "table", None) if data is not None else None
    if table is not None and getattr(hf_dataset, "_indices", None) is None:
        import pyarrow as pa

        if isinstance(table, pa.Table):
            n = override_num_blocks
            if n and n > 1 and table.num_rows > 1:
                per = math.ceil(table.num_rows / n)
                return from_arrow([
                    table.slice(i * per, per)
                    for i in _builtins.range(n) if i * per < table.num_rows])
            return from_arrow(table)
    return from_items(list(hf_dataset),
                      override_num_blocks=override_num_blocks)


def _read_parquet_group(group, columns, filters, endpoint_url=None):
    """One parquet read task (module-level so pushdown can rebuild it with
    pruned columns/filters). s3:// objects fetch through the stdlib S3
    client into a seekable buffer."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data import s3 as _s3

    tables = []
    for p in group:
        src = _s3.open_uri(p, endpoint_url) if _s3.is_s3_uri(p) else p
        tables.append(pq.read_table(src, columns=columns, filters=filters))
    return tables[0] if len(tables) == 1 else pa.concat_tables(tables)


def read_parquet(paths: str | list, *, columns: list | None = None,
                 filters: list | None = None,
                 endpoint_url: str | None = None,
                 override_num_blocks: int | None = None) -> Dataset:
    """Arrow-native parquet read: each read task yields a pyarrow.Table
    block (reference: ray.data.read_parquet over Arrow datasets; tables
    pickle with protocol-5 buffers so they move through the shm store
    zero-copy). The ReadTasks carry structured metadata so a following
    select_columns()/filter(expr=...) pushes down into the reader
    (reference: data/_internal/logical optimizer rules). Paths may be
    s3:// URIs against an S3-compatible endpoint (data/s3.py)."""
    import functools

    from ray_tpu.data.dataset import ReadTask

    files = _expand(paths, endpoint_url=endpoint_url)
    groups = [[p] for p in files]
    if override_num_blocks is not None and 0 < override_num_blocks < len(files):
        n = override_num_blocks
        per = math.ceil(len(files) / n)
        groups = [files[i * per:(i + 1) * per] for i in _builtins.range(n)]
        groups = [g for g in groups if g]

    tasks = []
    for g in groups:
        meta = {"kind": "parquet", "group": list(g), "columns": columns,
                "filters": filters, "endpoint_url": endpoint_url}
        tasks.append(ReadTask(
            fn=functools.partial(_read_parquet_group, list(g), columns,
                                 filters, endpoint_url),
            meta=meta))
    return Dataset(tasks)


def read_csv(paths: str | list, *, override_num_blocks: int | None = None
             ) -> Dataset:
    def read_one(p):
        import csv

        with _open(p) as f:
            return [dict(r) for r in csv.DictReader(f)]

    return _lazy_read(_expand(paths), read_one, override_num_blocks)


def read_numpy(paths: str | list, *, override_num_blocks: int | None = None
               ) -> Dataset:
    def read_one(p):
        import numpy as _np

        return [{"data": a} for a in _np.load(p)]

    return _lazy_read(_expand(paths), read_one, override_num_blocks)


def read_tfrecords(paths: str | list, *,
                   override_num_blocks: int | None = None,
                   verify_crc: bool = False) -> Dataset:
    """Rows from TFRecord files of tf.train.Example protos (reference:
    data/read_api.py read_tfrecords — parsed here by the dependency-free
    codec in data/tfrecord.py; no TensorFlow required)."""

    def read_one(p, verify=verify_crc):
        from ray_tpu.data import tfrecord as _tfr

        with _open(p, "rb") as f:    # s3:// URIs route through _open
            return [_tfr.parse_example(rec)
                    for rec in _tfr.read_records(f, verify=verify)]

    return _lazy_read(_expand(paths), read_one, override_num_blocks)


def read_binary_files(paths: str | list, *, include_paths: bool = False,
                      override_num_blocks: int | None = None) -> Dataset:
    """One row per file with raw bytes (reference:
    data/read_api.py read_binary_files)."""

    def read_one(p, include_paths=include_paths):
        with _open(p, "rb") as f:
            data = f.read()
        row = {"bytes": data}
        if include_paths:
            row["path"] = p
        return [row]

    return _lazy_read(_expand(paths), read_one, override_num_blocks)


def read_images(paths: str | list, *, include_paths: bool = False,
                mode: str | None = None, size: tuple | None = None,
                override_num_blocks: int | None = None) -> Dataset:
    """One row per image file with an ndarray "image" column (reference:
    data/read_api.py read_images, incl. its (height, width) `size`
    convention). mode: PIL convert target (e.g. "RGB"); a fixed size makes
    the column batch into one dense array, the shape TPU input pipelines
    want."""
    try:
        import PIL  # noqa: F401
    except ImportError as e:  # pragma: no cover
        raise ImportError("read_images requires pillow") from e

    def read_one(p, include_paths=include_paths, mode=mode, size=size):
        import numpy as _np
        from PIL import Image

        with Image.open(p) as f:
            img = f.convert(mode) if mode else f
            if size:
                # size is (height, width); PIL resize takes (width, height).
                img = img.resize((size[1], size[0]))
            arr = _np.asarray(img)
        row = {"image": arr}
        if include_paths:
            row["path"] = p
        return [row]

    return _lazy_read(_expand(paths), read_one, override_num_blocks)


def _expand(paths: str | list, endpoint_url: str | None = None) -> list:
    from ray_tpu.data import s3 as _s3

    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        if _s3.is_s3_uri(p):
            listed = sorted(_s3.expand_uri(p, endpoint_url))
            out.extend(listed if listed else [p])
            continue
        matches = sorted(_glob.glob(p))
        out.extend(matches if matches else [p])
    return out


def _open(path: str, mode: str = "r", endpoint_url: str | None = None):
    """Open a local path or s3:// object for the row-based readers."""
    from ray_tpu.data import s3 as _s3

    if _s3.is_s3_uri(path):
        buf = _s3.open_uri(path, endpoint_url)
        if "b" in mode:
            return buf
        import io as _io

        return _io.TextIOWrapper(buf, encoding="utf-8")
    return open(path, mode)


from ray_tpu.data.mongo import read_mongo, write_mongo  # noqa: E402
from ray_tpu.data.optimizer import (  # noqa: E402
    Rule,
    register_optimizer_rule,
)
from ray_tpu.data.sql import read_sql, read_webdataset  # noqa: E402

__all__ = [
    "DataContext",
    "Dataset", "DataIterator", "GroupedData", "from_items", "range",
    "range_tensor", "from_numpy", "from_pandas", "from_arrow", "read_text",
    "read_json", "read_csv", "read_numpy", "read_parquet",
    "read_binary_files", "read_images", "read_tfrecords", "from_huggingface",
    "read_sql", "read_webdataset", "read_mongo", "write_mongo",
    "Rule", "register_optimizer_rule",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu('data')
del _rlu
