"""DataContext: process-wide execution knobs for ray_tpu.data
(reference: python/ray/data/context.py — DataContext.get_current()).

    ctx = ray_tpu.data.DataContext.get_current()
    ctx.max_in_flight_blocks = 16   # streaming backpressure window
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import ClassVar


@dataclass
class DataContext:
    # Streaming executor backpressure: how many block-transform tasks may
    # be outstanding per pipeline segment (reference: ExecutionResources
    # limits in streaming_executor.py:280).
    max_in_flight_blocks: int = 8
    # Byte-based backpressure: estimated in-flight block bytes are kept
    # under this budget (0 disables). Sizes are learned from completed
    # blocks, so >RAM datasets stream with a bounded footprint.
    max_in_flight_bytes: int = 512 * 1024 * 1024
    # Default block count for from_items/range when unspecified.
    default_block_count: int = 8
    # Per-block remote task timeout (seconds) in the streaming loop.
    block_task_timeout_s: float = 300.0
    # Logical-optimizer catalog override: None = the built-in rules from
    # ray_tpu/data/optimizer.py (plus any register_optimizer_rule()
    # additions, reference: _user_provided_optimizer_rules.py). Set to a
    # list of Rule instances to replace the catalog wholesale.
    optimizer_rules: list | None = None

    _lock: ClassVar[threading.Lock] = threading.Lock()
    _current: ClassVar["DataContext | None"] = None

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = cls()
            return cls._current
