"""Blocks: the unit of data in ray_tpu.data.

Parity: reference python/ray/data/block.py — blocks are Arrow/pandas/numpy
tables living in plasma. Here a block is one of:
  - a pyarrow.Table (columnar; the reference's primary format — pickles
    with protocol-5 out-of-band buffers, so tables round-trip through the
    shm store zero-copy and parquet IO is native),
  - a dict of numpy column arrays (the TPU feed format: contiguous
    columns that `jax.device_put` ships to HBM without conversion),
  - a list of rows (simple format).
Blocks travel as object-store refs so the streaming executor moves
references, not data.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow ships in the image
    pa = None


def is_arrow(block) -> bool:
    return pa is not None and isinstance(block, pa.Table)


def block_len(block) -> int:
    if is_arrow(block):
        return block.num_rows
    if isinstance(block, dict):
        if not block:
            return 0
        return len(next(iter(block.values())))
    return len(block)


def block_to_rows(block) -> list:
    if is_arrow(block):
        return block.to_pylist()
    if isinstance(block, dict):
        keys = list(block.keys())
        n = block_len(block)
        return [{k: block[k][i] for k in keys} for i in range(n)]
    return list(block)


def rows_to_batch(rows: list) -> dict:
    """rows of dicts → dict of numpy arrays; non-dict rows get 'item'."""
    if not rows:
        return {}
    if isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return {"item": np.asarray(rows)}


def block_to_batch(block) -> dict:
    if is_arrow(block):
        # Columnar → numpy dict; fixed-width columns come out zero-copy
        # when the table is a single chunk. Tensor-extension columns
        # (fixed-shape ndarrays — reference: ray.data tensor extensions)
        # come back as (n, *shape) arrays.
        out = {}
        for name in block.column_names:
            col = block.column(name)
            if isinstance(col.type, pa.FixedShapeTensorType):
                out[name] = col.combine_chunks().to_numpy_ndarray()
                continue
            try:
                out[name] = col.to_numpy(zero_copy_only=False)
            except Exception:
                out[name] = np.asarray(col.to_pylist())
        return out
    if isinstance(block, dict):
        return block
    return rows_to_batch(block)


def _column_to_arrow(arr):
    """numpy column → arrow array; multi-dim columns become fixed-shape
    tensor extension arrays (one tensor per row), which survive parquet
    round-trips with their shape."""
    arr = np.asarray(arr)
    if arr.ndim > 1:
        return pa.FixedShapeTensorArray.from_numpy_ndarray(
            np.ascontiguousarray(arr))
    return pa.array(arr)


def block_to_arrow(block):
    if pa is None:
        raise ImportError("pyarrow is required for arrow blocks")
    if is_arrow(block):
        return block
    if isinstance(block, dict):
        return pa.table({k: _column_to_arrow(v) for k, v in block.items()})
    rows = block_to_rows(block)
    if rows and not isinstance(rows[0], dict):
        rows = [{"item": r} for r in rows]
    if rows and isinstance(rows[0], dict):
        # Rows whose values are ndarrays of one fixed shape batch into
        # tensor columns; ragged/mixed shapes fall back to pylist.
        cols = {}
        tensorable = True
        for k in rows[0]:
            vals = [r[k] for r in rows]
            if isinstance(vals[0], np.ndarray) and all(
                    isinstance(v, np.ndarray)
                    and v.shape == vals[0].shape
                    and v.dtype == vals[0].dtype for v in vals):
                cols[k] = _column_to_arrow(np.stack(vals))
            elif isinstance(vals[0], np.ndarray):
                tensorable = False
                break
            else:
                cols[k] = pa.array(vals)
        if tensorable and cols:
            return pa.table(cols)
    return pa.Table.from_pylist(rows)


def batch_to_block(batch, batch_format: str):
    if batch_format in ("pyarrow", "arrow"):
        return batch if is_arrow(batch) else block_to_arrow(batch)
    if batch_format in ("numpy", "batch", "dict"):
        return batch
    return block_to_rows(batch)


def slice_block(block, start: int, end: int):
    if is_arrow(block):
        return block.slice(start, end - start)
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    return block[start:end]


def concat_blocks(blocks: list):
    blocks = [b for b in blocks if block_len(b)]
    if not blocks:
        return []
    if is_arrow(blocks[0]):
        return pa.concat_tables(block_to_arrow(b) for b in blocks)
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    out = []
    for b in blocks:
        out.extend(block_to_rows(b))
    return out


def block_nbytes(block) -> int:
    """Approximate in-memory size (backpressure accounting)."""
    if is_arrow(block):
        return block.nbytes
    if isinstance(block, dict):
        return sum(getattr(v, "nbytes", len(v) * 8) for v in block.values())
    return len(block) * 64  # rough row estimate
