"""Blocks: the unit of data in ray_tpu.data.

Parity: reference python/ray/data/block.py — blocks are Arrow/pandas/numpy
tables living in plasma. Here a block is either a list of rows (simple
format) or a dict of numpy column arrays (batch format); blocks travel as
object-store refs so the streaming executor moves references, not data.
The numpy-dict format is the TPU feed format: columns are contiguous
arrays that `jax.device_put` ships to HBM without conversion.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np


def block_len(block) -> int:
    if isinstance(block, dict):
        if not block:
            return 0
        return len(next(iter(block.values())))
    return len(block)


def block_to_rows(block) -> list:
    if isinstance(block, dict):
        keys = list(block.keys())
        n = block_len(block)
        return [{k: block[k][i] for k in keys} for i in range(n)]
    return list(block)


def rows_to_batch(rows: list) -> dict:
    """rows of dicts → dict of numpy arrays; non-dict rows get 'item'."""
    if not rows:
        return {}
    if isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return {"item": np.asarray(rows)}


def block_to_batch(block) -> dict:
    if isinstance(block, dict):
        return block
    return rows_to_batch(block)


def batch_to_block(batch, batch_format: str):
    if batch_format in ("numpy", "batch", "dict"):
        return batch
    return block_to_rows(batch)


def slice_block(block, start: int, end: int):
    if isinstance(block, dict):
        return {k: v[start:end] for k, v in block.items()}
    return block[start:end]


def concat_blocks(blocks: list):
    blocks = [b for b in blocks if block_len(b)]
    if not blocks:
        return []
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    out = []
    for b in blocks:
        out.extend(block_to_rows(b))
    return out
