"""Dataset: lazy block-parallel data pipelines executed as ray_tpu tasks.

Parity: reference python/ray/data/dataset.py:178 (Dataset, map_batches:397,
iter_batches:3499) with the streaming execution model of
data/_internal/execution/streaming_executor.py:49 — a logical plan of
stages, executed block-parallel with bounded in-flight tasks
(backpressure), blocks living in the shared-memory object store.

TPU-first addition: `iter_jax_batches` feeds mesh-sharded device arrays
(the host-CPU data plane feeding per-host jax.device_put, SURVEY.md §7
stage 8).
"""

from __future__ import annotations

import math
import random as _random
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    batch_to_block,
    block_len,
    block_to_batch,
    block_to_rows,
    concat_blocks,
    rows_to_batch,
    slice_block,
)



@dataclass
class _Stage:
    name: str
    fn: Callable  # block -> block  (run remotely)
    # Logical-plan pushdown tags (reference: data/_internal/logical
    # optimizer rules): when the stage directly follows parquet ReadTasks,
    # the executor folds it into the read itself — a projection prunes
    # columns at the file reader, a (col, op, literal) predicate prunes
    # row groups/rows — and drops the stage from the physical plan.
    pushdown_projection: list | None = None
    pushdown_filter: tuple | None = None
    all_to_all: bool = False  # needs every input block materialized first
    # Order-only barrier (randomize_block_order): all_to_all_fn permutes
    # the list of block REFS — blocks are never fetched or touched.
    reorder: bool = False
    all_to_all_fn: Callable | None = None  # blocks(list of refs) -> list[blocks]
    num_cpus: float = 1.0
    # >0: run on a pool of stateful actors instead of tasks (parity:
    # reference ActorPoolMapOperator for callable-class UDFs).
    actor_pool: int = 0
    # Distributed shuffle barrier (parity: reference push-based shuffle,
    # data/_internal/push_based_shuffle.py): map tasks split each block into
    # n_out partitions (separate objects via num_returns), reduce task j
    # merges partition j of every map — blocks never route through the
    # driver. shuffle_map_fn(block, n_out, index) -> [n_out blocks];
    # shuffle_reduce_fn(parts, j) -> block.
    shuffle_map_fn: Callable | None = None
    shuffle_reduce_fn: Callable | None = None
    # Optional driver-side planner run before the maps: samples small
    # per-block digests to compute partition boundaries (distributed sort).
    # shuffle_plan_fn(sampled) -> aux passed to map/reduce fns.
    shuffle_sample_fn: Callable | None = None
    shuffle_plan_fn: Callable | None = None


# Index of the block currently being transformed — lets seeded per-block
# stages (random_sample) derive a DISTINCT stream per block instead of
# replaying one sequence on every block (which would correlate the draws).
_current_block_index = 0


@ray_tpu.remote
def _apply_fused(fn_blobs, block, index=0):
    """Run a FUSED chain of per-block stage fns in one task (logical->
    physical optimization: consecutive row/batch transforms collapse into
    a single operator, reference: data/_internal/logical optimizer's
    fuse rules — N stages cost one task and zero intermediate objects)."""
    import ray_tpu.data.dataset as _ds
    from ray_tpu._private import serialization

    _ds._current_block_index = index
    for blob in fn_blobs:
        block = serialization.loads_func(blob)(block)
    return block


def _apply_stage(fn_blob, block, index=0):
    import ray_tpu.data.dataset as _ds
    from ray_tpu._private import serialization

    _ds._current_block_index = index
    fn = serialization.loads_func(fn_blob)
    return fn(block)


@dataclass
class ReadTask:
    """A deferred source block: `fn()` produces the block rows when executed
    remotely (reference: data/datasource ReadTask — reads run as cluster
    tasks, never materializing the whole dataset on the driver)."""

    fn: Callable
    # Metadata the driver may know without reading (row count for
    # splits/estimates; None when unknown).
    num_rows: int | None = None
    # Structured description for optimizer pushdown; parquet shape:
    # {"kind": "parquet", "group": [paths], "columns": list|None,
    #  "filters": list|None, "endpoint_url": str|None}. None = opaque fn.
    meta: dict | None = None


def _pushdown_rewrite(source: list, stages: list) -> tuple[list, list]:
    """Back-compat shim over the optimizer's ParquetReadPushdown rule
    (the full catalog lives in ray_tpu/data/optimizer.py)."""
    from ray_tpu.data.optimizer import LogicalPlan, ParquetReadPushdown

    plan = ParquetReadPushdown().apply(LogicalPlan(source, stages))
    return plan.source, plan.stages


@ray_tpu.remote
def _exec_read(fn_blob):
    from ray_tpu._private import serialization

    return serialization.loads_func(fn_blob)()


@ray_tpu.remote
def _shuffle_map(map_blob, block, n_out, index, aux):
    from ray_tpu._private import serialization

    fn = serialization.loads_func(map_blob)
    parts = fn(block, n_out, index, aux)
    return parts if n_out > 1 else parts[0]


@ray_tpu.remote
def _shuffle_reduce(reduce_blob, j, aux, *parts):
    from ray_tpu._private import serialization

    fn = serialization.loads_func(reduce_blob)
    return fn(list(parts), j, aux)


@ray_tpu.remote
def _shuffle_sample(sample_blob, block):
    from ray_tpu._private import serialization

    return serialization.loads_func(sample_blob)(block)


def _device_runtime_ready() -> bool:
    """True when this process is attached to a running cluster (the
    device-object plane can route landings); standalone/local use of
    Dataset falls back to host-side device_put."""
    try:
        from ray_tpu._private.api_internal import get_core_worker

        return get_core_worker() is not None
    except Exception:
        return False


@ray_tpu.remote
def _land_block_jax(block):
    """Device-landing stage for iter_jax_batches: the host→HBM copy for
    a block's numeric columns happens HERE, on a worker, and the arrays
    return as pinned device objects (tensor_transport="device") — the
    consumer resolves them over the device plane instead of paying the
    copy itself."""
    import jax

    batch = rows_to_batch(block_to_rows(block))
    return {k: jax.device_put(np.ascontiguousarray(np.asarray(v)))
            for k, v in batch.items()}


@ray_tpu.remote
class _StageActor:
    """Stateful map worker: constructs the UDF once, applies it per block."""

    def __init__(self, fn_blob):
        from ray_tpu._private import serialization

        self._fn = serialization.loads_func(fn_blob)

    def apply(self, block):
        return self._fn(block)


class Dataset:
    """Lazy, immutable; transforms return new Datasets."""

    def __init__(self, source_blocks: list, stages: list[_Stage] | None = None):
        # source_blocks: list of ObjectRefs OR in-memory blocks (small data).
        self._source = source_blocks
        self._stages = stages or []

    # ------------- transforms (lazy) -------------

    def _with(self, stage: _Stage) -> "Dataset":
        return Dataset(self._source, self._stages + [stage])

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    batch_size: int | None = None,
                    concurrency: int | None = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: dict | None = None,
                    **_ignored) -> "Dataset":
        is_class = isinstance(fn, type)
        if is_class and concurrency is None:
            concurrency = 2

        def stage_fn(block, fn=fn, batch_format=batch_format,
                     batch_size=batch_size, is_class=is_class,
                     ctor_args=fn_constructor_args,
                     ctor_kwargs=fn_constructor_kwargs):
            if is_class:
                # Construct once per process (the _StageActor deserializes
                # this function a single time, so the attribute persists
                # across blocks — stateful UDF semantics).
                udf = getattr(stage_fn, "_cached_udf", None)
                if udf is None:
                    udf = fn(*ctor_args, **(ctor_kwargs or {}))
                    stage_fn._cached_udf = udf
            else:
                udf = fn
            def to_batch(piece):
                if batch_format in ("pyarrow", "arrow"):
                    from ray_tpu.data.block import block_to_arrow

                    return block_to_arrow(piece)
                if batch_format == "numpy":
                    return block_to_batch(piece)
                return block_to_rows(piece)

            if batch_size is None:
                return batch_to_block(udf(to_batch(block)), batch_format)
            outs = []
            n = block_len(block)
            for s in range(0, n, batch_size):
                piece = slice_block(block, s, min(s + batch_size, n))
                outs.append(batch_to_block(udf(to_batch(piece)), batch_format))
            return concat_blocks(outs)

        return self._with(_Stage("map_batches", stage_fn,
                                 actor_pool=concurrency or 0))

    def map(self, fn: Callable) -> "Dataset":
        def stage_fn(block, fn=fn):
            return [fn(r) for r in block_to_rows(block)]

        return self._with(_Stage("map", stage_fn))

    def filter(self, fn: Callable | None = None, *,
               expr: tuple | None = None) -> "Dataset":
        """Keep rows matching `fn`, or a structured `expr` of the form
        (column, op, literal) with op in {==, !=, <, <=, >, >=, in,
        not in}. Expression form is optimizer-visible: directly after a
        parquet read it pushes down to row-group/row pruning inside the
        read task (reference: logical-plan predicate pushdown)."""
        if (fn is None) == (expr is None):
            raise ValueError("filter takes exactly one of fn or expr")
        if expr is not None:
            col, op, lit = expr
            import operator as _op

            ops = {"==": _op.eq, "!=": _op.ne, "<": _op.lt, "<=": _op.le,
                   ">": _op.gt, ">=": _op.ge,
                   "in": lambda a, b: a in b,
                   "not in": lambda a, b: a not in b}
            if op not in ops:
                raise ValueError(f"unsupported filter op {op!r}")

            def stage_fn(block, col=col, f=ops[op], lit=lit):
                return [r for r in block_to_rows(block) if f(r[col], lit)]

            return self._with(_Stage("filter", stage_fn,
                                     pushdown_filter=(col, op, lit)))

        def stage_fn(block, fn=fn):
            return [r for r in block_to_rows(block) if fn(r)]

        return self._with(_Stage("filter", stage_fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        def stage_fn(block, fn=fn):
            out = []
            for r in block_to_rows(block):
                out.extend(fn(r))
            return out

        return self._with(_Stage("flat_map", stage_fn))

    def randomize_block_order(self, seed: int | None = None) -> "Dataset":
        """Shuffle BLOCK order without touching rows (parity:
        dataset.py randomize_block_order) — an order-only barrier that
        permutes block refs, zero data movement. The optimizer pushes it
        past map stages and deletes it when a random_shuffle follows
        (optimizer.py ReorderRandomizeBlocks / DropRedundantRandomize,
        reference: logical/rules/randomize_blocks.py)."""
        def reorder_fn(blocks, seed=seed):
            rng = _random.Random(seed)
            out = list(blocks)
            rng.shuffle(out)
            return out

        return self._with(_Stage(name="randomize_block_order", fn=None,
                                 all_to_all=True, all_to_all_fn=reorder_fn,
                                 reorder=True))

    def random_shuffle(self, seed: int | None = None) -> "Dataset":
        """Distributed push-based shuffle: each map task scatters its rows
        across n_out partitions, each reduce task merges and re-shuffles one
        partition (reference: data/_internal/push_based_shuffle.py)."""
        def map_fn(block, n_out, index, aux, seed=seed):
            rows = block_to_rows(block)
            rng = _random.Random(None if seed is None
                                 else seed * 1_000_003 + index)
            parts = [[] for _ in range(n_out)]
            for r in rows:
                parts[rng.randrange(n_out)].append(r)
            return parts

        def reduce_fn(parts, j, aux, seed=seed):
            rows = []
            for p in parts:
                rows.extend(block_to_rows(p))
            rng = _random.Random(None if seed is None
                                 else seed * 7_368_787 + j)
            rng.shuffle(rows)
            return rows

        return self._with(_Stage("random_shuffle", None,
                                 shuffle_map_fn=map_fn,
                                 shuffle_reduce_fn=reduce_fn))

    def repartition(self, num_blocks: int) -> "Dataset":
        def repart_fn(blocks: list, num_blocks=num_blocks):
            rows = []
            for b in blocks:
                rows.extend(block_to_rows(b))
            per = math.ceil(len(rows) / num_blocks) if rows else 0
            return [rows[i * per:(i + 1) * per] for i in range(num_blocks)]

        return self._with(_Stage("repartition", None, all_to_all=True,
                                 all_to_all_fn=repart_fn))

    def limit(self, n: int) -> "Dataset":
        """First n rows (parity: dataset.py Dataset.limit)."""
        ds = self
        if not self._stages and len(self._source) > 1:
            # Limit pushdown (reference: the logical optimizer's limit
            # rule): when source row counts are known without reading
            # (materialized blocks, ReadTasks with num_rows metadata —
            # e.g. sql shards), trailing sources past the limit are
            # dropped BEFORE any read executes.
            counts: list = []
            for s in self._source:
                if isinstance(s, ReadTask):
                    counts.append(s.num_rows)
                elif isinstance(s, list):
                    counts.append(len(s))
                else:
                    counts.append(None)
            if all(c is not None for c in counts):
                acc, keep = 0, []
                for s, c in zip(self._source, counts):
                    keep.append(s)
                    acc += c
                    if acc >= n:
                        break
                if len(keep) < len(self._source):
                    ds = Dataset(keep, [])
        rows = []
        for r in ds.iter_rows():
            rows.append(r)
            if len(rows) >= n:
                break
        return Dataset([rows], [])

    def random_sample(self, fraction: float, *, seed: int | None = None
                      ) -> "Dataset":
        def stage_fn(block, fraction=fraction, seed=seed):
            import ray_tpu.data.dataset as _ds

            block_seed = None if seed is None \
                else seed * 1_000_003 + _ds._current_block_index
            rng = _random.Random(block_seed)
            return [r for r in block_to_rows(block)
                    if rng.random() < fraction]

        return self._with(_Stage("random_sample", stage_fn))

    def unique(self, column: str) -> list:
        seen = []
        seen_set = set()
        for r in self.iter_rows():
            v = r[column] if isinstance(r, dict) else r
            if v not in seen_set:
                seen_set.add(v)
                seen.append(v)
        return seen

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def stage_fn(block, name=name, fn=fn):
            batch = block_to_batch(block)
            batch = dict(batch)
            batch[name] = np.asarray(fn(batch))
            return batch

        return self._with(_Stage("add_column", stage_fn))

    def drop_columns(self, cols: list[str]) -> "Dataset":
        def stage_fn(block, cols=tuple(cols)):
            batch = block_to_batch(block)
            return {k: v for k, v in batch.items() if k not in cols}

        return self._with(_Stage("drop_columns", stage_fn))

    def select_columns(self, cols: list[str]) -> "Dataset":
        def stage_fn(block, cols=tuple(cols)):
            batch = block_to_batch(block)
            return {k: batch[k] for k in cols}

        return self._with(_Stage("select_columns", stage_fn,
                                 pushdown_projection=list(cols)))

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-wise zip of two datasets (parity: Dataset.zip)."""
        rows_a = self.take_all()
        rows_b = other.take_all()
        if len(rows_a) != len(rows_b):
            raise ValueError(
                f"zip requires equal row counts ({len(rows_a)} vs {len(rows_b)})")
        out = []
        for a, b in _builtin_zip(rows_a, rows_b):
            if isinstance(a, dict) and isinstance(b, dict):
                merged = dict(a)
                for k, v in b.items():
                    merged[k if k not in merged else k + "_1"] = v
                out.append(merged)
            else:
                out.append((a, b))
        return Dataset([out], [])

    def groupby(self, key: str | Callable) -> "GroupedData":
        return GroupedData(self, key)

    def sort(self, key: Callable | str | None = None,
             descending: bool = False) -> "Dataset":
        """Distributed range-partitioned sort: sample keys per block →
        boundaries on the driver → maps route rows by range → each reduce
        sorts one disjoint range (reference: data sort_and_partition /
        push-based shuffle reduce)."""
        def key_of(r, key=key):
            if key is None:
                return r
            if isinstance(key, str):
                return r[key]
            return key(r)

        def sample_fn(block):
            rows = block_to_rows(block)
            # ~20 evenly-spaced key samples per block.
            step = max(1, len(rows) // 20)
            return [key_of(r) for r in rows[::step]]

        def plan_fn(sampled, descending=descending):
            return {"keys": sorted(k for s in sampled for k in s)}

        def map_fn(block, n_out, index, aux, descending=descending):
            import bisect

            keys = aux["keys"]
            # n_out-1 boundaries at sample quantiles.
            bounds = [keys[(i + 1) * len(keys) // n_out]
                      for i in range(n_out - 1)] if keys else []
            parts = [[] for _ in range(n_out)]
            for r in block_to_rows(block):
                j = bisect.bisect_right(bounds, key_of(r))
                if descending:
                    j = n_out - 1 - j
                parts[j].append(r)
            return parts

        def reduce_fn(parts, j, aux, descending=descending):
            rows = []
            for p in parts:
                rows.extend(block_to_rows(p))
            rows.sort(key=key_of, reverse=descending)
            return rows

        return self._with(_Stage("sort", None,
                                 shuffle_map_fn=map_fn,
                                 shuffle_reduce_fn=reduce_fn,
                                 shuffle_sample_fn=sample_fn,
                                 shuffle_plan_fn=plan_fn))

    # ------------- execution -------------

    def _iter_output_blocks(self, max_in_flight: int | None = None,
                            yield_refs: bool = False) -> Iterator[Any]:
        """The streaming loop: push blocks through stages with bounded
        in-flight remote tasks (reference: streaming_executor.py:217
        scheduling loop + ExecutionResources backpressure :280).
        Execution stats (wall time, blocks, rows) land in self._last_stats
        for Dataset.stats()."""
        import time as _time

        if max_in_flight is None:
            from ray_tpu.data.context import DataContext

            max_in_flight = DataContext.get_current().max_in_flight_blocks
        t0 = _time.perf_counter()
        n_blocks = n_rows = 0
        try:
            for blk in self._iter_output_blocks_inner(max_in_flight,
                                                      yield_refs=yield_refs):
                n_blocks += 1
                try:
                    n_rows += len(blk)
                except TypeError:
                    pass
                yield blk
        finally:
            # finally: early-terminated consumption (take/limit breaking out
            # of the generator) still records what ran.
            self._last_stats = {
                "wall_s": round(_time.perf_counter() - t0, 4),
                "output_blocks": n_blocks,
                "output_rows": n_rows,
                "stages": [st.name for st in self._stages],
            }

    def explain(self) -> str:
        """Logical plan before and after the optimizer rule catalog
        (reference: the DAG repr Dataset.__repr__ prints + the logical
        optimizer in _internal/logical/optimizers.py). Shows which
        stages were pushed into reads, fused, reordered, or dropped."""
        from ray_tpu.data.optimizer import LogicalPlan, optimize

        def describe(source, stages):
            if source and isinstance(source[0], ReadTask):
                kind = (source[0].meta or {}).get("kind", "read")
                cols = (source[0].meta or {}).get("columns")
                filt = (source[0].meta or {}).get("filters")
                src = f"{kind}[{len(source)} tasks"
                if cols:
                    src += f", columns={list(cols)}"
                if filt:
                    src += f", filters={list(filt)}"
                src += "]"
            else:
                src = f"blocks[{len(source)}]"
            return " -> ".join([src] + [st.name for st in stages])

        before = describe(self._source, self._stages)
        plan = optimize(LogicalPlan(list(self._source),
                                    list(self._stages)))
        after = describe(plan.source, plan.stages)
        return f"logical : {before}\noptimized: {after}"

    def stats(self) -> str:
        """Execution summary of the last run (reference: Dataset.stats() —
        data/_internal/stats.py; per-stage timing there, end-to-end here)."""
        s = getattr(self, "_last_stats", None)
        if s is None:
            return "Dataset not executed yet; call materialize()/take()/... first."
        stages = " -> ".join(s["stages"]) or "(read only)"
        return (f"Stages: {stages}\n"
                f"Output: {s['output_blocks']} blocks, {s['output_rows']} rows\n"
                f"Wall time: {s['wall_s']}s")

    def _iter_output_blocks_inner(self, max_in_flight: int,
                                  yield_refs: bool = False) -> Iterator[Any]:
        from ray_tpu._private import serialization
        from ray_tpu.data.context import DataContext

        task_timeout = DataContext.get_current().block_task_timeout_s

        from ray_tpu.data.optimizer import LogicalPlan, optimize

        plan = optimize(LogicalPlan(list(self._source),
                                    list(self._stages)))
        source, stages = plan.source, plan.stages

        def resolve_sources() -> Iterator:
            """Launch deferred reads as remote tasks; their ObjectRefs feed
            straight into downstream stage tasks (blocks never route
            through the driver)."""
            for src in source:
                if isinstance(src, ReadTask):
                    yield _exec_read.remote(serialization.dumps_func(src.fn))
                else:
                    yield src

        blocks: Iterable = resolve_sources()
        # Split into segments at all-to-all/shuffle barriers and actor-pool
        # stages.
        segment: list[_Stage] = []
        segments: list[tuple[list[_Stage], _Stage | None]] = []
        for st in stages:
            if st.all_to_all or st.shuffle_map_fn is not None:
                segments.append((segment, st))
                segment = []
            elif st.actor_pool:
                # Actor stage runs alone in its own segment.
                if segment:
                    segments.append((segment, None))
                segments.append(([st], None))
                segment = []
            else:
                segment.append(st)
        segments.append((segment, None))

        def run_actor_segment(in_blocks: Iterable, st: _Stage) -> Iterator:
            blob = serialization.dumps_func(st.fn)
            actors = [_StageActor.remote(blob) for _ in range(st.actor_pool)]
            window: list = []
            i = 0
            try:
                for blk in in_blocks:
                    window.append(actors[i % len(actors)].apply.remote(blk))
                    i += 1
                    if len(window) >= max(max_in_flight, len(actors)):
                        yield ray_tpu.get(window.pop(0), timeout=task_timeout)
                while window:
                    yield ray_tpu.get(window.pop(0), timeout=task_timeout)
            finally:
                for a in actors:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass

        def run_segment(in_blocks: Iterable, seg: list[_Stage]) -> Iterator:
            if not seg:
                yield from in_blocks
                return
            if len(seg) == 1 and seg[0].actor_pool:
                yield from run_actor_segment(in_blocks, seg[0])
                return
            fn_blobs = [serialization.dumps_func(s.fn) for s in seg]

            def launch(blk, idx):
                # Operator FUSION: the whole per-block stage chain runs as
                # one task — no intermediate objects, no per-stage RPCs.
                return _apply_fused.remote(fn_blobs, blk, idx)

            # FIFO window: yield in submission order (dataset semantics are
            # ordered, matching the reference's OutputSplitter default).
            # The window is bounded by COUNT and by estimated BYTES
            # (reference: ExecutionResources memory limits,
            # streaming_executor.py:280) — block sizes are learned from
            # completed blocks, so a >RAM dataset streams with bounded
            # in-flight footprint.
            from ray_tpu.data.block import block_nbytes
            from ray_tpu.data.context import DataContext

            byte_budget = DataContext.get_current().max_in_flight_bytes
            avg_size = 0.0
            done = 0
            window: list = []
            for idx, blk in enumerate(in_blocks):
                window.append(launch(blk, idx))
                limit = max_in_flight
                if avg_size > 0 and byte_budget > 0:
                    limit = min(limit,
                                max(2, int(byte_budget / avg_size)))
                while len(window) >= limit:
                    out = ray_tpu.get(window.pop(0), timeout=task_timeout)
                    done += 1
                    avg_size += (block_nbytes(out) - avg_size) / done
                    yield out
            while window:
                out = ray_tpu.get(window.pop(0), timeout=task_timeout)
                done += 1
                avg_size += (block_nbytes(out) - avg_size) / done
                yield out

        def run_shuffle(in_blocks: Iterable, st: _Stage) -> Iterator:
            """Push-based shuffle: map tasks partition (num_returns=n_out
            separate objects), reduce task j fetches partition j from every
            map — no driver materialization."""
            in_refs = [b if isinstance(b, ray_tpu.ObjectRef)
                       else ray_tpu.put(b) for b in in_blocks]
            if not in_refs:
                return
            n_out = len(in_refs)
            aux = None
            if st.shuffle_sample_fn is not None:
                sblob = serialization.dumps_func(st.shuffle_sample_fn)
                sampled = ray_tpu.get(
                    [_shuffle_sample.remote(sblob, r) for r in in_refs],
                    timeout=task_timeout)
                aux = st.shuffle_plan_fn(sampled)
            mblob = serialization.dumps_func(st.shuffle_map_fn)
            rblob = serialization.dumps_func(st.shuffle_reduce_fn)
            map_out = [
                _shuffle_map.options(num_returns=n_out).remote(
                    mblob, ref, n_out, i, aux)
                for i, ref in enumerate(in_refs)]
            if n_out == 1:
                map_out = [[r] for r in map_out]
            for j in range(n_out):
                yield _shuffle_reduce.remote(
                    rblob, j, aux, *[parts[j] for parts in map_out])

        for seg, barrier in segments:
            blocks = run_segment(blocks, seg)
            if barrier is None:
                continue
            if barrier.shuffle_map_fn is not None:
                blocks = run_shuffle(blocks, barrier)
            elif barrier.reorder:
                # Order-only barrier: permute the REFS, never fetch.
                blocks = iter(barrier.all_to_all_fn(list(blocks)))
            else:
                materialized = [b if not isinstance(b, ray_tpu.ObjectRef)
                                else ray_tpu.get(b) for b in blocks]
                blocks = iter(barrier.all_to_all_fn(materialized))
        if yield_refs:
            # Consumer-side landing sinks (iter_jax_batches' device
            # path) feed each ref into their own remote stage — handing
            # the refs through keeps blocks off this process entirely.
            # Segment boundaries (barriers, actor pools) may have
            # materialized already; those pass through as values.
            yield from blocks
            return
        # Windowed fetch: keep up to max_in_flight refs outstanding so
        # stage-less pipelines (bare lazy reads) still run reads in
        # parallel instead of one round-trip per block.
        window: list = []
        for b in blocks:
            if not isinstance(b, ray_tpu.ObjectRef):
                while window:
                    yield ray_tpu.get(window.pop(0), timeout=task_timeout)
                yield b
                continue
            window.append(b)
            if len(window) >= max_in_flight:
                yield ray_tpu.get(window.pop(0), timeout=task_timeout)
        while window:
            yield ray_tpu.get(window.pop(0), timeout=task_timeout)

    def materialize(self) -> "Dataset":
        out = list(self._iter_output_blocks())
        return Dataset(out, [])

    # ------------- consumption -------------

    def iter_rows(self) -> Iterator:
        for block in self._iter_output_blocks():
            yield from block_to_rows(block)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator:
        carry: list = []
        for block in self._iter_output_blocks():
            carry.extend(block_to_rows(block))
            while len(carry) >= batch_size:
                chunk, carry = carry[:batch_size], carry[batch_size:]
                yield rows_to_batch(chunk) if batch_format == "numpy" else chunk
        if carry and not drop_last:
            yield rows_to_batch(carry) if batch_format == "numpy" else carry

    def iter_jax_batches(self, *, batch_size: int, mesh=None, spec=None,
                         drop_last: bool = True,
                         device_transport: bool | None = None) -> Iterator:
        """Batches as (mesh-sharded) jax arrays — the TPU ingest path.

        With device_transport (default: on whenever the runtime is up),
        each output block's host→HBM copy runs on a WORKER via a
        tensor_transport="device" landing task; this consumer resolves
        the pinned arrays over the cheapest device-plane route
        (same-mesh collective, counted host fallback) and batches
        on-device — the consuming process never does the host→device
        copy itself. Off (or with no runtime), batches are formed on
        the host here and device_put directly."""
        import jax

        sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(mesh, spec or PartitionSpec(("dp", "fsdp")))
        if device_transport is None:
            device_transport = _device_runtime_ready()
        if device_transport:
            yield from self._iter_jax_batches_device(batch_size, sharding,
                                                     drop_last)
            return
        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            arrs = {k: jax.device_put(v, sharding) if sharding is not None
                    else jax.device_put(v) for k, v in batch.items()}
            yield arrs

    def _iter_jax_batches_device(self, batch_size: int, sharding,
                                 drop_last: bool) -> Iterator:
        """Pipelined device landings: one landing task per output block
        (window-bounded, like the host fetch path), resolved in order
        and rebatched on-device with jnp concatenation/slicing."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.data.context import DataContext

        max_in_flight = DataContext.get_current().max_in_flight_blocks
        task_timeout = DataContext.get_current().block_task_timeout_s

        def landings():
            window: list = []
            for b in self._iter_output_blocks(yield_refs=True):
                window.append(_land_block_jax.options(
                    tensor_transport="device").remote(b))
                if len(window) >= max_in_flight:
                    yield ray_tpu.get(window.pop(0), timeout=task_timeout)
            while window:
                yield ray_tpu.get(window.pop(0), timeout=task_timeout)

        def place(batch):
            return {k: jax.device_put(v, sharding) if sharding is not None
                    else v for k, v in batch.items()}

        carry: dict | None = None
        for landed in landings():
            if not landed:
                continue
            carry = landed if carry is None else \
                {k: jnp.concatenate([carry[k], landed[k]]) for k in carry}
            n = len(next(iter(carry.values())))
            while n >= batch_size:
                yield place({k: v[:batch_size] for k, v in carry.items()})
                carry = {k: v[batch_size:] for k, v in carry.items()}
                n -= batch_size
        if carry is not None and not drop_last and \
                len(next(iter(carry.values()))):
            yield place(carry)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None, device: str | None = None,
                           drop_last: bool = False) -> Iterator:
        """Batches as torch tensors (parity: Dataset.iter_torch_batches —
        the torch-side ingest path; numeric columns become tensors, other
        columns pass through)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                arr = np.asarray(v)
                if arr.dtype.kind in "biuf":
                    t = torch.from_numpy(np.ascontiguousarray(arr))
                    if dtypes is not None:
                        want = dtypes.get(k) if isinstance(dtypes, dict) \
                            else dtypes
                        if want is not None:
                            t = t.to(want)
                    if device:
                        t = t.to(device)
                    out[k] = t
                else:
                    out[k] = arr
            yield out

    def take(self, n: int = 20) -> list:
        out = []
        for r in self.iter_rows():
            out.append(r)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(block_len(b) for b in self._iter_output_blocks())

    def sum(self, on: str | None = None):
        total = 0
        for r in self.iter_rows():
            total += r[on] if on else r
        return total

    def min(self, on: str | None = None):
        return min(r[on] if on else r for r in self.iter_rows())

    def max(self, on: str | None = None):
        return max(r[on] if on else r for r in self.iter_rows())

    def mean(self, on: str | None = None):
        values = [r[on] if on else r for r in self.iter_rows()]
        return sum(values) / len(values) if values else 0.0

    def num_blocks(self) -> int:
        return len(self._source)

    def split(self, n: int, *, equal: bool = True) -> list["Dataset"]:
        """Split into n datasets (per-train-worker shards)."""
        blocks = list(self._iter_output_blocks())
        rows = []
        for b in blocks:
            rows.extend(block_to_rows(b))
        per = len(rows) // n if equal else math.ceil(len(rows) / n)
        out = []
        for i in range(n):
            chunk = rows[i * per:(i + 1) * per] if (equal or i < n - 1) \
                else rows[i * per:]
            out.append(Dataset([chunk], []))
        return out

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = list(self._iter_output_blocks())
        for o in others:
            blocks.extend(o._iter_output_blocks())
        return Dataset(blocks, [])

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.take_all())

    def schema(self):
        first = self.take(1)
        if not first:
            return None
        row = first[0]
        if isinstance(row, dict):
            return {k: type(v).__name__ for k, v in row.items()}
        return type(row).__name__

    def streaming_split(self, n: int, *, equal: bool = True
                        ) -> list["DataIterator"]:
        """n iterators over disjoint shards, for per-train-worker ingest
        (parity: Dataset.streaming_split feeding Train workers)."""
        shards = self.split(n, equal=equal)
        return [DataIterator(s) for s in shards]

    def iterator(self) -> "DataIterator":
        return DataIterator(self)

    # ------------- writes -------------

    def write_json(self, path: str) -> None:
        import json as _json
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._iter_output_blocks()):
            with open(os.path.join(path, f"part-{i:05d}.jsonl"), "w") as f:
                for r in block_to_rows(block):
                    f.write(_json.dumps(_jsonable(r)) + "\n")

    def write_csv(self, path: str) -> None:
        import csv as _csv
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._iter_output_blocks()):
            rows = [r if isinstance(r, dict) else {"value": r}
                    for r in block_to_rows(block)]
            if not rows:
                continue
            with open(os.path.join(path, f"part-{i:05d}.csv"), "w",
                      newline="") as f:
                w = _csv.DictWriter(f, fieldnames=list(rows[0].keys()))
                w.writeheader()
                w.writerows(_jsonable(r) for r in rows)

    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow.parquet as pq

        from ray_tpu.data.block import block_to_arrow

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._iter_output_blocks()):
            if not block_len(block):
                continue
            table = block_to_arrow(block)  # no-op for arrow blocks
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_numpy(self, path: str, *, column: str = "data") -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._iter_output_blocks()):
            batch = block_to_batch(block)
            if column in batch:
                np.save(os.path.join(path, f"part-{i:05d}.npy"), batch[column])

    def write_mongo(self, uri: str, database: str, collection: str, *,
                    client_factory=None) -> int:
        """Insert every row into a MongoDB collection (reference:
        Dataset.write_mongo; connector in data/mongo.py)."""
        from ray_tpu.data.mongo import write_mongo

        return write_mongo(self, uri, database, collection,
                           client_factory=client_factory)

    def write_tfrecords(self, path: str) -> None:
        """One TFRecord file of tf.train.Example protos per output block
        (reference: Dataset.write_tfrecords; codec in data/tfrecord.py)."""
        import os

        from ray_tpu.data import tfrecord as _tfr

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self._iter_output_blocks()):
            rows = block_to_rows(block)
            _tfr.write_records(
                os.path.join(path, f"part-{i:05d}.tfrecords"),
                (_tfr.encode_example(_jsonable(r)) for r in rows))

    def __repr__(self):
        names = [s.name for s in self._stages]
        return f"Dataset(blocks={len(self._source)}, stages={names})"


def _jsonable(r):
    if isinstance(r, dict):
        return {k: _jsonable(v) for k, v in r.items()}
    if isinstance(r, np.generic):
        return r.item()
    if isinstance(r, np.ndarray):
        return r.tolist()
    return r


_builtin_zip = zip


class DataIterator:
    """Per-consumer iterator over a dataset shard (parity: reference
    ray.data.DataIterator from streaming_split / Dataset.iterator)."""

    def __init__(self, ds: Dataset):
        self._ds = ds

    def iter_rows(self):
        return self._ds.iter_rows()

    def iter_batches(self, **kwargs):
        return self._ds.iter_batches(**kwargs)

    def iter_jax_batches(self, **kwargs):
        return self._ds.iter_jax_batches(**kwargs)


class GroupedData:
    """ds.groupby(key).count()/sum()/mean()/min()/max()/aggregate()
    (parity: reference data/grouped_data.py). Executes as a hash shuffle:
    rows bucket by key hash into num_blocks partitions, then per-partition
    aggregation runs block-parallel."""

    def __init__(self, ds: Dataset, key):
        self._ds = ds
        self._key = key

    def _key_fn(self):
        key = self._key
        if callable(key):
            return key
        return lambda r: r[key]

    def _groups(self) -> dict:
        kf = self._key_fn()
        groups: dict = {}
        for r in self._ds.iter_rows():
            groups.setdefault(kf(r), []).append(r)
        return groups

    def count(self) -> "Dataset":
        keyname = self._key if isinstance(self._key, str) else "key"
        rows = [{keyname: k, "count()": len(v)}
                for k, v in sorted(self._groups().items())]
        return Dataset([rows], [])

    def _agg(self, on: str, fn: Callable, label: str) -> "Dataset":
        keyname = self._key if isinstance(self._key, str) else "key"
        rows = []
        for k, grp in sorted(self._groups().items()):
            vals = [r[on] for r in grp]
            rows.append({keyname: k, f"{label}({on})": fn(vals)})
        return Dataset([rows], [])

    def sum(self, on: str) -> "Dataset":
        return self._agg(on, sum, "sum")

    def min(self, on: str) -> "Dataset":
        return self._agg(on, min, "min")

    def max(self, on: str) -> "Dataset":
        return self._agg(on, max, "max")

    def mean(self, on: str) -> "Dataset":
        return self._agg(on, lambda v: sum(v) / len(v), "mean")

    def aggregate(self, on: str, fn: Callable, label: str = "agg") -> "Dataset":
        return self._agg(on, fn, label)

    def map_groups(self, fn: Callable) -> "Dataset":
        rows = []
        for _k, grp in sorted(self._groups().items()):
            out = fn(grp)
            rows.extend(out if isinstance(out, list) else [out])
        return Dataset([rows], [])
