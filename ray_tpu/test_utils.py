"""Chaos-testing utilities.

Parity: reference _private/test_utils.py:1401 NodeKillerActor (random
raylet SIGKILL during workloads) + release/nightly_tests/setup_chaos.py.
The in-process `NodeKiller` thread kills worker raylets from a
`cluster_utils.Cluster` at an interval, optionally re-adding replacements,
while the test drives a workload — the assertion is that retries, actor
restarts, and lineage reconstruction keep the workload correct
(SURVEY.md §5 failure-detection inventory).
"""

from __future__ import annotations

import asyncio
import random
import threading
import time


class NodeKiller:
    """Kills random non-head nodes of a Cluster every `interval_s`.

    with NodeKiller(cluster, interval_s=0.5, respawn=True,
                    node_args={"num_cpus": 2}):
        ... run workload ...
    """

    def __init__(self, cluster, *, interval_s: float = 1.0,
                 respawn: bool = True, node_args: dict | None = None,
                 max_kills: int | None = None, seed: int | None = None):
        self.cluster = cluster
        self.interval_s = interval_s
        self.respawn = respawn
        self.node_args = node_args or {}
        self.max_kills = max_kills
        self.rng = random.Random(seed)
        self.kills = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _victims(self):
        return [n for n in self.cluster._node.nodes
                if n is not self.cluster.head_node
                and n.proc.poll() is None]

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            victims = self._victims()
            if not victims:
                continue
            node = self.rng.choice(victims)
            try:
                self.cluster.remove_node(node)
                self.kills += 1
            except Exception:
                continue
            if self.respawn:
                try:
                    self.cluster.add_node(**self.node_args)
                except Exception:
                    pass

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-killer")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class NodePreempter:
    """Graceful-preemption chaos: drain-with-deadline, then kill — the
    spot/maintenance reclamation model (NodeKiller's SIGKILL cousin;
    reference: autoscaler.proto DrainNode preceding reclaim). The
    assertion model inverts NodeKiller's: a PREEMPTED node's death must
    be a non-event — zero lineage reconstructions, zero client-visible
    actor errors (drain evacuated everything first).

    Deterministic use (what most tests want)::

        preempter = NodePreempter(cluster, deadline_s=10)
        result = preempter.preempt(node)   # drain → DRAINED → kill
        assert result["state"] == "DRAINED"

    Interval mode mirrors NodeKiller::

        with NodePreempter(cluster, interval_s=2.0, respawn=True,
                           node_args={"num_cpus": 2}) as p:
            ... workload ...
        assert p.preemptions >= 1

    Stochastic STEP schedule (elastic-train chaos, reproducible): a
    preemption every ~`step_interval` training steps with ±`step_jitter`
    relative jitter, gaps drawn from the seeded rng — the same seed
    replays the same schedule. `step_source` is a zero-arg callable
    returning the workload's current global step::

        p = NodePreempter(cluster, deadline_s=5, step_interval=20,
                          step_source=lambda: trainer_step(), seed=7,
                          respawn=True, node_args={"num_cpus": 2})
        with p:
            ... train ...
        assert p.preemptions >= 2
    """

    def __init__(self, cluster, *, deadline_s: float = 10.0,
                 reason: str = "preemption", interval_s: float | None = None,
                 respawn: bool = False, node_args: dict | None = None,
                 max_preemptions: int | None = None, seed: int | None = None,
                 step_interval: int | None = None,
                 step_jitter: float = 0.3, step_source=None):
        self.cluster = cluster
        self.deadline_s = deadline_s
        self.reason = reason
        self.interval_s = interval_s
        self.respawn = respawn
        self.node_args = node_args or {}
        self.max_preemptions = max_preemptions
        self.rng = random.Random(seed)
        self.preemptions = 0
        self.results: list[dict] = []
        self.step_interval = step_interval
        self.step_jitter = step_jitter
        self.step_source = step_source
        self.step_schedule: list[int] = []  # steps preemptions fired at
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def preempt(self, node, *, kill: bool = True) -> dict:
        """Drain one node with the configured deadline, wait for
        DRAINED, then (by default) kill it. Returns the drain response
        (its "state" is DRAINED on a clean evacuation)."""
        result = self.cluster.drain_node(
            node, deadline_s=self.deadline_s, reason=self.reason,
            wait=True)
        self.results.append(result)
        if kill:
            self.cluster.remove_node(node)
        self.preemptions += 1
        return result

    def _victims(self):
        return [n for n in self.cluster._node.nodes
                if n is not self.cluster.head_node
                and n.proc.poll() is None]

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            if self.max_preemptions is not None \
                    and self.preemptions >= self.max_preemptions:
                return
            victims = self._victims()
            if not victims:
                continue
            node = self.rng.choice(victims)
            try:
                self.preempt(node)
            except Exception:
                continue
            if self.respawn:
                try:
                    self.cluster.add_node(**self.node_args)
                except Exception:
                    pass

    def _next_gap(self) -> int:
        """Steps until the next preemption: step_interval ± jitter,
        drawn from the seeded rng (deterministic schedule per seed)."""
        lo = max(1, int(round(self.step_interval * (1 - self.step_jitter))))
        hi = max(lo, int(round(self.step_interval * (1 + self.step_jitter))))
        return self.rng.randint(lo, hi)

    def _step_loop(self):
        target = self._next_gap()
        while not self._stop.wait(0.05):
            if self.max_preemptions is not None \
                    and self.preemptions >= self.max_preemptions:
                return
            try:
                step = int(self.step_source())
            except Exception:
                continue
            if step < target:
                continue
            victims = self._victims()
            if not victims:
                continue
            node = self.rng.choice(victims)
            try:
                self.preempt(node)
                self.step_schedule.append(step)
            except Exception:
                continue
            if self.respawn:
                try:
                    self.cluster.add_node(**self.node_args)
                except Exception:
                    pass
            target = step + self._next_gap()

    def start(self):
        if self.step_interval is not None:
            assert self.step_source is not None, \
                "step schedule needs step_source (current-step callable)"
            self._thread = threading.Thread(target=self._step_loop,
                                            daemon=True,
                                            name="node-preempter")
        else:
            assert self.interval_s is not None, \
                "interval mode needs interval_s; use preempt() directly"
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="node-preempter")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class _ChaosLink:
    """One proxied TCP link (internal to NetChaos).

    Fault knobs are plain attributes read by the pump coroutines on
    every frame; writes from the test thread are atomic under the GIL,
    so no locking is needed for test purposes. Each direction gets its
    own seeded rng so the two pumps never interleave draws — the same
    seed replays the same drop/dup schedule per stream.
    """

    def __init__(self, name: str, upstream: tuple[str, int], seed):
        self.name = name
        self.upstream = upstream
        self.rng = {d: random.Random(f"{seed}:{name}:{d}")
                    for d in ("c2s", "s2c")}
        self.drop = 0.0       # P(silently drop a frame)
        self.delay_s = 0.0    # added one-way latency per frame
        self.dup = 0.0        # P(forward a frame twice)
        self.blackhole: set[str] = set()  # directions silently eaten
        self.refusing = False  # new connections rejected (link "down")
        self.server = None
        self.host: str | None = None
        self.port: int | None = None
        self.writers: list = []  # live writers, for cut()
        self.stats = {"conns": 0, "conns_refused": 0,
                      "frames_forwarded": 0, "frames_dropped": 0,
                      "frames_duplicated": 0, "frames_blackholed": 0}


def scale_chaos_schedule(seed: int, n_flaps: int) -> dict:
    """The scale-chaos gate's hostility, as a pure function of the
    seed: flap (offset, duration) pairs and the two spot-kill offsets,
    in wave-relative seconds. `bench.py --scale-chaos` records this in
    its artifact so a certification run can be replayed from its JSON
    alone."""
    rng = random.Random(seed)
    flaps = [(round(rng.uniform(0.05, 0.6), 3),
              round(rng.uniform(0.2, 0.45), 3))
             for _ in range(n_flaps)]
    kills = [round(rng.uniform(0.1, 0.5), 3) for _ in range(2)]
    return {"seed": seed, "flaps": flaps, "kills": kills}


class NetChaos:
    """Seeded, deterministic network fault injector: a frame-aware TCP
    proxy interposed on the repo's length-prefixed msgpack RPC links.

    Faults operate on WHOLE frames (4-byte BE length + body, the
    _private/rpc.py wire format), so injected drops/dups/partitions
    exercise the resilient-session layer (reconnect, replay, server-side
    dedup, SUSPECT-before-DEAD) rather than producing protocol garbage.
    Composable with NodeKiller/NodePreempter — proxy the control links,
    then kill/preempt through the same cluster.

    Usage::

        chaos = NetChaos(seed=7).start()
        ph, pp = chaos.link("n1-gcs", gcs_host, gcs_port)
        node = cluster.add_node(num_cpus=2, gcs_addr=(ph, pp))
        chaos.set_faults("n1-gcs", drop=0.05, delay_s=0.01, dup=0.02)
        chaos.partition("n1-gcs", "c2s")  # one-way: raylet->GCS eaten
        chaos.heal("n1-gcs")
        chaos.flap("n1-gcs", down_s=0.5)  # cut + refuse, then heal
        chaos.cut("n1-gcs")               # close live sockets once
        print(chaos.stats("n1-gcs"))
        chaos.stop()

    Fault vocabulary:
      - drop/delay_s/dup — per-frame probabilistic faults (seeded rng).
      - partition(direction=None) — silently eat frames one way ("c2s"
        client->server, "s2c" server->client) or both; sockets stay OPEN.
        This is the asymmetric partition SUSPECT exists for.
      - cut() — close every live proxied socket (clean connection loss).
      - flap(down_s) — refuse + cut for down_s, then heal: the
        transient outage that must be a non-event (no false DEAD).
    """

    def __init__(self, seed: int | None = None):
        self.seed = seed if seed is not None else random.randrange(2**31)
        self._links: dict[str, _ChaosLink] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None

    def start(self):
        started = threading.Event()

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            self._loop.call_soon(started.set)
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="net-chaos")
        self._thread.start()
        if not started.wait(10.0):
            raise RuntimeError("NetChaos loop failed to start")
        return self

    def link(self, name: str, upstream_host: str,
             upstream_port: int) -> tuple[str, int]:
        """Open a proxy listener for `upstream`; returns (host, port)
        to hand to the client side (e.g. Cluster.add_node(gcs_addr=))."""
        assert self._loop is not None, "call start() first"
        assert name not in self._links, f"link {name!r} already exists"
        link = _ChaosLink(name, (upstream_host, upstream_port), self.seed)
        asyncio.run_coroutine_threadsafe(
            self._open(link), self._loop).result(10.0)
        self._links[name] = link
        return link.host, link.port

    async def _open(self, link: _ChaosLink):
        async def on_conn(reader, writer):
            if link.refusing:
                link.stats["conns_refused"] += 1
                writer.close()
                return
            try:
                up_reader, up_writer = await asyncio.open_connection(
                    *link.upstream)
            except OSError:
                link.stats["conns_refused"] += 1
                writer.close()
                return
            from ray_tpu._private.common import supervised_task

            link.stats["conns"] += 1
            link.writers += [writer, up_writer]
            pumps = [
                supervised_task(
                    self._pump(link, reader, up_writer, "c2s"),
                    name=f"chaos-{link.name}-c2s"),
                supervised_task(
                    self._pump(link, up_reader, writer, "s2c"),
                    name=f"chaos-{link.name}-s2c"),
            ]
            # One side dying kills the whole proxied conn, like a real
            # TCP reset would.
            await asyncio.wait(pumps, return_when=asyncio.FIRST_COMPLETED)
            for p in pumps:
                p.cancel()
            for w in (writer, up_writer):
                try:
                    w.close()
                except Exception:
                    pass
                if w in link.writers:
                    link.writers.remove(w)

        link.server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        link.host, link.port = link.server.sockets[0].getsockname()[:2]

    async def _pump(self, link: _ChaosLink, reader, writer, direction: str):
        rng = link.rng[direction]
        try:
            while True:
                header = await reader.readexactly(4)
                body = await reader.readexactly(int.from_bytes(header, "big"))
                frame = header + body
                if direction in link.blackhole:
                    link.stats["frames_blackholed"] += 1
                    continue
                if link.drop and rng.random() < link.drop:
                    link.stats["frames_dropped"] += 1
                    continue
                if link.delay_s:
                    await asyncio.sleep(link.delay_s)
                writer.write(frame)
                link.stats["frames_forwarded"] += 1
                if link.dup and rng.random() < link.dup:
                    # Replays the identical REQUEST frame — exercises
                    # the server-side (session_id, seq) reply cache.
                    writer.write(frame)
                    link.stats["frames_duplicated"] += 1
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass

    def set_faults(self, name: str, *, drop: float = 0.0,
                   delay_s: float = 0.0, dup: float = 0.0):
        link = self._links[name]
        link.drop, link.delay_s, link.dup = drop, delay_s, dup

    def partition(self, name: str, direction: str | None = None):
        """Silently eat frames — one way ("c2s"/"s2c") or both (None).
        Sockets stay open: neither side sees a connection error, only
        silence, so failure detection must come from heartbeat expiry."""
        link = self._links[name]
        link.blackhole |= {direction} if direction else {"c2s", "s2c"}

    def heal(self, name: str):
        """Lift partitions and connection refusal (probabilistic faults
        set via set_faults persist until reset explicitly)."""
        link = self._links[name]
        link.blackhole.clear()
        link.refusing = False

    def cut(self, name: str):
        """Close every live proxied socket on this link — both ends see
        a clean connection loss (the reconnect/replay trigger)."""
        link = self._links[name]

        def _close():
            for w in list(link.writers):
                try:
                    w.close()
                except Exception:
                    pass
            link.writers.clear()

        self._loop.call_soon_threadsafe(_close)

    def flap(self, name: str, down_s: float = 0.5):
        """Take the link fully down (refuse new conns + cut live ones)
        for `down_s`, then bring it back. Blocks the calling thread."""
        link = self._links[name]
        link.refusing = True
        self.cut(name)
        time.sleep(down_s)
        self.heal(name)

    def stats(self, name: str) -> dict:
        return dict(self._links[name].stats)

    def stop(self):
        if self._loop is None:
            return

        async def _shutdown():
            for link in self._links.values():
                if link.server is not None:
                    link.server.close()
                for w in list(link.writers):
                    try:
                        w.close()
                    except Exception:
                        pass
                link.writers.clear()

        try:
            asyncio.run_coroutine_threadsafe(
                _shutdown(), self._loop).result(10.0)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._loop = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def wait_for_condition(predicate, timeout: float = 30.0,
                       retry_interval_ms: float = 100.0) -> None:
    """Parity: reference _private/test_utils.py wait_for_condition."""
    deadline = time.monotonic() + timeout
    last_exc = None
    while time.monotonic() < deadline:
        try:
            if predicate():
                return
        except Exception as e:  # noqa: BLE001
            last_exc = e
        time.sleep(retry_interval_ms / 1000.0)
    msg = f"condition not met within {timeout}s"
    if last_exc is not None:
        msg += f" (last error: {last_exc})"
    raise TimeoutError(msg)
