"""Chaos-testing utilities.

Parity: reference _private/test_utils.py:1401 NodeKillerActor (random
raylet SIGKILL during workloads) + release/nightly_tests/setup_chaos.py.
The in-process `NodeKiller` thread kills worker raylets from a
`cluster_utils.Cluster` at an interval, optionally re-adding replacements,
while the test drives a workload — the assertion is that retries, actor
restarts, and lineage reconstruction keep the workload correct
(SURVEY.md §5 failure-detection inventory).
"""

from __future__ import annotations

import random
import threading
import time


class NodeKiller:
    """Kills random non-head nodes of a Cluster every `interval_s`.

    with NodeKiller(cluster, interval_s=0.5, respawn=True,
                    node_args={"num_cpus": 2}):
        ... run workload ...
    """

    def __init__(self, cluster, *, interval_s: float = 1.0,
                 respawn: bool = True, node_args: dict | None = None,
                 max_kills: int | None = None, seed: int | None = None):
        self.cluster = cluster
        self.interval_s = interval_s
        self.respawn = respawn
        self.node_args = node_args or {}
        self.max_kills = max_kills
        self.rng = random.Random(seed)
        self.kills = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _victims(self):
        return [n for n in self.cluster._node.nodes
                if n is not self.cluster.head_node
                and n.proc.poll() is None]

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            victims = self._victims()
            if not victims:
                continue
            node = self.rng.choice(victims)
            try:
                self.cluster.remove_node(node)
                self.kills += 1
            except Exception:
                continue
            if self.respawn:
                try:
                    self.cluster.add_node(**self.node_args)
                except Exception:
                    pass

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-killer")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class NodePreempter:
    """Graceful-preemption chaos: drain-with-deadline, then kill — the
    spot/maintenance reclamation model (NodeKiller's SIGKILL cousin;
    reference: autoscaler.proto DrainNode preceding reclaim). The
    assertion model inverts NodeKiller's: a PREEMPTED node's death must
    be a non-event — zero lineage reconstructions, zero client-visible
    actor errors (drain evacuated everything first).

    Deterministic use (what most tests want)::

        preempter = NodePreempter(cluster, deadline_s=10)
        result = preempter.preempt(node)   # drain → DRAINED → kill
        assert result["state"] == "DRAINED"

    Interval mode mirrors NodeKiller::

        with NodePreempter(cluster, interval_s=2.0, respawn=True,
                           node_args={"num_cpus": 2}) as p:
            ... workload ...
        assert p.preemptions >= 1

    Stochastic STEP schedule (elastic-train chaos, reproducible): a
    preemption every ~`step_interval` training steps with ±`step_jitter`
    relative jitter, gaps drawn from the seeded rng — the same seed
    replays the same schedule. `step_source` is a zero-arg callable
    returning the workload's current global step::

        p = NodePreempter(cluster, deadline_s=5, step_interval=20,
                          step_source=lambda: trainer_step(), seed=7,
                          respawn=True, node_args={"num_cpus": 2})
        with p:
            ... train ...
        assert p.preemptions >= 2
    """

    def __init__(self, cluster, *, deadline_s: float = 10.0,
                 reason: str = "preemption", interval_s: float | None = None,
                 respawn: bool = False, node_args: dict | None = None,
                 max_preemptions: int | None = None, seed: int | None = None,
                 step_interval: int | None = None,
                 step_jitter: float = 0.3, step_source=None):
        self.cluster = cluster
        self.deadline_s = deadline_s
        self.reason = reason
        self.interval_s = interval_s
        self.respawn = respawn
        self.node_args = node_args or {}
        self.max_preemptions = max_preemptions
        self.rng = random.Random(seed)
        self.preemptions = 0
        self.results: list[dict] = []
        self.step_interval = step_interval
        self.step_jitter = step_jitter
        self.step_source = step_source
        self.step_schedule: list[int] = []  # steps preemptions fired at
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def preempt(self, node, *, kill: bool = True) -> dict:
        """Drain one node with the configured deadline, wait for
        DRAINED, then (by default) kill it. Returns the drain response
        (its "state" is DRAINED on a clean evacuation)."""
        result = self.cluster.drain_node(
            node, deadline_s=self.deadline_s, reason=self.reason,
            wait=True)
        self.results.append(result)
        if kill:
            self.cluster.remove_node(node)
        self.preemptions += 1
        return result

    def _victims(self):
        return [n for n in self.cluster._node.nodes
                if n is not self.cluster.head_node
                and n.proc.poll() is None]

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            if self.max_preemptions is not None \
                    and self.preemptions >= self.max_preemptions:
                return
            victims = self._victims()
            if not victims:
                continue
            node = self.rng.choice(victims)
            try:
                self.preempt(node)
            except Exception:
                continue
            if self.respawn:
                try:
                    self.cluster.add_node(**self.node_args)
                except Exception:
                    pass

    def _next_gap(self) -> int:
        """Steps until the next preemption: step_interval ± jitter,
        drawn from the seeded rng (deterministic schedule per seed)."""
        lo = max(1, int(round(self.step_interval * (1 - self.step_jitter))))
        hi = max(lo, int(round(self.step_interval * (1 + self.step_jitter))))
        return self.rng.randint(lo, hi)

    def _step_loop(self):
        target = self._next_gap()
        while not self._stop.wait(0.05):
            if self.max_preemptions is not None \
                    and self.preemptions >= self.max_preemptions:
                return
            try:
                step = int(self.step_source())
            except Exception:
                continue
            if step < target:
                continue
            victims = self._victims()
            if not victims:
                continue
            node = self.rng.choice(victims)
            try:
                self.preempt(node)
                self.step_schedule.append(step)
            except Exception:
                continue
            if self.respawn:
                try:
                    self.cluster.add_node(**self.node_args)
                except Exception:
                    pass
            target = step + self._next_gap()

    def start(self):
        if self.step_interval is not None:
            assert self.step_source is not None, \
                "step schedule needs step_source (current-step callable)"
            self._thread = threading.Thread(target=self._step_loop,
                                            daemon=True,
                                            name="node-preempter")
        else:
            assert self.interval_s is not None, \
                "interval mode needs interval_s; use preempt() directly"
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="node-preempter")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def wait_for_condition(predicate, timeout: float = 30.0,
                       retry_interval_ms: float = 100.0) -> None:
    """Parity: reference _private/test_utils.py wait_for_condition."""
    deadline = time.monotonic() + timeout
    last_exc = None
    while time.monotonic() < deadline:
        try:
            if predicate():
                return
        except Exception as e:  # noqa: BLE001
            last_exc = e
        time.sleep(retry_interval_ms / 1000.0)
    msg = f"condition not met within {timeout}s"
    if last_exc is not None:
        msg += f" (last error: {last_exc})"
    raise TimeoutError(msg)
