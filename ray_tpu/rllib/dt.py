"""DT: Decision Transformer — offline RL as sequence modeling.

Parity: reference rllib/algorithms/dt/ (return-conditioned behavior
cloning: a causal transformer over (return-to-go, state, action) token
triples predicts the next action; acting conditions on a target
return). This is the most TPU-native algorithm in the family — training
IS a transformer train step under jit, no simulator in the loop.

A compact JAX transformer is built inline (token embeddings per
modality + learned positions, pre-LN causal blocks); episodes come from
the same JSONL logs BC/MARWIL/CRR read.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.offline import JsonReader


@dataclass
class DTConfig:
    """Fluent config (parity: rllib DTConfig)."""

    env: Any = "CartPole-v1"
    input_path: str | None = None
    context_len: int = 8          # K timesteps => 3K tokens
    embed_dim: int = 64
    n_layers: int = 2
    n_heads: int = 2
    gamma: float = 1.0            # DT uses undiscounted returns-to-go
    lr: float = 1e-3
    train_batch_size: int = 64
    num_sgd_iter_per_train: int = 20
    target_return: float | None = None  # None: best return in the data
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def offline_data(self, input_path: str):
        self.input_path = input_path
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DT option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "DT":
        return DT(self)


class DT:
    def __init__(self, config: DTConfig):
        self.config = config
        probe = make_env(config.env)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.episodes = self._load_episodes()
        self.max_return = max(ep["rtg"][0] for ep in self.episodes)
        self.params = self._init_params()
        self._update = None
        self.iteration = 0

    # ---- data ----

    def _load_episodes(self) -> list:
        cfg = self.config
        if cfg.input_path is None:
            raise ValueError("DT needs offline_data(input_path=...)")
        d = JsonReader(cfg.input_path).read_all()
        obs, acts = d["obs"], d["actions"]
        rews, dones = d["rewards"], d["dones"]
        episodes, start = [], 0
        for t in range(len(obs)):
            if dones[t] or t == len(obs) - 1:
                ep_r = rews[start:t + 1]
                # (Discounted) return-to-go; DT's canonical setting is
                # gamma=1 but the knob is honored.
                rtg = np.zeros(len(ep_r), np.float32)
                acc = 0.0
                for i in range(len(ep_r) - 1, -1, -1):
                    acc = ep_r[i] + cfg.gamma * acc
                    rtg[i] = acc
                episodes.append({"obs": obs[start:t + 1],
                                 "actions": acts[start:t + 1],
                                 "rtg": rtg})
                start = t + 1
        return [e for e in episodes if len(e["obs"]) > 0]

    # ---- model ----

    def _init_params(self) -> dict:
        cfg = self.config
        rng = np.random.default_rng(cfg.seed)
        E, K = cfg.embed_dim, cfg.context_len

        def dense(i, o):
            return {"w": (rng.standard_normal((i, o)) *
                          (1.0 / np.sqrt(i))).astype(np.float32),
                    "b": np.zeros(o, np.float32)}

        p = {
            "emb_rtg": dense(1, E),
            "emb_obs": dense(self.obs_size, E),
            "emb_act": dense(self.num_actions, E),  # one-hot actions
            "pos": (rng.standard_normal((3 * K, E)) * 0.02
                    ).astype(np.float32),
            "head": dense(E, self.num_actions),
        }
        for li in range(cfg.n_layers):
            p[f"blk{li}"] = {
                "ln1_g": np.ones(E, np.float32),
                "ln1_b": np.zeros(E, np.float32),
                "qkv": dense(E, 3 * E),
                "proj": dense(E, E),
                "ln2_g": np.ones(E, np.float32),
                "ln2_b": np.zeros(E, np.float32),
                "mlp1": dense(E, 4 * E),
                "mlp2": dense(4 * E, E),
            }
        return p

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        E, H, K = cfg.embed_dim, cfg.n_heads, cfg.context_len
        T = 3 * K
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def ln(x, g, b):
            mu = x.mean(-1, keepdims=True)
            var = ((x - mu) ** 2).mean(-1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g + b

        def block(p, x):
            B = x.shape[0]
            h = ln(x, p["ln1_g"], p["ln1_b"])
            qkv = h @ p["qkv"]["w"] + p["qkv"]["b"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, T, H, E // H).transpose(0, 2, 1, 3)
            k = k.reshape(B, T, H, E // H).transpose(0, 2, 1, 3)
            v = v.reshape(B, T, H, E // H).transpose(0, 2, 1, 3)
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(E // H)
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask, s, -1e30)
            a = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
            o = o.transpose(0, 2, 1, 3).reshape(B, T, E)
            x = x + o @ p["proj"]["w"] + p["proj"]["b"]
            h = ln(x, p["ln2_g"], p["ln2_b"])
            h = jax.nn.gelu(h @ p["mlp1"]["w"] + p["mlp1"]["b"])
            return x + h @ p["mlp2"]["w"] + p["mlp2"]["b"]

        def forward(params, rtg, obs, act_onehot):
            # Interleave (rtg, obs, act) tokens: position 3t..3t+2.
            B = rtg.shape[0]
            e_r = rtg[..., None] @ params["emb_rtg"]["w"] \
                + params["emb_rtg"]["b"]
            e_o = obs @ params["emb_obs"]["w"] + params["emb_obs"]["b"]
            e_a = act_onehot @ params["emb_act"]["w"] \
                + params["emb_act"]["b"]
            x = jnp.stack([e_r, e_o, e_a], axis=2).reshape(B, T, E)
            x = x + params["pos"][None]
            for li in range(cfg.n_layers):
                x = block(params[f"blk{li}"], x)
            # Predict action t from the OBS token at position 3t+1.
            return x[:, 1::3] @ params["head"]["w"] + params["head"]["b"]

        self._forward = jax.jit(forward)

        def loss_fn(params, rtg, obs, act_onehot, actions, mask):
            logits = forward(params, rtg, obs, act_onehot)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, actions[..., None], axis=-1)[..., 0]
            return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def update(params, opt_state, *batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update)

    def _sample_batch(self, rng):
        import jax.numpy as jnp

        cfg = self.config
        K = cfg.context_len
        B = cfg.train_batch_size
        rtg = np.zeros((B, K), np.float32)
        obs = np.zeros((B, K, self.obs_size), np.float32)
        act = np.zeros((B, K), np.int32)
        mask = np.zeros((B, K), np.float32)
        for i in range(B):
            ep = self.episodes[rng.integers(len(self.episodes))]
            L = len(ep["obs"])
            start = rng.integers(max(1, L - K + 1))
            n = min(K, L - start)
            rtg[i, :n] = ep["rtg"][start:start + n]
            obs[i, :n] = ep["obs"][start:start + n]
            act[i, :n] = ep["actions"][start:start + n]
            mask[i, :n] = 1.0
        onehot = np.eye(self.num_actions, dtype=np.float32)[act]
        # Action token t must not leak action t into its own prediction:
        # the causal mask handles it (action token sits AFTER the obs
        # token the prediction reads from).
        return (jnp.asarray(rtg), jnp.asarray(obs), jnp.asarray(onehot),
                jnp.asarray(act), jnp.asarray(mask))

    def train(self) -> dict:
        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        rng = np.random.default_rng(cfg.seed + self.iteration)
        losses = []
        for _ in range(cfg.num_sgd_iter_per_train):
            batch = self._sample_batch(rng)
            self.params, self._opt_state, loss = self._update(
                self.params, self._opt_state, *batch)
            losses.append(float(loss))
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "loss": float(np.mean(losses)),
            "num_samples_trained": cfg.num_sgd_iter_per_train
            * cfg.train_batch_size,
            "episodes_in_dataset": len(self.episodes),
            "max_dataset_return": float(self.max_return),
            "iter_time_s": round(time.time() - t0, 3),
        }

    def evaluate(self, episodes: int = 4,
                 target_return: float | None = None,
                 max_steps: int = 200) -> dict:
        """Roll out conditioning on the target return (DT's whole point:
        aim for a return, act accordingly)."""
        import jax.numpy as jnp

        if self._update is None:
            self._build_update()
        cfg = self.config
        K = cfg.context_len
        env = make_env(cfg.env)
        target = (target_return if target_return is not None
                  else cfg.target_return
                  if cfg.target_return is not None else self.max_return)
        totals = []
        for ep in range(episodes):
            obs_hist, act_hist, rtg_hist = [], [], []
            o = env.reset(seed=cfg.seed + 100 + ep)
            rtg = float(target)
            total = 0.0
            for _t in range(max_steps):
                obs_hist.append(np.asarray(o, np.float32))
                rtg_hist.append(rtg)
                act_hist.append(0)   # placeholder for the current step
                rtgs = np.zeros((1, K), np.float32)
                obss = np.zeros((1, K, self.obs_size), np.float32)
                acts = np.zeros((1, K), np.int32)
                n = min(K, len(obs_hist))
                rtgs[0, :n] = rtg_hist[-n:]
                obss[0, :n] = obs_hist[-n:]
                acts[0, :n] = act_hist[-n:]
                onehot = np.eye(self.num_actions, dtype=np.float32)[acts]
                logits = self._forward(self.params, jnp.asarray(rtgs),
                                       jnp.asarray(obss),
                                       jnp.asarray(onehot))
                a = int(np.argmax(np.asarray(logits)[0, n - 1]))
                act_hist[-1] = a
                o, r, done, _ = env.step(a)
                total += r
                rtg -= r
                if done:
                    break
            totals.append(total)
        return {"episode_reward_mean": float(np.mean(totals)),
                "target_return": float(target)}

    def compute_single_action(self, obs) -> int:
        import jax.numpy as jnp

        if self._update is None:
            self._build_update()
        cfg = self.config
        K = cfg.context_len
        rtgs = np.zeros((1, K), np.float32)
        rtgs[0, 0] = self.max_return
        obss = np.zeros((1, K, self.obs_size), np.float32)
        obss[0, 0] = obs
        acts = np.zeros((1, K), np.int32)
        onehot = np.eye(self.num_actions, dtype=np.float32)[acts]
        logits = self._forward(self.params, jnp.asarray(rtgs),
                               jnp.asarray(obss), jnp.asarray(onehot))
        return int(np.argmax(np.asarray(logits)[0, 0]))

    def stop(self):
        pass
