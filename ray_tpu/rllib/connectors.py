"""Connector pipelines: composable transforms between env and module.

Parity: reference rllib/connectors/ — env-to-module connectors transform
raw observations before the policy forward, module-to-env connectors
transform policy outputs into env actions. Pipelines are pure functions
over numpy data with a small amount of carried state (e.g. frame stacks,
running normalizer moments), so they run identically inside CPU rollout
actors and at serving time — the reference's portability argument for
connectors over ad-hoc preprocessing.
"""

from __future__ import annotations

from typing import Any

import numpy as np


class Connector:
    """One transform. env-to-module: __call__(obs) -> obs.
    module-to-env: __call__(action) -> action. Stateful connectors carry
    their state on self and expose reset()."""

    def __call__(self, x: Any) -> Any:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def state(self) -> dict:
        """Serializable state (checkpointing parity: connectors travel
        with policies)."""
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipeline(Connector):
    def __init__(self, connectors: list[Connector] | None = None):
        self.connectors = list(connectors or [])

    def append(self, c: Connector) -> "ConnectorPipeline":
        self.connectors.append(c)
        return self

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def reset(self):
        for c in self.connectors:
            c.reset()

    def state(self):
        return {i: c.state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if i in state or str(i) in state:
                c.set_state(state.get(i, state.get(str(i), {})))


# ---------------- env-to-module connectors ----------------


class FlattenObs(Connector):
    def __call__(self, obs):
        return np.asarray(obs, np.float32).reshape(-1)


class NormalizeObs(Connector):
    """Running mean/std normalization (Welford). State travels with the
    policy so evaluation uses the training moments."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0):
        self.eps = eps
        self.clip = clip
        self.count = 0.0
        self.mean: np.ndarray | None = None
        self.m2: np.ndarray | None = None
        self.frozen = False

    def __call__(self, obs):
        x = np.asarray(obs, np.float64)
        if self.mean is None:
            self.mean = np.zeros_like(x)
            self.m2 = np.ones_like(x)
        if not self.frozen:
            self.count += 1.0
            delta = x - self.mean
            self.mean = self.mean + delta / self.count
            self.m2 = self.m2 + delta * (x - self.mean)
        var = self.m2 / max(self.count, 2.0)
        out = (x - self.mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def state(self):
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.tolist(),
                "m2": None if self.m2 is None else self.m2.tolist()}

    def set_state(self, state):
        self.count = state.get("count", 0.0)
        self.mean = None if state.get("mean") is None \
            else np.asarray(state["mean"])
        self.m2 = None if state.get("m2") is None else np.asarray(state["m2"])


class FrameStack(Connector):
    """Stack the last k observations along the last axis (Atari-style
    temporal context without a recurrent module)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames: list = []

    def __call__(self, obs):
        obs = np.asarray(obs, np.float32)
        if not self._frames:
            self._frames = [obs] * self.k
        else:
            self._frames = self._frames[1:] + [obs]
        return np.concatenate(self._frames, axis=-1)

    def reset(self):
        self._frames = []


# ---------------- module-to-env connectors ----------------


class ClipActions(Connector):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, action):
        return np.clip(action, self.low, self.high)


class RescaleActions(Connector):
    """[-1, 1] policy outputs to the env's action bounds."""

    def __init__(self, low, high):
        low, high = np.asarray(low, np.float32), np.asarray(high, np.float32)
        self.mid = (low + high) / 2.0
        self.scale = (high - low) / 2.0

    def __call__(self, action):
        return self.mid + self.scale * np.asarray(action, np.float32)
