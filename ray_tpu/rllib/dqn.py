"""DQN: off-policy Q-learning with replay buffer and target network.

Parity: reference rllib/algorithms/dqn/ (double-DQN update, epsilon-greedy
exploration schedule, target-network sync every N steps) with the
rollout/learner split of SURVEY.md §3.6: CPU sampling actors feed a
replay buffer (reference: rllib/utils/replay_buffers/replay_buffer.py);
the learner is one jitted jax update on the attached accelerator.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


def init_q_params(obs_size: int, num_actions: int, hidden: int = 64,
                  seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    return {"h1": dense(obs_size, hidden), "h2": dense(hidden, hidden),
            "q": dense(hidden, num_actions)}


def numpy_q_values(params: dict, obs: np.ndarray) -> np.ndarray:
    h = np.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
    h = np.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    return h @ params["q"]["w"] + params["q"]["b"]


class ReplayBuffer:
    """Uniform-sampling ring buffer (reference:
    rllib/utils/replay_buffers/replay_buffer.py storage + sample)."""

    def __init__(self, capacity: int, obs_size: int, seed: int = 0,
                 action_shape: tuple = (), action_dtype=np.int32):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_size), np.float32)
        self.next_obs = np.zeros((capacity, obs_size), np.float32)
        self.actions = np.zeros((capacity, *action_shape), action_dtype)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.pos = 0
        self.size = 0
        self.rng = np.random.default_rng(seed)

    def add_batch(self, batch: dict) -> None:
        n = len(batch["obs"])
        idx = (self.pos + np.arange(n)) % self.capacity
        self.obs[idx] = batch["obs"]
        self.next_obs[idx] = batch["next_obs"]
        self.actions[idx] = batch["actions"]
        self.rewards[idx] = batch["rewards"]
        self.dones[idx] = batch["dones"]
        self.pos = int((self.pos + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))

    def sample(self, batch_size: int) -> dict:
        idx = self.rng.integers(0, self.size, batch_size)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx], "rewards": self.rewards[idx],
                "dones": self.dones[idx]}


@ray_tpu.remote
class DQNRolloutWorker:
    """CPU epsilon-greedy sampler (parity: rollout_worker.py)."""

    def __init__(self, env_spec, worker_index: int):
        self.env = make_env(env_spec)
        self.index = worker_index
        self.rng = np.random.default_rng(2000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)
        self.ep_ret = 0.0

    def sample(self, params: dict, num_steps: int, epsilon: float) -> dict:
        obs_b, act_b, rew_b, next_b, done_b = [], [], [], [], []
        episode_returns = []
        for _ in range(num_steps):
            if self.rng.random() < epsilon:
                action = int(self.rng.integers(self.env.num_actions))
            else:
                q = numpy_q_values(params, self.obs[None, :])[0]
                action = int(np.argmax(q))
            next_obs, reward, done, _ = self.env.step(action)
            obs_b.append(self.obs)
            act_b.append(action)
            rew_b.append(reward)
            next_b.append(next_obs)
            done_b.append(float(done))
            self.ep_ret += reward
            if done:
                episode_returns.append(self.ep_ret)
                self.ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {"obs": np.asarray(obs_b, np.float32),
                "actions": np.asarray(act_b, np.int32),
                "rewards": np.asarray(rew_b, np.float32),
                "next_obs": np.asarray(next_b, np.float32),
                "dones": np.asarray(done_b, np.float32),
                "episode_returns": episode_returns}


@dataclass
class DQNConfig:
    """Parity: rllib DQNConfig fluent-config object."""

    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 256
    buffer_capacity: int = 50_000
    learning_starts: int = 1_000
    train_batch_size: int = 128
    num_sgd_iter: int = 32
    gamma: float = 0.99
    lr: float = 1e-3
    hidden_size: int = 64
    target_network_update_freq: int = 4  # iterations between target syncs
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_iters: int = 20
    double_q: bool = True
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DQN option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class DQN:
    """Algorithm driver (parity: Algorithm.step / DQN training_step)."""

    def __init__(self, config: DQNConfig):
        self.config = config
        probe = make_env(config.env)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.params = init_q_params(self.obs_size, self.num_actions,
                                    config.hidden_size, config.seed)
        self.target_params = {k: {kk: vv.copy() for kk, vv in v.items()}
                              for k, v in self.params.items()}
        self.buffer = ReplayBuffer(config.buffer_capacity, self.obs_size,
                                   config.seed)
        self.workers = [DQNRolloutWorker.remote(config.env, i)
                        for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def q_fn(params, obs):
            h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            return h @ params["q"]["w"] + params["q"]["b"]

        def loss_fn(params, target_params, batch):
            q = q_fn(params, batch["obs"])
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
            q_next_target = q_fn(target_params, batch["next_obs"])
            if cfg.double_q:
                # Double DQN: online net picks the argmax, target net rates it.
                a_star = jnp.argmax(q_fn(params, batch["next_obs"]), axis=1)
                q_next = jnp.take_along_axis(
                    q_next_target, a_star[:, None], axis=1)[:, 0]
            else:
                q_next = q_next_target.max(axis=1)
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) \
                * q_next
            td = q_sel - jax.lax.stop_gradient(target)
            # Huber loss (reference: dqn uses huber by default)
            loss = jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td,
                             jnp.abs(td) - 0.5).mean()
            return loss

        def update(params, target_params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, target_params,
                                                      batch)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = jax.jit(update)

    def train(self) -> dict:
        import jax

        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        eps = self._epsilon()
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        batches = ray_tpu.get(
            [w.sample.remote(host_params, cfg.rollout_fragment_length, eps)
             for w in self.workers], timeout=600)
        episode_returns = []
        for b in batches:
            episode_returns.extend(b.pop("episode_returns"))
            self.buffer.add_batch(b)
            self.total_steps += len(b["obs"])
        sample_time = time.time() - t0

        t1 = time.time()
        loss = 0.0
        updates_done = 0
        if self.buffer.size >= max(cfg.train_batch_size, cfg.learning_starts):
            for _ in range(cfg.num_sgd_iter):
                mb = self.buffer.sample(cfg.train_batch_size)
                self.params, self._opt_state, loss = self._update(
                    self.params, self.target_params, self._opt_state, mb)
                updates_done += 1
        self.iteration += 1
        if self.iteration % cfg.target_network_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(
                lambda x: x, self.params)
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "episodes_this_iter": len(episode_returns),
            "timesteps_total": self.total_steps,
            "buffer_size": self.buffer.size,
            "epsilon": round(eps, 4),
            "num_updates": updates_done,
            "loss": float(loss),
            "sample_time_s": round(sample_time, 3),
            "learn_time_s": round(time.time() - t1, 3),
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def get_policy_params(self) -> dict:
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def compute_single_action(self, obs) -> int:
        return int(np.argmax(
            numpy_q_values(self.get_policy_params(), obs[None, :])[0]))
