"""Replay buffers (parity: reference rllib/utils/replay_buffers/ —
replay_buffer.py, prioritized_replay_buffer.py).

`ReplayBuffer` (uniform) lives in dqn.py for historical reasons and is
re-exported here; `PrioritizedReplayBuffer` adds proportional
prioritization (Schaul et al. 2016) with importance-sampling weights.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.dqn import ReplayBuffer

__all__ = ["ReplayBuffer", "PrioritizedReplayBuffer"]


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay: P(i) ∝ p_i^alpha, with IS weights
    w_i = (N·P(i))^-beta / max w. New samples enter at max priority so
    every transition is trained on at least once."""

    def __init__(self, capacity: int, obs_size: int, seed: int = 0,
                 alpha: float = 0.6, beta: float = 0.4,
                 action_shape: tuple = (), action_dtype=np.int32):
        super().__init__(capacity, obs_size, seed, action_shape, action_dtype)
        self.alpha = alpha
        self.beta = beta
        self.priorities = np.zeros(capacity, np.float32)
        self.max_priority = 1.0

    def add_batch(self, batch: dict) -> None:
        n = len(batch["obs"])
        idx = (self.pos + np.arange(n)) % self.capacity
        super().add_batch(batch)
        self.priorities[idx] = self.max_priority

    def sample(self, batch_size: int) -> dict:
        p = self.priorities[: self.size] ** self.alpha
        probs = p / p.sum()
        idx = self.rng.choice(self.size, batch_size, p=probs)
        weights = (self.size * probs[idx]) ** (-self.beta)
        weights = (weights / weights.max()).astype(np.float32)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx], "rewards": self.rewards[idx],
                "dones": self.dones[idx], "weights": weights,
                "indices": idx.astype(np.int64)}

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prios = np.abs(np.asarray(td_errors, np.float32)) + 1e-6
        self.priorities[np.asarray(indices, np.int64)] = prios
        self.max_priority = max(self.max_priority, float(prios.max()))
