"""Model catalog (parity: reference rllib/models/catalog.py — maps spec →
network). Every algorithm here uses the same dual-representation policy:
a numpy forward for CPU rollout actors (no jax import in samplers) and a
jax forward for the jitted learner. The catalog centralizes construction
so custom models plug into any algorithm."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

MODEL_REGISTRY: dict[str, "ModelSpec"] = {}


@dataclass
class ModelSpec:
    name: str
    init_params: Callable  # (obs_size, num_actions, hidden, seed) -> params
    numpy_forward: Callable  # (params, obs) -> (logits, value)
    jax_forward: Callable    # same contract under jit/grad
    default_hidden: int = 64  # the spec owns its width default


def register_model(spec: ModelSpec) -> None:
    MODEL_REGISTRY[spec.name] = spec


def get_model(name: str) -> ModelSpec:
    if name not in MODEL_REGISTRY:
        raise ValueError(
            f"unknown model {name!r}; registered: {sorted(MODEL_REGISTRY)}")
    return MODEL_REGISTRY[name]


# -- built-in: 2-layer tanh MLP actor-critic (the default everywhere) ------

def _mlp_init(obs_size: int, num_actions: int, hidden: int = 64,
              seed: int = 0) -> dict:
    from ray_tpu.rllib.ppo import init_policy_params

    return init_policy_params(obs_size, num_actions, hidden, seed)


def _mlp_numpy(params: dict, obs: np.ndarray):
    from ray_tpu.rllib.ppo import numpy_forward

    return numpy_forward(params, obs)


def _mlp_jax(params: dict, obs):
    import jax.numpy as jnp

    h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
    h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


register_model(ModelSpec("mlp", _mlp_init, _mlp_numpy, _mlp_jax))


# -- deeper residual MLP for harder control tasks --------------------------

def _resmlp_init(obs_size: int, num_actions: int, hidden: int = 128,
                 seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    return {"inp": dense(obs_size, hidden),
            "res1": dense(hidden, hidden), "res2": dense(hidden, hidden),
            "pi": dense(hidden, num_actions), "vf": dense(hidden, 1)}


def _resmlp_numpy(params, obs):
    h = np.tanh(obs @ params["inp"]["w"] + params["inp"]["b"])
    h = h + np.tanh(h @ params["res1"]["w"] + params["res1"]["b"])
    h = h + np.tanh(h @ params["res2"]["w"] + params["res2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def _resmlp_jax(params, obs):
    import jax.numpy as jnp

    h = jnp.tanh(obs @ params["inp"]["w"] + params["inp"]["b"])
    h = h + jnp.tanh(h @ params["res1"]["w"] + params["res1"]["b"])
    h = h + jnp.tanh(h @ params["res2"]["w"] + params["res2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


register_model(ModelSpec("resmlp", _resmlp_init, _resmlp_numpy, _resmlp_jax))


# -- Atari-style conv net (parity: reference rllib Nature-CNN default for
# image observations, rllib/models/catalog.py conv defaults). Used for
# pixel envs: obs (H, W, C) uint8/float; learner runs it under jit on
# the accelerator, rollout workers run the SAME jax forward jitted on
# their CPU backend (a numpy conv would dominate sampling time). --------

def _cnn_init(obs_shape, num_actions: int, hidden: int = 256,
              seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    h, w, c = obs_shape

    def conv(kh, kw, cin, cout):
        fan_in = kh * kw * cin
        return {"w": (rng.standard_normal((kh, kw, cin, cout))
                      / np.sqrt(fan_in)).astype(np.float32),
                "b": np.zeros(cout, np.float32)}

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)
                      ).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    # Strided convs shrink H,W by 2 each: 42 -> 21 -> 11 -> 6.
    def out_hw(x):
        for _ in range(3):
            x = (x + 1) // 2
        return x

    flat = out_hw(h) * out_hw(w) * 64
    return {
        "c1": conv(5, 5, c, 16),
        "c2": conv(3, 3, 16, 32),
        "c3": conv(3, 3, 32, 64),
        "fc": dense(flat, hidden),
        "pi": dense(hidden, num_actions),
        "vf": dense(hidden, 1),
    }


def _cnn_jax(params: dict, obs):
    """obs: (B, H, W, C); [0,255] inputs are normalized. Symmetric k//2
    padding with stride 2 (matches the numpy fallback exactly)."""
    import jax
    import jax.numpy as jnp

    x = obs.astype(jnp.float32)
    x = x / jnp.maximum(1.0, jnp.where(jnp.max(x) > 1.5, 255.0, 1.0))
    for key in ("c1", "c2", "c3"):
        w = params[key]["w"]
        kh, kw = w.shape[0], w.shape[1]
        dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                            ("NHWC", "HWIO", "NHWC"))
        x = jax.lax.conv_general_dilated(
            x, w, window_strides=(2, 2),
            padding=[(kh // 2, kh // 2), (kw // 2, kw // 2)],
            dimension_numbers=dn)
        x = jax.nn.relu(x + params[key]["b"])
    x = x.reshape(x.shape[0], -1)
    h = jnp.tanh(x @ params["fc"]["w"] + params["fc"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def _cnn_numpy(params: dict, obs: np.ndarray):
    """Fallback numpy path (tests / environments without jax): naive but
    correct strided conv."""
    x = obs.astype(np.float32)
    if x.max() > 1.5:
        x = x / 255.0

    def conv2d(x, w, b):
        bsz, hh, ww, cin = x.shape
        kh, kw, _, cout = w.shape
        ph, pw = kh // 2, kw // 2
        xp = np.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        oh, ow = (hh + 1) // 2, (ww + 1) // 2
        out = np.zeros((bsz, oh, ow, cout), np.float32)
        for i in range(oh):
            for j in range(ow):
                patch = xp[:, i * 2:i * 2 + kh, j * 2:j * 2 + kw, :]
                out[:, i, j, :] = np.tensordot(patch, w, axes=([1, 2, 3],
                                                               [0, 1, 2]))
        return np.maximum(out + b, 0.0)

    for key in ("c1", "c2", "c3"):
        x = conv2d(x, params[key]["w"], params[key]["b"])
    x = x.reshape(x.shape[0], -1)
    h = np.tanh(x @ params["fc"]["w"] + params["fc"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


register_model(ModelSpec("atari_cnn", _cnn_init, _cnn_numpy, _cnn_jax,
                         default_hidden=256))
