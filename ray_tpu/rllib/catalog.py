"""Model catalog (parity: reference rllib/models/catalog.py — maps spec →
network). Every algorithm here uses the same dual-representation policy:
a numpy forward for CPU rollout actors (no jax import in samplers) and a
jax forward for the jitted learner. The catalog centralizes construction
so custom models plug into any algorithm."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

MODEL_REGISTRY: dict[str, "ModelSpec"] = {}


@dataclass
class ModelSpec:
    name: str
    init_params: Callable  # (obs_size, num_actions, hidden, seed) -> params
    numpy_forward: Callable  # (params, obs) -> (logits, value)
    jax_forward: Callable    # same contract under jit/grad


def register_model(spec: ModelSpec) -> None:
    MODEL_REGISTRY[spec.name] = spec


def get_model(name: str) -> ModelSpec:
    if name not in MODEL_REGISTRY:
        raise ValueError(
            f"unknown model {name!r}; registered: {sorted(MODEL_REGISTRY)}")
    return MODEL_REGISTRY[name]


# -- built-in: 2-layer tanh MLP actor-critic (the default everywhere) ------

def _mlp_init(obs_size: int, num_actions: int, hidden: int = 64,
              seed: int = 0) -> dict:
    from ray_tpu.rllib.ppo import init_policy_params

    return init_policy_params(obs_size, num_actions, hidden, seed)


def _mlp_numpy(params: dict, obs: np.ndarray):
    from ray_tpu.rllib.ppo import numpy_forward

    return numpy_forward(params, obs)


def _mlp_jax(params: dict, obs):
    import jax.numpy as jnp

    h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
    h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


register_model(ModelSpec("mlp", _mlp_init, _mlp_numpy, _mlp_jax))


# -- deeper residual MLP for harder control tasks --------------------------

def _resmlp_init(obs_size: int, num_actions: int, hidden: int = 128,
                 seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    return {"inp": dense(obs_size, hidden),
            "res1": dense(hidden, hidden), "res2": dense(hidden, hidden),
            "pi": dense(hidden, num_actions), "vf": dense(hidden, 1)}


def _resmlp_numpy(params, obs):
    h = np.tanh(obs @ params["inp"]["w"] + params["inp"]["b"])
    h = h + np.tanh(h @ params["res1"]["w"] + params["res1"]["b"])
    h = h + np.tanh(h @ params["res2"]["w"] + params["res2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def _resmlp_jax(params, obs):
    import jax.numpy as jnp

    h = jnp.tanh(obs @ params["inp"]["w"] + params["inp"]["b"])
    h = h + jnp.tanh(h @ params["res1"]["w"] + params["res1"]["b"])
    h = h + jnp.tanh(h @ params["res2"]["w"] + params["res2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


register_model(ModelSpec("resmlp", _resmlp_init, _resmlp_numpy, _resmlp_jax))
