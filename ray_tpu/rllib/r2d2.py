"""R2D2: recurrent replay distributed DQN.

Parity: reference rllib/algorithms/r2d2/ (recurrent Q-network trained
on stored SEQUENCES with burn-in: the first `burn_in` steps of each
replayed sequence only warm the hidden state — no gradient — so the
recurrent state the network trains from is close to the state it acted
from; double-Q targets; target network). JAX-native: the GRU unroll is
a lax.scan inside one jitted update, so the whole
burn-in + train-segment pipeline compiles to a single program.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


def init_r2d2_params(obs_size: int, num_actions: int, hidden: int = 32,
                     seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def mat(i, o):
        return (rng.standard_normal((i, o)) / np.sqrt(i)).astype(np.float32)

    return {
        # GRU: update gate z, reset gate r, candidate n (torch layout).
        "wx": mat(obs_size, 3 * hidden), "wh": mat(hidden, 3 * hidden),
        "b": np.zeros(3 * hidden, np.float32),
        "q_w": mat(hidden, num_actions),
        "q_b": np.zeros(num_actions, np.float32),
    }


def _gru_step_np(params, h, x):
    g = x @ params["wx"] + h @ params["wh"] + params["b"]
    H = h.shape[-1]
    z = 1.0 / (1.0 + np.exp(-g[..., :H]))
    r = 1.0 / (1.0 + np.exp(-g[..., H:2 * H]))
    n = np.tanh(x @ params["wx"][:, 2 * H:]
                + r * (h @ params["wh"][:, 2 * H:])
                + params["b"][2 * H:])
    return (1.0 - z) * n + z * h


def numpy_r2d2_q(params: dict, h: np.ndarray, obs: np.ndarray):
    """One recurrent step on CPU: returns (q_values, next_hidden)."""
    h2 = _gru_step_np(params, h, obs)
    return h2 @ params["q_w"] + params["q_b"], h2


class SequenceReplay:
    """Ring buffer of fixed-length sequences with their initial hidden
    state (reference: r2d2's sequence storage — replay_sequence_length
    = burn_in + train length, zero/stored initial states)."""

    def __init__(self, capacity: int, seq_len: int, obs_size: int,
                 hidden: int, seed: int = 0):
        self.capacity, self.seq_len = capacity, seq_len
        self.obs = np.zeros((capacity, seq_len, obs_size), np.float32)
        self.next_obs = np.zeros((capacity, seq_len, obs_size), np.float32)
        self.actions = np.zeros((capacity, seq_len), np.int32)
        self.rewards = np.zeros((capacity, seq_len), np.float32)
        self.dones = np.zeros((capacity, seq_len), np.float32)
        self.resets = np.zeros((capacity, seq_len), np.float32)
        self.h0 = np.zeros((capacity, hidden), np.float32)
        self.pos = 0
        self.size = 0
        self.rng = np.random.default_rng(seed)

    def add_sequences(self, seqs: list[dict]) -> None:
        for s in seqs:
            i = self.pos
            self.obs[i] = s["obs"]
            self.next_obs[i] = s["next_obs"]
            self.actions[i] = s["actions"]
            self.rewards[i] = s["rewards"]
            self.dones[i] = s["dones"]
            self.resets[i] = s.get("resets", s["dones"])
            self.h0[i] = s["h0"]
            self.pos = (self.pos + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int) -> dict:
        idx = self.rng.integers(0, self.size, batch_size)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx], "rewards": self.rewards[idx],
                "dones": self.dones[idx], "resets": self.resets[idx],
                "h0": self.h0[idx]}


@ray_tpu.remote
class R2D2RolloutWorker:
    """CPU sampler carrying the recurrent state across fragments; emits
    fixed-length sequences stamped with the hidden state they started
    from (parity: rollout_worker.py + R2D2's state-in-replay)."""

    def __init__(self, env_spec, worker_index: int, hidden: int,
                 seq_len: int):
        self.env = make_env(env_spec)
        self.hidden = hidden
        self.seq_len = seq_len
        self.rng = np.random.default_rng(4000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)
        self.h = np.zeros(hidden, np.float32)
        self.ep_ret = 0.0

    def sample(self, params: dict, num_seqs: int, epsilon: float) -> dict:
        seqs = []
        episode_returns = []
        for _ in range(num_seqs):
            seq = {k: [] for k in ("obs", "actions", "rewards",
                                   "next_obs", "dones", "resets")}
            h0 = self.h.copy()
            for _ in range(self.seq_len):
                q, self.h = numpy_r2d2_q(params, self.h[None, :],
                                         self.obs[None, :])
                self.h = self.h[0]
                if self.rng.random() < epsilon:
                    action = int(self.rng.integers(self.env.num_actions))
                else:
                    action = int(np.argmax(q[0]))
                next_obs, reward, done, info = self.env.step(action)
                seq["obs"].append(self.obs)
                seq["actions"].append(action)
                seq["rewards"].append(reward)
                seq["next_obs"].append(next_obs)
                # dones = bootstrap mask (time-limit cuts bootstrap
                # through, env.py convention); resets = where the
                # episode ended and the actor zeroed its hidden state —
                # the training unroll must do the same.
                seq["dones"].append(float(bool(done)
                                    and not info.get("truncated",
                                                     False)))
                seq["resets"].append(float(done))
                self.ep_ret += reward
                if done:
                    episode_returns.append(self.ep_ret)
                    self.ep_ret = 0.0
                    self.obs = self.env.reset()
                    self.h = np.zeros(self.hidden, np.float32)
                else:
                    self.obs = next_obs
            seqs.append({"obs": np.asarray(seq["obs"], np.float32),
                         "actions": np.asarray(seq["actions"], np.int32),
                         "rewards": np.asarray(seq["rewards"], np.float32),
                         "next_obs": np.asarray(seq["next_obs"],
                                                np.float32),
                         "dones": np.asarray(seq["dones"], np.float32),
                         "resets": np.asarray(seq["resets"], np.float32),
                         "h0": h0})
        return {"sequences": seqs, "episode_returns": episode_returns,
                "steps": num_seqs * self.seq_len}


@dataclass
class R2D2Config:
    """Parity: rllib R2D2Config (replay_sequence_length = burn_in +
    train segment, zero_init_states=False — states come from the actor)."""

    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    sequences_per_rollout: int = 8
    burn_in: int = 4
    train_length: int = 12
    buffer_capacity: int = 4_000
    train_batch_size: int = 32
    num_sgd_iter: int = 16
    gamma: float = 0.99
    lr: float = 1e-3
    hidden_size: int = 32
    target_network_update_freq: int = 4
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_iters: int = 15
    seed: int = 0

    @property
    def seq_len(self) -> int:
        return self.burn_in + self.train_length

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown R2D2 option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "R2D2":
        return R2D2(self)


class R2D2:
    """Algorithm driver (parity: Algorithm.step / R2D2 training_step)."""

    def __init__(self, config: R2D2Config):
        self.config = config
        probe = make_env(config.env)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.params = init_r2d2_params(self.obs_size, self.num_actions,
                                       config.hidden_size, config.seed)
        self.target_params = {k: v.copy() for k, v in self.params.items()}
        self.buffer = SequenceReplay(config.buffer_capacity,
                                     config.seq_len, self.obs_size,
                                     config.hidden_size, config.seed)
        self.workers = [
            R2D2RolloutWorker.remote(config.env, i, config.hidden_size,
                                     config.seq_len)
            for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        H = cfg.hidden_size
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def gru_step(params, h, x):
            g = x @ params["wx"] + h @ params["wh"] + params["b"]
            z = jax.nn.sigmoid(g[..., :H])
            r = jax.nn.sigmoid(g[..., H:2 * H])
            n = jnp.tanh(x @ params["wx"][:, 2 * H:]
                         + r * (h @ params["wh"][:, 2 * H:])
                         + params["b"][2 * H:])
            return (1.0 - z) * n + z * h

        def unroll_q(params, h0, obs_seq, resets):
            """obs_seq [B, T, obs] -> q [B, T, A] via lax.scan over T.
            `resets` [B, T] zeroes the carried state AFTER a step where
            the episode ended — matching the actor, which starts the
            next episode from h = 0 (sequences may span resets)."""
            def step(h, xs):
                x_t, r_t = xs
                h2 = gru_step(params, h, x_t)
                h_next = h2 * (1.0 - r_t)[:, None]
                return h_next, h2 @ params["q_w"] + params["q_b"]

            hT, qs = jax.lax.scan(
                step, h0, (jnp.swapaxes(obs_seq, 0, 1),
                           jnp.swapaxes(resets, 0, 1)))
            return jnp.swapaxes(qs, 0, 1), hT

        def loss_fn(params, target_params, batch):
            B = batch["obs"].shape[0]
            # Burn-in: warm the hidden state on the replayed prefix with
            # NO gradient (R2D2's stored-state + burn-in strategy).
            burn_obs = batch["obs"][:, :cfg.burn_in]
            burn_resets = batch["resets"][:, :cfg.burn_in]
            _, h_warm = unroll_q(jax.lax.stop_gradient(params),
                                 batch["h0"], burn_obs, burn_resets)
            h_warm = jax.lax.stop_gradient(h_warm)
            train = slice(cfg.burn_in, cfg.seq_len)
            train_resets = batch["resets"][:, train]
            q_seq, _ = unroll_q(params, h_warm, batch["obs"][:, train],
                                train_resets)
            # Targets: unroll the TARGET net one step shifted (its own
            # burn-in includes the first train step), double-Q action
            # selection from the online unroll over next_obs.
            q_next_online, _ = unroll_q(params, h_warm,
                                        batch["next_obs"][:, train],
                                        train_resets)
            q_next_target, _ = unroll_q(target_params, h_warm,
                                        batch["next_obs"][:, train],
                                        train_resets)
            a_star = jnp.argmax(q_next_online, axis=-1)
            q_boot = jnp.take_along_axis(
                q_next_target, a_star[..., None], axis=-1)[..., 0]
            r = batch["rewards"][:, train]
            d = batch["dones"][:, train]
            target = r + cfg.gamma * (1.0 - d) * \
                jax.lax.stop_gradient(q_boot)
            q_taken = jnp.take_along_axis(
                q_seq, batch["actions"][:, train, None].astype(jnp.int32),
                axis=-1)[..., 0]
            return jnp.mean((q_taken - target) ** 2)

        @jax.jit
        def update(params, target_params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, target_params, batch)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = update

    def train(self) -> dict:
        cfg = self.config
        if self._update is None:
            self._build_update()
        eps = self._epsilon()
        rollout_params = {k: np.asarray(v) for k, v in self.params.items()}
        outs = ray_tpu.get([
            w.sample.remote(rollout_params, cfg.sequences_per_rollout, eps)
            for w in self.workers])
        returns = []
        for out in outs:
            self.buffer.add_sequences(out["sequences"])
            returns += out["episode_returns"]
            self.total_steps += out["steps"]
        losses = []
        if self.buffer.size >= cfg.train_batch_size:
            for _ in range(cfg.num_sgd_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                self.params, self._opt_state, loss = self._update(
                    self.params, self.target_params, self._opt_state,
                    batch)
                losses.append(float(loss))
        self.iteration += 1
        if self.iteration % cfg.target_network_update_freq == 0:
            self.target_params = {k: np.asarray(v).copy()
                                  for k, v in self.params.items()}
        return {"training_iteration": self.iteration,
                "episode_reward_mean":
                    float(np.mean(returns)) if returns else float("nan"),
                "num_env_steps_sampled": self.total_steps,
                "loss": float(np.mean(losses)) if losses else None}
