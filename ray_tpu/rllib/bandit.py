"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Parity: reference rllib/algorithms/bandit/ (BanditLinUCB / BanditLinTS
over the per-arm linear model in bandit_torch_model.py). Exact linear
algebra — A = I + sum x x^T per arm, ridge solve per step — so the
whole algorithm is numpy on the driver; there is nothing to place on an
accelerator or distribute. The env contract is one-step episodic:
reset() -> context, step(arm) -> (next context, reward, True, {}).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from ray_tpu.rllib.env import ENV_REGISTRY, Env, make_env


class LinearDiscreteBandit(Env):
    """Synthetic contextual bandit: reward = theta_arm . context + noise
    (parity: the reference's LinearDiscreteEnv test env)."""

    observation_size = 8
    num_actions = 4

    def __init__(self, seed: int = 0, noise: float = 0.1):
        self._rng = np.random.default_rng(seed)
        self._theta = self._rng.standard_normal(
            (self.num_actions, self.observation_size))
        self._noise = noise
        self._ctx = None

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._ctx = self._rng.standard_normal(self.observation_size)
        return self._ctx.astype(np.float32)

    def step(self, action: int):
        rew = float(self._theta[action] @ self._ctx
                    + self._noise * self._rng.standard_normal())
        best = float(np.max(self._theta @ self._ctx))
        nxt = self.reset()
        return nxt, rew, True, {"regret": best - rew}


ENV_REGISTRY.setdefault("LinearBandit-v0", LinearDiscreteBandit)


@dataclass
class BanditConfig:
    """Fluent config (parity: rllib BanditConfig). exploration:
    "ucb" (LinUCB, alpha-scaled bonus) or "ts" (Thompson sampling)."""

    env: Any = "LinearBandit-v0"
    exploration: str = "ucb"
    alpha: float = 1.0            # UCB bonus scale / TS posterior scale
    steps_per_iter: int = 256
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, **kw):
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown Bandit option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "Bandit":
        return Bandit(self)


class Bandit:
    """Per-arm ridge regression; arm choice by UCB bonus or posterior
    sample. Runs in-process (a bandit step is a dot product — remote
    workers would be pure overhead)."""

    def __init__(self, config: BanditConfig):
        self.config = config
        self.env = make_env(config.env)
        d = self.env.observation_size
        k = self.env.num_actions
        self._A = np.stack([np.eye(d) for _ in range(k)])   # (k, d, d)
        self._b = np.zeros((k, d))
        self.rng = np.random.default_rng(config.seed)
        self.iteration = 0
        self.total_steps = 0
        self._obs = self.env.reset(seed=config.seed)

    def _choose(self, x: np.ndarray) -> int:
        k = self._A.shape[0]
        scores = np.empty(k)
        for a in range(k):
            A_inv = np.linalg.inv(self._A[a])
            theta = A_inv @ self._b[a]
            if self.config.exploration == "ts":
                theta = self.rng.multivariate_normal(
                    theta, self.config.alpha ** 2 * A_inv)
                scores[a] = theta @ x
            else:
                bonus = self.config.alpha * np.sqrt(x @ A_inv @ x)
                scores[a] = theta @ x + bonus
        return int(np.argmax(scores))

    def train(self) -> dict:
        t0 = time.time()
        rewards, regrets = [], []
        for _ in range(self.config.steps_per_iter):
            x = np.asarray(self._obs, np.float64)
            a = self._choose(x)
            self._obs, rew, _done, info = self.env.step(a)
            self._A[a] += np.outer(x, x)
            self._b[a] += rew * x
            rewards.append(rew)
            if "regret" in info:
                regrets.append(info["regret"])
        self.iteration += 1
        self.total_steps += len(rewards)
        out = {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(rewards)),
            "timesteps_this_iter": len(rewards),
            "timesteps_total": self.total_steps,
            "iter_time_s": round(time.time() - t0, 3),
        }
        if regrets:
            out["mean_regret"] = float(np.mean(regrets))
        return out

    def compute_single_action(self, obs) -> int:
        return self._choose(np.asarray(obs, np.float64))

    def stop(self):
        pass
