"""SAC: soft actor-critic for continuous control.

Parity: reference rllib/algorithms/sac/ (torch learner + replay) rebuilt
on the rollout/learner split — numpy Gaussian-policy rollout actors feed a
replay buffer; the learner runs the twin-Q soft-Bellman update with
automatic entropy-temperature tuning as ONE jitted jax step on the
attached accelerator.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.dqn import ReplayBuffer
from ray_tpu.rllib.env import make_env

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def init_sac_params(obs_size: int, act_size: int, hidden: int = 64,
                    seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    def q_net():
        return {"h1": dense(obs_size + act_size, hidden),
                "h2": dense(hidden, hidden), "out": dense(hidden, 1)}

    return {
        "pi": {"h1": dense(obs_size, hidden), "h2": dense(hidden, hidden),
               "mu": dense(hidden, act_size), "log_std": dense(hidden, act_size)},
        "q1": q_net(),
        "q2": q_net(),
    }


def numpy_policy(params: dict, obs: np.ndarray):
    """Gaussian policy forward (rollout side): returns (mu, log_std)."""
    pi = params["pi"]
    h = np.tanh(obs @ pi["h1"]["w"] + pi["h1"]["b"])
    h = np.tanh(h @ pi["h2"]["w"] + pi["h2"]["b"])
    mu = h @ pi["mu"]["w"] + pi["mu"]["b"]
    log_std = np.clip(h @ pi["log_std"]["w"] + pi["log_std"]["b"],
                      LOG_STD_MIN, LOG_STD_MAX)
    return mu, log_std


@ray_tpu.remote
class SACRolloutWorker:
    """CPU sampling actor with a squashed-Gaussian exploration policy."""

    def __init__(self, env_spec, worker_index: int):
        self.env = make_env(env_spec)
        self.index = worker_index
        self.rng = np.random.default_rng(2000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)
        self.scale = (self.env.action_high - self.env.action_low) / 2.0
        self.mid = (self.env.action_high + self.env.action_low) / 2.0

    def sample(self, params: dict, num_steps: int, random_policy: bool = False
               ) -> dict:
        obs_b, act_b, rew_b, next_b, done_b = [], [], [], [], []
        episode_returns, ep_ret = [], 0.0
        for _ in range(num_steps):
            if random_policy:
                a = self.rng.uniform(-1.0, 1.0, self.env.action_size)
            else:
                mu, log_std = numpy_policy(params, self.obs[None, :])
                a = np.tanh(mu[0] + np.exp(log_std[0])
                            * self.rng.standard_normal(mu.shape[1]))
            env_action = self.mid + self.scale * a
            next_obs, reward, done, info = self.env.step(env_action)
            obs_b.append(self.obs)
            act_b.append(a.astype(np.float32))
            rew_b.append(reward)
            next_b.append(next_obs)
            # True terminals block bootstrapping; time-limit truncations
            # (info["truncated"], e.g. every Pendulum episode) bootstrap
            # through the cut.
            done_b.append(bool(done) and not info.get("truncated", False))
            ep_ret += reward
            if done:
                episode_returns.append(ep_ret)
                ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {
            "obs": np.asarray(obs_b, np.float32),
            "actions": np.asarray(act_b, np.float32),
            "rewards": np.asarray(rew_b, np.float32),
            "next_obs": np.asarray(next_b, np.float32),
            "dones": np.asarray(done_b, np.float32),
            "episode_returns": episode_returns,
        }


@dataclass
class SACConfig:
    """Parity: rllib SACConfig fluent-config object."""

    env: Any = "Pendulum-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 200
    train_batch_size: int = 256
    num_updates_per_iter: int = 64
    replay_buffer_capacity: int = 100_000
    learning_starts: int = 500
    gamma: float = 0.99
    tau: float = 0.005               # polyak averaging for target nets
    lr: float = 3e-4
    initial_alpha: float = 0.1
    autotune_alpha: bool = True
    hidden_size: int = 64
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown SAC option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "SAC":
        return SAC(self)


class SAC:
    """Algorithm driver (parity: Algorithm.step / SAC training_step)."""

    def __init__(self, config: SACConfig):
        self.config = config
        probe = make_env(config.env)
        if getattr(probe, "action_size", 0) < 1:
            raise ValueError("SAC needs a continuous-action env "
                             "(action_size >= 1)")
        self.obs_size = probe.observation_size
        self.act_size = probe.action_size
        self.params = init_sac_params(self.obs_size, self.act_size,
                                      config.hidden_size, config.seed)
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.log_alpha = float(np.log(config.initial_alpha))
        self.buffer = ReplayBuffer(config.replay_buffer_capacity,
                                   self.obs_size, seed=config.seed,
                                   action_shape=(self.act_size,),
                                   action_dtype=np.float32)
        self.workers = [SACRolloutWorker.remote(config.env, i)
                        for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        target_entropy = -float(self.act_size)
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)
        alpha_opt = optax.adam(cfg.lr)
        self._alpha_opt = alpha_opt
        self._alpha_state = alpha_opt.init(jnp.asarray(self.log_alpha))

        def mlp(net, x):
            h = jnp.tanh(x @ net["h1"]["w"] + net["h1"]["b"])
            h = jnp.tanh(h @ net["h2"]["w"] + net["h2"]["b"])
            return h

        def q_val(net, obs, act):
            h = mlp(net, jnp.concatenate([obs, act], -1))
            return (h @ net["out"]["w"] + net["out"]["b"])[..., 0]

        def pi_sample(pi, obs, key):
            h = mlp(pi, obs)
            mu = h @ pi["mu"]["w"] + pi["mu"]["b"]
            log_std = jnp.clip(h @ pi["log_std"]["w"] + pi["log_std"]["b"],
                               LOG_STD_MIN, LOG_STD_MAX)
            std = jnp.exp(log_std)
            eps = jax.random.normal(key, mu.shape)
            pre = mu + std * eps
            act = jnp.tanh(pre)
            # log prob with tanh-squash correction
            logp = (-0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
                    ).sum(-1)
            logp = logp - jnp.log(1 - act ** 2 + 1e-6).sum(-1)
            return act, logp

        def update(params, target, log_alpha, opt_state, alpha_state, batch,
                   key):
            alpha = jnp.exp(log_alpha)
            key_t, key_a = jax.random.split(key)

            # -- critic loss: soft Bellman target from the TARGET twin-Q --
            next_act, next_logp = pi_sample(params["pi"], batch["next_obs"],
                                            key_t)
            tq = jnp.minimum(q_val(target["q1"], batch["next_obs"], next_act),
                             q_val(target["q2"], batch["next_obs"], next_act))
            y = batch["rewards"] + cfg.gamma * (1 - batch["dones"]) * (
                tq - alpha * next_logp)
            y = jax.lax.stop_gradient(y)

            def critic_loss(p):
                l1 = ((q_val(p["q1"], batch["obs"], batch["actions"]) - y) ** 2
                      ).mean()
                l2 = ((q_val(p["q2"], batch["obs"], batch["actions"]) - y) ** 2
                      ).mean()
                return l1 + l2

            def actor_loss(p):
                act, logp = pi_sample(p["pi"], batch["obs"], key_a)
                q = jnp.minimum(q_val(jax.lax.stop_gradient(p["q1"]),
                                      batch["obs"], act),
                                q_val(jax.lax.stop_gradient(p["q2"]),
                                      batch["obs"], act))
                return (alpha * logp - q).mean(), logp

            closs, cgrads = jax.value_and_grad(critic_loss)(params)
            (aloss, logp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(params)
            # Critic grads touch q1/q2, actor grads touch pi; merge.
            grads = {"pi": agrads["pi"], "q1": cgrads["q1"], "q2": cgrads["q2"]}
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)

            # -- temperature --
            def alpha_loss(la):
                return -(jnp.exp(la) * jax.lax.stop_gradient(
                    logp + target_entropy)).mean()

            if cfg.autotune_alpha:
                agrad = jax.grad(alpha_loss)(log_alpha)
                aupd, alpha_state = alpha_opt.update(agrad, alpha_state)
                log_alpha = optax.apply_updates(log_alpha, aupd)

            # -- polyak target update --
            target = jax.tree_util.tree_map(
                lambda t, p: (1 - cfg.tau) * t + cfg.tau * p, target,
                {"q1": params["q1"], "q2": params["q2"]})
            metrics = {"critic_loss": closs, "actor_loss": aloss,
                       "alpha": alpha, "entropy": -logp.mean()}
            return params, target, log_alpha, opt_state, alpha_state, metrics

        self._update = jax.jit(update)

    def train(self) -> dict:
        import jax
        import jax.numpy as jnp

        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        host = jax.tree_util.tree_map(np.asarray, self.params)
        random_phase = self.total_steps < cfg.learning_starts
        batches = ray_tpu.get(
            [w.sample.remote(host, cfg.rollout_fragment_length, random_phase)
             for w in self.workers], timeout=600)
        episode_returns = []
        for b in batches:
            episode_returns += b.pop("episode_returns")
            self.buffer.add_batch(b)
            self.total_steps += len(b["obs"])
        sample_time = time.time() - t0

        t1 = time.time()
        metrics = {}
        log_alpha = jnp.asarray(self.log_alpha)
        if self.total_steps >= cfg.learning_starts:
            for i in range(cfg.num_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                key = jax.random.PRNGKey(cfg.seed * 100003 + self.iteration
                                         * 1009 + i)
                (self.params, self.target, log_alpha, self._opt_state,
                 self._alpha_state, metrics) = self._update(
                    self.params, self.target, log_alpha, self._opt_state,
                    self._alpha_state, batch, key)
            self.log_alpha = float(log_alpha)
        learn_time = time.time() - t1
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else float("nan"),
            "episodes_this_iter": len(episode_returns),
            "timesteps_total": self.total_steps,
            "sample_time_s": round(sample_time, 3),
            "learn_time_s": round(learn_time, 3),
            **{k: float(v) for k, v in metrics.items()},
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def get_policy_params(self) -> dict:
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def compute_single_action(self, obs) -> np.ndarray:
        mu, _ = numpy_policy(self.get_policy_params(), obs[None, :])
        env = make_env(self.config.env)
        scale = (env.action_high - env.action_low) / 2.0
        mid = (env.action_high + env.action_low) / 2.0
        return mid + scale * np.tanh(mu[0])
