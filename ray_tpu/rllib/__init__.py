from ray_tpu.rllib.a2c import A2C, A2CConfig
from ray_tpu.rllib.a3c import A3C, A3CConfig
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.bandit import (Bandit, BanditConfig,
                                  LinearDiscreteBandit)
from ray_tpu.rllib.crr import CRR, CRRConfig
from ray_tpu.rllib.dt import DT, DTConfig
from ray_tpu.rllib.es import ARS, ES, ARSConfig, ESConfig
from ray_tpu.rllib.qmix import QMIX, CoopSwitch, QMIXConfig
from ray_tpu.rllib.random_agent import RandomAgent, RandomAgentConfig
from ray_tpu.rllib.simple_q import (ApexDQN, ApexDQNConfig, SimpleQ,
                                    SimpleQConfig)
from ray_tpu.rllib.catalog import (MODEL_REGISTRY, ModelSpec, get_model,
                                   register_model)
from ray_tpu.rllib.connectors import (ClipActions, Connector,
                                      ConnectorPipeline, FlattenObs,
                                      FrameStack, NormalizeObs,
                                      RescaleActions)
from ray_tpu.rllib.cql import CQL, CQLConfig
from ray_tpu.rllib.ddpg import DDPG, TD3, DDPGConfig, TD3Config
from ray_tpu.rllib.dqn import DQN, DQNConfig, ReplayBuffer
from ray_tpu.rllib.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.env import ENV_REGISTRY, CartPole, Env, Pendulum, make_env
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.impala import Impala, ImpalaConfig
from ray_tpu.rllib.offline import (BC, MARWIL, BCConfig, JsonReader,
                                   MARWILConfig, write_offline_json)
from ray_tpu.rllib.alphazero import (AlphaZero, AlphaZeroConfig, MCTS,
                                     TicTacToe)
from ray_tpu.rllib.maddpg import MADDPG, CoopNav, MADDPGConfig
from ray_tpu.rllib.pg import PG, PGConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.r2d2 import R2D2, R2D2Config, SequenceReplay
from ray_tpu.rllib.rainbow import Rainbow, RainbowConfig
from ray_tpu.rllib.replay import PrioritizedReplayBuffer
from ray_tpu.rllib.sac import SAC, SACConfig
from ray_tpu.rllib.slateq import SlateDocEnv, SlateQ, SlateQConfig

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig", "SAC", "SACConfig",
           "Impala", "ImpalaConfig", "APPO", "APPOConfig", "A2C", "A2CConfig",
           "TD3", "TD3Config", "DDPG", "DDPGConfig", "CQL", "CQLConfig",
           "PG", "PGConfig",
           "BC", "BCConfig", "MARWIL", "MARWILConfig", "JsonReader",
           "write_offline_json", "ReplayBuffer", "PrioritizedReplayBuffer",
           "ModelSpec", "MODEL_REGISTRY", "get_model", "register_model",
           "Env", "CartPole", "Pendulum", "ENV_REGISTRY", "make_env",
           "Connector", "ConnectorPipeline", "FlattenObs", "NormalizeObs",
           "FrameStack", "ClipActions", "RescaleActions", "EnvRunner",
           "A3C", "A3CConfig", "ES", "ESConfig", "ARS", "ARSConfig",
           "SimpleQ", "SimpleQConfig", "ApexDQN", "ApexDQNConfig",
           "Bandit", "BanditConfig", "LinearDiscreteBandit",
           "CRR", "CRRConfig", "RandomAgent", "RandomAgentConfig",
           "DT", "DTConfig", "QMIX", "QMIXConfig", "CoopSwitch",
           "Rainbow", "RainbowConfig", "R2D2", "R2D2Config",
           "DreamerV3", "DreamerV3Config",
           "SequenceReplay", "MADDPG", "MADDPGConfig", "CoopNav",
           "AlphaZero", "AlphaZeroConfig", "MCTS", "TicTacToe",
           "SlateQ", "SlateQConfig", "SlateDocEnv"]

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu('rllib')
del _rlu
