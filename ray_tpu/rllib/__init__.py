from ray_tpu.rllib.dqn import DQN, DQNConfig, ReplayBuffer
from ray_tpu.rllib.env import ENV_REGISTRY, CartPole, Env, make_env
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig", "ReplayBuffer", "Env",
           "CartPole", "ENV_REGISTRY", "make_env"]
