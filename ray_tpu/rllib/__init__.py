from ray_tpu.rllib.dqn import DQN, DQNConfig, ReplayBuffer
from ray_tpu.rllib.env import ENV_REGISTRY, CartPole, Env, Pendulum, make_env
from ray_tpu.rllib.impala import Impala, ImpalaConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig", "SAC", "SACConfig",
           "Impala", "ImpalaConfig", "ReplayBuffer", "Env", "CartPole",
           "Pendulum", "ENV_REGISTRY", "make_env"]
