"""PPO: CPU rollout actors + JAX (TPU) learner.

Parity: reference rllib/algorithms/ppo/ + the rollout/learner split of
SURVEY.md §3.6 — WorkerSet.sample on CPU actors, LearnerGroup.update on
accelerators (reference: rllib/core/learner/learner_group.py wraps torch
DDP; here the learner is ONE jitted jax update — data parallelism over
learner devices comes from the mesh, not a gradient bucket library).

Rollout workers evaluate the policy with a pure-numpy forward pass (no
jax import in the sampling processes); the learner runs the PPO
clipped-surrogate update under jit on whatever accelerator is attached.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


# ---------------- policy: MLP actor-critic ----------------


def init_policy_params(obs_size: int, num_actions: int, hidden: int = 64,
                       seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    return {
        "h1": dense(obs_size, hidden),
        "h2": dense(hidden, hidden),
        "pi": dense(hidden, num_actions),
        "vf": dense(hidden, 1),
    }


def numpy_forward(params: dict, obs: np.ndarray):
    """Policy forward pass used inside rollout workers."""
    h = np.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
    h = np.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def _gae(rews, vals, dones, gamma, lam):
    """Generalized advantage estimation over one fragment; vals has the
    bootstrap value appended."""
    n = len(rews)
    adv = np.zeros(n, np.float32)
    last = 0.0
    for t in range(n - 1, -1, -1):
        nonterminal = 0.0 if dones[t] else 1.0
        delta = rews[t] + gamma * vals[t + 1] * nonterminal - vals[t]
        last = delta + gamma * lam * nonterminal * last
        adv[t] = last
    return adv, adv + vals[:-1]


@ray_tpu.remote
class RolloutWorker:
    """CPU sampling actor (parity: rllib/evaluation/rollout_worker.py).

    model="mlp" uses the catalog's numpy forward; image models (CNN) run
    the SAME jax forward jitted on the worker's CPU backend — a python
    conv per env step would dominate sampling."""

    def __init__(self, env_spec, worker_index: int, gamma: float, lam: float,
                 model: str = "mlp"):
        from ray_tpu.rllib.catalog import get_model

        self.env = make_env(env_spec)
        self.index = worker_index
        self.gamma = gamma
        self.lam = lam
        self.rng = np.random.default_rng(1000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)
        self._spec = get_model(model)
        self._fwd = None
        if model != "mlp":
            import jax

            jax.config.update("jax_platforms", "cpu")
            self._fwd = jax.jit(self._spec.jax_forward)

    def _forward(self, params, obs):
        if self._fwd is not None:
            logits, value = self._fwd(params, obs)
            return np.asarray(logits), np.asarray(value)
        return self._spec.numpy_forward(params, obs)

    def sample_multi_agent(self, policy_params: dict, num_steps: int,
                           mapping: dict) -> dict:
        """Multi-agent fragment (parity: reference MultiAgentEnv sampling):
        steps every live agent with its mapped policy; returns one batch
        PER POLICY plus episode stats."""
        env = self.env
        if not isinstance(self.obs, dict):
            self.obs = env.reset(seed=self.index)
        bufs = {a: {k: [] for k in
                    ("obs", "actions", "logp", "rew", "val", "done")}
                for a in env.agent_ids}
        episode_returns = []
        ep_ret = 0.0
        for _ in range(num_steps):
            actions = {}
            for a, ob in self.obs.items():
                params = policy_params[mapping[a]]
                logits, value = self._forward(params, np.asarray(ob)[None])
                logits = logits[0]
                pr = np.exp(logits - logits.max())
                pr /= pr.sum()
                act = int(self.rng.choice(len(pr), p=pr))
                actions[a] = act
                b = bufs[a]
                b["obs"].append(np.asarray(ob, np.float32))
                b["actions"].append(act)
                b["logp"].append(float(np.log(pr[act] + 1e-8)))
                b["val"].append(float(value[0]))
            next_obs, rews, dones, _ = env.step(actions)
            for a in actions:
                bufs[a]["rew"].append(float(rews.get(a, 0.0)))
                bufs[a]["done"].append(bool(dones.get(a, False)))
                ep_ret += float(rews.get(a, 0.0))
            if dones.get("__all__"):
                episode_returns.append(ep_ret)
                ep_ret = 0.0
                self.obs = env.reset()
            else:
                # Agents that just finished deliver their terminal obs with
                # done=True and then leave the episode: keep only live
                # agents, or the next loop would record a phantom
                # transition from a terminal state.
                self.obs = {a: o for a, o in next_obs.items()
                            if not dones.get(a, False)}
        out = {}
        for a, b in bufs.items():
            if not b["obs"]:
                continue
            # Bootstrap with the policy's value of the agent's last obs
            # (0 when the agent is already done).
            if a in self.obs and not (b["done"] and b["done"][-1]):
                _, lv = self._forward(policy_params[mapping[a]],
                                      np.asarray(self.obs[a])[None])
                last_val = float(lv[0])
            else:
                last_val = 0.0
            vals = np.asarray(b["val"] + [last_val], np.float32)
            adv, rets = _gae(np.asarray(b["rew"], np.float32), vals,
                             np.asarray(b["done"], bool), self.gamma,
                             self.lam)
            pid = mapping[a]
            batch = {
                "obs": np.asarray(b["obs"], np.float32),
                "actions": np.asarray(b["actions"], np.int32),
                "logp": np.asarray(b["logp"], np.float32),
                "advantages": adv,
                "returns": rets,
            }
            if pid in out:
                out[pid] = {k: np.concatenate([out[pid][k], batch[k]])
                            for k in batch}
            else:
                out[pid] = batch
        return {"policy_batches": out, "episode_returns": episode_returns}

    def sample(self, params: dict, num_steps: int) -> dict:
        obs_buf, act_buf, logp_buf, rew_buf, val_buf, done_buf = \
            [], [], [], [], [], []
        episode_returns = []
        ep_ret = 0.0
        for _ in range(num_steps):
            logits, value = self._forward(params, np.asarray(self.obs)[None])
            logits = logits[0]
            p = np.exp(logits - logits.max())
            p /= p.sum()
            action = int(self.rng.choice(len(p), p=p))
            logp = float(np.log(p[action] + 1e-8))
            next_obs, reward, done, _ = self.env.step(action)
            obs_buf.append(self.obs)
            act_buf.append(action)
            logp_buf.append(logp)
            rew_buf.append(reward)
            val_buf.append(float(value[0]))
            done_buf.append(done)
            ep_ret += reward
            if done:
                episode_returns.append(ep_ret)
                ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        # Bootstrap value for the final partial episode.
        _, last_val = self._forward(params, np.asarray(self.obs)[None])
        vals = np.array(val_buf + [float(last_val[0])], np.float32)
        rews = np.array(rew_buf, np.float32)
        dones = np.array(done_buf, bool)
        adv = np.zeros(num_steps, np.float32)
        last = 0.0
        for t in range(num_steps - 1, -1, -1):
            nonterminal = 0.0 if dones[t] else 1.0
            delta = rews[t] + self.gamma * vals[t + 1] * nonterminal - vals[t]
            last = delta + self.gamma * self.lam * nonterminal * last
            adv[t] = last
        returns = adv + vals[:-1]
        return {
            "obs": np.asarray(obs_buf, np.float32),
            "actions": np.asarray(act_buf, np.int32),
            "logp": np.asarray(logp_buf, np.float32),
            "advantages": adv,
            "returns": returns,
            "episode_returns": episode_returns,
        }


@dataclass
class PPOConfig:
    """Parity: rllib AlgorithmConfig/PPOConfig fluent-config object."""

    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 512
    train_batch_size: int = 1024
    num_sgd_iter: int = 6
    sgd_minibatch_size: int = 256
    gamma: float = 0.99
    lam: float = 0.95
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    lr: float = 3e-4
    # None -> the catalog model's own default width.
    hidden_size: int | None = None
    seed: int = 0
    # Catalog model name ("mlp", "resmlp", "atari_cnn" for pixel envs).
    model: str = "mlp"
    # >1: updates run on a LearnerGroup of remote learner actors with
    # ring-allreduced gradients (reference: learner_group.py remote
    # learners + DDP sync); 1 = in-process jitted update.
    num_learners: int = 1
    # Multi-agent (parity: reference .multi_agent(policies=...,
    # policy_mapping_fn=...)): policy_id -> None; mapping agent_id ->
    # policy_id. None = single-agent.
    policies: Any = None
    policy_mapping_fn: Any = None

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown PPO option {k!r}")
            setattr(self, k, v)
        return self

    def multi_agent(self, *, policies: dict, policy_mapping_fn):
        self.policies = dict(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "PPO":
        return PPO(self)


def make_ppo_loss(forward, clip_param: float, vf_coeff: float,
                  entropy_coeff: float):
    """The PPO clipped-surrogate loss as a free function so the
    in-process learner and the distributed LearnerGroup's learner
    actors jit the SAME math (reference: Learner.compute_loss,
    rllib/core/learner/learner.py)."""
    import jax
    import jax.numpy as jnp

    def loss_fn(params, batch):
        logits, value = forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None].astype(jnp.int32), axis=1
        )[:, 0]
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        clipped = jnp.clip(ratio, 1 - clip_param, 1 + clip_param)
        pi_loss = -jnp.minimum(ratio * adv, clipped * adv).mean()
        vf_loss = ((value - batch["returns"]) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    return loss_fn


class PPO:
    """Algorithm driver (parity: Algorithm.step rllib/algorithms/
    algorithm.py:815 / training_step:1402)."""

    def __init__(self, config: PPOConfig):
        from ray_tpu.rllib.catalog import get_model

        self.config = config
        probe_env = make_env(config.env)
        self.num_actions = probe_env.num_actions
        self._spec = get_model(config.model)
        if config.model == "atari_cnn":
            obs_in = getattr(probe_env, "observation_shape")
        else:
            obs_in = probe_env.observation_size
        self.obs_size = obs_in

        hidden = config.hidden_size or self._spec.default_hidden

        def fresh_params(seed):
            return self._spec.init_params(obs_in, self.num_actions, hidden,
                                          seed)

        if config.policies:
            self.policy_params = {
                pid: fresh_params(config.seed + i)
                for i, pid in enumerate(sorted(config.policies))}
            self.params = None
        else:
            self.params = fresh_params(config.seed)
            self.policy_params = None
        self.workers = [
            RolloutWorker.remote(config.env, i, config.gamma, config.lam,
                                 config.model)
            for i in range(config.num_rollout_workers)]
        self._agent_mapping = None
        if config.policies:
            self._agent_mapping = {
                a: config.policy_mapping_fn(a)
                for a in probe_env.agent_ids}
        self._update = None
        self._learner_group = None
        if config.num_learners > 1 and not config.policies:
            from ray_tpu.rllib.learner_group import LearnerGroup

            self._learner_group = LearnerGroup(
                num_learners=config.num_learners, model=config.model,
                obs_size=obs_in, num_actions=self.num_actions,
                hidden=hidden, lr=config.lr, clip_param=config.clip_param,
                vf_coeff=config.vf_coeff,
                entropy_coeff=config.entropy_coeff, seed=config.seed)
        self.iteration = 0

    # ---- learner (jit) ----

    def _build_update(self):
        import jax
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        if self.policy_params is not None:
            self._opt_state = {pid: opt.init(p)
                               for pid, p in self.policy_params.items()}
        else:
            self._opt_state = opt.init(self.params)

        loss_fn = make_ppo_loss(self._spec.jax_forward, cfg.clip_param,
                                cfg.vf_coeff, cfg.entropy_coeff)

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update)

    def train(self) -> dict:
        """One training iteration: parallel sample → minibatch SGD epochs."""
        import jax
        import numpy as np

        if self._update is None and self._learner_group is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        per_worker = max(cfg.rollout_fragment_length,
                         cfg.train_batch_size // max(1, len(self.workers)))
        if self.policy_params is not None:
            return self._train_multi_agent(per_worker, t0)
        if self._learner_group is not None:
            # Rollouts sample against the gang's (synchronized) params.
            self.params = self._learner_group.get_params()
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        batches = ray_tpu.get(
            [w.sample.remote(host_params, per_worker) for w in self.workers],
            timeout=600)
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in ("obs", "actions", "logp", "advantages", "returns")}
        episode_returns = sum((b["episode_returns"] for b in batches), [])
        sample_time = time.time() - t0

        n = len(batch["obs"])
        rng = np.random.default_rng(cfg.seed + self.iteration)
        t1 = time.time()
        last_aux = {}
        for _ in range(cfg.num_sgd_iter):
            perm = rng.permutation(n)
            for s in range(0, n, cfg.sgd_minibatch_size):
                idx = perm[s: s + cfg.sgd_minibatch_size]
                mb = {k: v[idx] for k, v in batch.items()}
                if self._learner_group is not None:
                    last_aux = self._learner_group.update(mb)
                else:
                    self.params, self._opt_state, loss, aux = self._update(
                        self.params, self._opt_state, mb)
                    last_aux = aux
        if self._learner_group is not None:
            self.params = self._learner_group.get_params()
        learn_time = time.time() - t1
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "episodes_this_iter": len(episode_returns),
            "timesteps_this_iter": n,
            "sample_time_s": round(sample_time, 3),
            "learn_time_s": round(learn_time, 3),
            **{k: float(v) for k, v in last_aux.items()},
        }

    def _train_multi_agent(self, per_worker: int, t0: float) -> dict:
        """Multi-agent iteration: per-policy batches from every worker,
        one PPO update stream per policy (parity: reference multi-agent
        training_step updating each policy from its own batch)."""
        import jax
        import numpy as np

        cfg = self.config
        mapping = self._agent_mapping
        host = {pid: jax.tree_util.tree_map(np.asarray, p)
                for pid, p in self.policy_params.items()}
        results = ray_tpu.get(
            [w.sample_multi_agent.remote(host, per_worker, mapping)
             for w in self.workers], timeout=600)
        episode_returns = sum((r["episode_returns"] for r in results), [])
        sample_time = time.time() - t0
        t1 = time.time()
        total_steps = 0
        last_aux = {}
        for pid in self.policy_params:
            parts = [r["policy_batches"][pid] for r in results
                     if pid in r["policy_batches"]]
            if not parts:
                continue
            batch = {k: np.concatenate([p[k] for p in parts])
                     for k in parts[0]}
            n = len(batch["obs"])
            total_steps += n
            rng = np.random.default_rng(cfg.seed + self.iteration)
            for _ in range(cfg.num_sgd_iter):
                perm = rng.permutation(n)
                for st in range(0, n, cfg.sgd_minibatch_size):
                    idx = perm[st: st + cfg.sgd_minibatch_size]
                    mb = {k: v[idx] for k, v in batch.items()}
                    (self.policy_params[pid], self._opt_state[pid],
                     _loss, aux) = self._update(
                        self.policy_params[pid], self._opt_state[pid], mb)
                    last_aux = aux
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "episodes_this_iter": len(episode_returns),
            "timesteps_this_iter": total_steps,
            "sample_time_s": round(sample_time, 3),
            "learn_time_s": round(time.time() - t1, 3),
            **{k: float(v) for k, v in last_aux.items()},
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self._learner_group is not None:
            self._learner_group.shutdown()

    def get_policy_params(self, policy_id: str | None = None):
        import jax
        import numpy as np

        if self.policy_params is not None:
            if policy_id is None:
                raise ValueError(
                    "multi-agent PPO: pass policy_id to "
                    f"get_policy_params (policies: {sorted(self.policy_params)})")
            return jax.tree_util.tree_map(np.asarray,
                                          self.policy_params[policy_id])
        return jax.tree_util.tree_map(np.asarray, self.params)

    def compute_single_action(self, obs, policy_id: str | None = None) -> int:
        logits, _ = self._spec.numpy_forward(
            self.get_policy_params(policy_id), np.asarray(obs)[None])
        return int(np.argmax(logits[0]))
