"""PPO: CPU rollout actors + JAX (TPU) learner.

Parity: reference rllib/algorithms/ppo/ + the rollout/learner split of
SURVEY.md §3.6 — WorkerSet.sample on CPU actors, LearnerGroup.update on
accelerators (reference: rllib/core/learner/learner_group.py wraps torch
DDP; here the learner is ONE jitted jax update — data parallelism over
learner devices comes from the mesh, not a gradient bucket library).

Rollout workers evaluate the policy with a pure-numpy forward pass (no
jax import in the sampling processes); the learner runs the PPO
clipped-surrogate update under jit on whatever accelerator is attached.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


# ---------------- policy: MLP actor-critic ----------------


def init_policy_params(obs_size: int, num_actions: int, hidden: int = 64,
                       seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    return {
        "h1": dense(obs_size, hidden),
        "h2": dense(hidden, hidden),
        "pi": dense(hidden, num_actions),
        "vf": dense(hidden, 1),
    }


def numpy_forward(params: dict, obs: np.ndarray):
    """Policy forward pass used inside rollout workers."""
    h = np.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
    h = np.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


@ray_tpu.remote
class RolloutWorker:
    """CPU sampling actor (parity: rllib/evaluation/rollout_worker.py)."""

    def __init__(self, env_spec, worker_index: int, gamma: float, lam: float):
        self.env = make_env(env_spec)
        self.index = worker_index
        self.gamma = gamma
        self.lam = lam
        self.rng = np.random.default_rng(1000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)

    def sample(self, params: dict, num_steps: int) -> dict:
        obs_buf, act_buf, logp_buf, rew_buf, val_buf, done_buf = \
            [], [], [], [], [], []
        episode_returns = []
        ep_ret = 0.0
        for _ in range(num_steps):
            logits, value = numpy_forward(params, self.obs[None, :])
            logits = logits[0]
            p = np.exp(logits - logits.max())
            p /= p.sum()
            action = int(self.rng.choice(len(p), p=p))
            logp = float(np.log(p[action] + 1e-8))
            next_obs, reward, done, _ = self.env.step(action)
            obs_buf.append(self.obs)
            act_buf.append(action)
            logp_buf.append(logp)
            rew_buf.append(reward)
            val_buf.append(float(value[0]))
            done_buf.append(done)
            ep_ret += reward
            if done:
                episode_returns.append(ep_ret)
                ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        # Bootstrap value for the final partial episode.
        _, last_val = numpy_forward(params, self.obs[None, :])
        vals = np.array(val_buf + [float(last_val[0])], np.float32)
        rews = np.array(rew_buf, np.float32)
        dones = np.array(done_buf, bool)
        adv = np.zeros(num_steps, np.float32)
        last = 0.0
        for t in range(num_steps - 1, -1, -1):
            nonterminal = 0.0 if dones[t] else 1.0
            delta = rews[t] + self.gamma * vals[t + 1] * nonterminal - vals[t]
            last = delta + self.gamma * self.lam * nonterminal * last
            adv[t] = last
        returns = adv + vals[:-1]
        return {
            "obs": np.asarray(obs_buf, np.float32),
            "actions": np.asarray(act_buf, np.int32),
            "logp": np.asarray(logp_buf, np.float32),
            "advantages": adv,
            "returns": returns,
            "episode_returns": episode_returns,
        }


@dataclass
class PPOConfig:
    """Parity: rllib AlgorithmConfig/PPOConfig fluent-config object."""

    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 512
    train_batch_size: int = 1024
    num_sgd_iter: int = 6
    sgd_minibatch_size: int = 256
    gamma: float = 0.99
    lam: float = 0.95
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    lr: float = 3e-4
    hidden_size: int = 64
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown PPO option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """Algorithm driver (parity: Algorithm.step rllib/algorithms/
    algorithm.py:815 / training_step:1402)."""

    def __init__(self, config: PPOConfig):
        self.config = config
        probe_env = make_env(config.env)
        self.obs_size = probe_env.observation_size
        self.num_actions = probe_env.num_actions
        self.params = init_policy_params(
            self.obs_size, self.num_actions, config.hidden_size, config.seed)
        self.workers = [
            RolloutWorker.remote(config.env, i, config.gamma, config.lam)
            for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0

    # ---- learner (jit) ----

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def loss_fn(params, batch):
            h = jnp.tanh(batch["obs"] @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            logits = h @ params["pi"]["w"] + params["pi"]["b"]
            value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32), axis=1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            clipped = jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param)
            pi_loss = -jnp.minimum(ratio * adv, clipped * adv).mean()
            vf_loss = ((value - batch["returns"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pi_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update)

    def train(self) -> dict:
        """One training iteration: parallel sample → minibatch SGD epochs."""
        import jax
        import numpy as np

        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        per_worker = max(cfg.rollout_fragment_length,
                         cfg.train_batch_size // max(1, len(self.workers)))
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        batches = ray_tpu.get(
            [w.sample.remote(host_params, per_worker) for w in self.workers],
            timeout=600)
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in ("obs", "actions", "logp", "advantages", "returns")}
        episode_returns = sum((b["episode_returns"] for b in batches), [])
        sample_time = time.time() - t0

        n = len(batch["obs"])
        rng = np.random.default_rng(cfg.seed + self.iteration)
        t1 = time.time()
        last_aux = {}
        for _ in range(cfg.num_sgd_iter):
            perm = rng.permutation(n)
            for s in range(0, n, cfg.sgd_minibatch_size):
                idx = perm[s: s + cfg.sgd_minibatch_size]
                mb = {k: v[idx] for k, v in batch.items()}
                self.params, self._opt_state, loss, aux = self._update(
                    self.params, self._opt_state, mb)
                last_aux = aux
        learn_time = time.time() - t1
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "episodes_this_iter": len(episode_returns),
            "timesteps_this_iter": n,
            "sample_time_s": round(sample_time, 3),
            "learn_time_s": round(learn_time, 3),
            **{k: float(v) for k, v in last_aux.items()},
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def get_policy_params(self):
        import jax
        import numpy as np

        return jax.tree_util.tree_map(np.asarray, self.params)

    def compute_single_action(self, obs) -> int:
        logits, _ = numpy_forward(self.get_policy_params(), obs[None, :])
        return int(np.argmax(logits[0]))
