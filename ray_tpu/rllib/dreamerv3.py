"""DreamerV3: model-based RL — world model + actor-critic in imagination.

Parity: reference rllib/algorithms/dreamerv3/ (torch/tf RSSM world model,
imagination-trained actor-critic). Re-designed for JAX/TPU: the entire
update — sequence-model unroll (lax.scan), KL-balanced world-model loss,
H-step imagination rollout, lambda-returns, actor/critic updates — is ONE
jitted function; no per-step Python. Core DreamerV3 signatures kept from
the paper (Hafner et al., 2023): symlog predictions, categorical latents
with straight-through gradients, free-bits KL with dyn/rep balancing,
percentile return normalization, EMA slow critic.

The env loop runs in-process with a jitted act() (the policy is the
world model's filter state, so sampling needs the model — the reference's
DreamerV3 EnvRunner holds the RSSM too, env_runner.py in its dreamerv3
package)."""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from ray_tpu.rllib.env import make_env


def _symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def _symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


@dataclass
class DreamerV3Config:
    """Fluent config (parity: DreamerV3Config in the reference)."""

    env: Any = "CartPole-v1"
    # World model sizes (reference XS-ish; CartPole-class defaults).
    deter: int = 128
    stoch_groups: int = 8
    stoch_classes: int = 8
    hidden: int = 128
    # Replay + schedule.
    replay_capacity: int = 100_000
    batch_size: int = 16
    batch_length: int = 16
    env_steps_per_iter: int = 500
    updates_per_iter: int = 30
    warmup_steps: int = 500
    # Horizons / discounts.
    imag_horizon: int = 15
    gamma: float = 0.997
    lam: float = 0.95
    # Losses.
    beta_pred: float = 1.0
    beta_dyn: float = 0.5
    beta_rep: float = 0.1
    free_bits: float = 1.0
    entropy_coeff: float = 3e-3
    critic_ema_decay: float = 0.98
    # Optim.
    model_lr: float = 1e-3
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DreamerV3 option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "DreamerV3":
        return DreamerV3(self)


class _SeqReplay:
    """Uniform sequence replay over one continuous stream per env
    (parity: reference dreamerv3 EpisodeReplayBuffer, simplified to a
    ring of transitions with episode-boundary `is_first` flags)."""

    def __init__(self, capacity: int, obs_size: int, num_actions: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_size), np.float32)
        self.action = np.zeros((capacity,), np.int32)
        self.reward = np.zeros((capacity,), np.float32)
        self.cont = np.zeros((capacity,), np.float32)
        self.is_first = np.zeros((capacity,), np.float32)
        self.n = 0
        self.ptr = 0

    def add(self, obs, action, reward, cont, is_first):
        i = self.ptr
        self.obs[i] = obs
        self.action[i] = action
        self.reward[i] = reward
        self.cont[i] = cont
        self.is_first[i] = is_first
        self.ptr = (i + 1) % self.capacity
        self.n = min(self.n + 1, self.capacity)

    def sample(self, rng, batch_size: int, length: int) -> dict:
        starts = rng.integers(0, self.n - length, size=batch_size)
        if self.n == self.capacity:
            # Wrapped ring: a linear window containing the write head
            # splices the newest transition onto the oldest with no
            # is_first at the joint — resample any window crossing it.
            for _ in range(8):
                bad = (starts < self.ptr) & (starts + length > self.ptr)
                if not bad.any():
                    break
                starts[bad] = rng.integers(0, self.n - length,
                                           size=int(bad.sum()))
            else:
                # Deterministic safe start: at ptr the window reads only
                # old data; if ptr is too near the end, 0 is clear of it.
                starts[bad] = self.ptr if self.ptr <= self.n - length else 0
        idx = starts[:, None] + np.arange(length)[None, :]
        return {
            "obs": self.obs[idx],
            "action": self.action[idx],
            "reward": self.reward[idx],
            "cont": self.cont[idx],
            "is_first": self.is_first[idx],
        }


class DreamerV3:
    """Algorithm driver (parity: Algorithm.train loop of the reference's
    dreamerv3/dreamerv3.py training_step: sample env → update world
    model + actor + critic from replayed sequences)."""

    def __init__(self, config: DreamerV3Config):
        import jax

        self.config = config
        self.env = make_env(config.env)
        self.obs_size = self.env.observation_size
        self.num_actions = self.env.num_actions
        self.replay = _SeqReplay(config.replay_capacity, self.obs_size,
                                 self.num_actions)
        self.rng = np.random.default_rng(config.seed)
        self._key = jax.random.PRNGKey(config.seed)
        self.params = self._init_params()
        self._build_fns()
        self._opt_init()
        # Filter state for the env loop.
        self._h = np.zeros((config.deter,), np.float32)
        self._z = np.zeros((config.stoch_groups * config.stoch_classes),
                           np.float32)
        self._prev_action = 0
        self._obs = self.env.reset(seed=config.seed)
        self._is_first = 1.0
        self._ep_ret = 0.0
        self._episode_returns: list[float] = []
        self.iteration = 0
        self.total_env_steps = 0
        # Percentile return normalization state (paper: S = EMA of
        # Per(R,95) - Per(R,5), advantages divided by max(1, S)).
        self._ret_scale = 1.0

    # ---------------- params ----------------

    def _init_params(self) -> dict:
        cfg = self.config
        rng = np.random.default_rng(cfg.seed)
        zdim = cfg.stoch_groups * cfg.stoch_classes
        na, h, d = self.num_actions, cfg.hidden, cfg.deter

        def dense(i, o, scale=1.0):
            return {"w": (rng.standard_normal((i, o)) * scale /
                          np.sqrt(i)).astype(np.float32),
                    "b": np.zeros(o, np.float32)}

        return {
            # encoder: symlog(obs) -> embedding
            "enc1": dense(self.obs_size, h),
            "enc2": dense(h, h),
            # GRU core: input [z, a_onehot] -> 3*deter gates
            "gru_x": dense(zdim + na, 3 * d),
            "gru_h": dense(d, 3 * d),
            # prior / posterior categorical logit heads
            "prior1": dense(d, h),
            "prior2": dense(h, zdim),
            "post1": dense(d + h, h),
            "post2": dense(h, zdim),
            # decoders ([h, z] features)
            "dec1": dense(d + zdim, h),
            "dec2": dense(h, self.obs_size),
            "rew1": dense(d + zdim, h),
            "rew2": dense(h, 1, scale=0.0),   # zero-init output head
            "cont1": dense(d + zdim, h),
            "cont2": dense(h, 1),
            # actor / critic (separate optimizers)
            "actor1": dense(d + zdim, h),
            "actor2": dense(h, na, scale=0.01),
            "critic1": dense(d + zdim, h),
            "critic2": dense(h, 1, scale=0.0),
        }

    # ---------------- jitted model fns ----------------

    def _build_fns(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        G, C = cfg.stoch_groups, cfg.stoch_classes
        zdim = G * C
        na = self.num_actions

        def lin(p, x):
            return x @ p["w"] + p["b"]

        def mlp2(p1, p2, x, act=jax.nn.silu):
            return lin(p2, act(lin(p1, x)))

        def gru(p, h, x):
            gates_x = lin(p["gru_x"], x)
            gates_h = lin(p["gru_h"], h)
            r_x, u_x, c_x = jnp.split(gates_x, 3, -1)
            r_h, u_h, c_h = jnp.split(gates_h, 3, -1)
            r = jax.nn.sigmoid(r_x + r_h)
            u = jax.nn.sigmoid(u_x + u_h)
            c = jnp.tanh(c_x + r * c_h)
            return u * c + (1 - u) * h

        def sample_latent(logits, key):
            """Straight-through one-hot sample from G categorical groups,
            with 1% uniform mix (paper: 'unimix' keeps KL finite)."""
            lg = logits.reshape(logits.shape[:-1] + (G, C))
            probs = 0.99 * jax.nn.softmax(lg) + 0.01 / C
            lg = jnp.log(probs)
            idx = jax.random.categorical(key, lg)
            onehot = jax.nn.one_hot(idx, C)
            st = onehot + probs - jax.lax.stop_gradient(probs)
            return st.reshape(st.shape[:-2] + (zdim,)), lg

        def kl_cat(lg_q, lg_p):
            """KL(q||p) summed over groups; inputs are log-prob tensors
            [..., G, C]."""
            q = jnp.exp(lg_q)
            return (q * (lg_q - lg_p)).sum(-1).sum(-1)

        def obs_step(params, h, z, action_onehot, emb, key):
            """One filtering step: advance the sequence model, then fuse
            the observation embedding into the posterior."""
            h = gru(params, h, jnp.concatenate([z, action_onehot], -1))
            prior_logits = mlp2(params["prior1"], params["prior2"], h)
            post_logits = mlp2(params["post1"], params["post2"],
                               jnp.concatenate([h, emb], -1))
            z, lg_post = sample_latent(post_logits, key)
            return h, z, prior_logits, post_logits

        def encode(params, obs):
            return mlp2(params["enc1"], params["enc2"], _symlog(obs))

        # ---- world model loss over [B, L] sequences ----

        def wm_loss(params, batch, key):
            B, L = batch["obs"].shape[:2]
            emb = encode(params, batch["obs"])           # [B, L, h]
            a_onehot = jax.nn.one_hot(batch["action"], na)
            h0 = jnp.zeros((B, cfg.deter))
            z0 = jnp.zeros((B, zdim))
            keys = jax.random.split(key, L)

            def step(carry, t):
                h, z = carry
                # Episode starts reset the recurrent state and the
                # previous action (paper: is_first masking).
                first = batch["is_first"][:, t][:, None]
                h = h * (1 - first)
                z = z * (1 - first)
                act = a_onehot[:, t] * (1 - first)
                h, z, prior_logits, post_logits = obs_step(
                    params, h, z, act, emb[:, t], keys[t])
                return (h, z), (h, z, prior_logits, post_logits)

            (_, _), (hs, zs, prior_lg, post_lg) = jax.lax.scan(
                step, (h0, z0), jnp.arange(L))
            # scan stacks on axis 0 = time; move to [B, L, ...]
            hs, zs = hs.swapaxes(0, 1), zs.swapaxes(0, 1)
            prior_lg = prior_lg.swapaxes(0, 1).reshape(B, L, G, C)
            post_lg = post_lg.swapaxes(0, 1).reshape(B, L, G, C)
            prior_lgp = jax.nn.log_softmax(
                jnp.log(0.99 * jax.nn.softmax(prior_lg) + 0.01 / C))
            post_lgp = jax.nn.log_softmax(
                jnp.log(0.99 * jax.nn.softmax(post_lg) + 0.01 / C))

            feat = jnp.concatenate([hs, zs], -1)
            obs_pred = mlp2(params["dec1"], params["dec2"], feat)
            rew_pred = mlp2(params["rew1"], params["rew2"], feat)[..., 0]
            cont_logit = mlp2(params["cont1"], params["cont2"], feat)[..., 0]

            pred_loss = ((obs_pred - _symlog(batch["obs"])) ** 2).sum(-1) \
                + (rew_pred - _symlog(batch["reward"])) ** 2
            # Binary CE for the continue head.
            cont_ce = -(batch["cont"] * jax.nn.log_sigmoid(cont_logit)
                        + (1 - batch["cont"]) *
                        jax.nn.log_sigmoid(-cont_logit))
            # KL balancing with free bits (paper eq. 5).
            dyn = jnp.maximum(cfg.free_bits,
                              kl_cat(jax.lax.stop_gradient(post_lgp),
                                     prior_lgp))
            rep = jnp.maximum(cfg.free_bits,
                              kl_cat(post_lgp,
                                     jax.lax.stop_gradient(prior_lgp)))
            loss = (cfg.beta_pred * (pred_loss + cont_ce)
                    + cfg.beta_dyn * dyn + cfg.beta_rep * rep).mean()
            return loss, (hs, zs, {"wm_loss": loss,
                                   "kl_dyn": dyn.mean(),
                                   "recon": pred_loss.mean()})

        # ---- imagination rollout + actor/critic losses ----

        def img_step(params, h, z, action_onehot, key):
            h = gru(params, h, jnp.concatenate([z, action_onehot], -1))
            prior_logits = mlp2(params["prior1"], params["prior2"], h)
            z, _ = sample_latent(prior_logits, key)
            return h, z

        def actor_logits(params, feat):
            lg = mlp2(params["actor1"], params["actor2"], feat)
            return jax.nn.log_softmax(lg)

        def critic_value(params, feat):
            return _symexp(mlp2(params["critic1"], params["critic2"],
                                feat)[..., 0])

        # Single fused update: world model grad, imagination, actor grad,
        # critic grad — one jit, one device round-trip per call.

        def lambda_returns(rew, cont, values, last_value):
            """Bootstrapped lambda-returns down the imagined horizon."""
            H = rew.shape[0]

            def step(nxt, t):
                ret = rew[t] + cfg.gamma * cont[t] * (
                    (1 - cfg.lam) * values[t + 1] + cfg.lam * nxt)
                return ret, ret

            _, rets = jax.lax.scan(
                step, last_value, jnp.arange(H - 1, -1, -1))
            return rets[::-1]

        def update(params, slow_critic, batch, key, ret_scale):
            kw, ki, ka = jax.random.split(key, 3)
            (wl, (hs, zs, wm_aux)), wm_grads = jax.value_and_grad(
                wm_loss, has_aux=True)(params, batch, kw)

            # ---- imagination under frozen world model ----
            wm = jax.lax.stop_gradient(params)
            h = hs.reshape(-1, cfg.deter)
            z = zs.reshape(-1, zdim)
            keys = jax.random.split(ki, cfg.imag_horizon)

            def istep(carry, k):
                h, z = carry
                feat = jnp.concatenate([h, z], -1)
                lgp = actor_logits(wm, feat)
                k1, k2 = jax.random.split(k)
                a = jax.random.categorical(k1, lgp)
                h2, z2 = img_step(wm, h, z, jax.nn.one_hot(a, na), k2)
                return (h2, z2), (feat, a)

            (hH, zH), (feats, acts) = jax.lax.scan(istep, (h, z), keys)
            featH = jnp.concatenate([hH, zH], -1)
            rew = mlp2(wm["rew1"], wm["rew2"], feats)[..., 0]
            rew = _symexp(rew)
            cont = jax.nn.sigmoid(
                mlp2(wm["cont1"], wm["cont2"], feats)[..., 0])
            values = critic_value(jax.lax.stop_gradient(params), feats)
            slow_values = critic_value(slow_critic, feats)
            last_v = critic_value(jax.lax.stop_gradient(params), featH)
            vals_for_ret = jnp.concatenate([values, last_v[None]], 0)
            rets = lambda_returns(rew, cont, vals_for_ret, last_v)
            # Discount weights: product of continues down the horizon.
            disc = jnp.cumprod(
                jnp.concatenate([jnp.ones_like(cont[:1]), cont[:-1]], 0), 0)

            # Percentile normalization (paper): scale advantages by
            # max(1, EMA(Per95 - Per5)).
            flat = rets.reshape(-1)
            scale = jnp.percentile(flat, 95) - jnp.percentile(flat, 5)
            new_ret_scale = 0.99 * ret_scale + 0.01 * scale
            norm = jnp.maximum(1.0, new_ret_scale)

            def actor_loss(ap):
                lgp = actor_logits({**wm, "actor1": ap["actor1"],
                                    "actor2": ap["actor2"]}, feats)
                logp_a = jnp.take_along_axis(
                    lgp, acts[..., None], -1)[..., 0]
                adv = jax.lax.stop_gradient((rets - values) / norm)
                ent = -(jnp.exp(lgp) * lgp).sum(-1)
                return -(disc * (logp_a * adv
                                 + cfg.entropy_coeff * ent)).mean(), ent

            (al, ent), actor_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(
                {"actor1": params["actor1"], "actor2": params["actor2"]})

            def critic_loss(cp):
                v_pred = mlp2(cp["critic1"], cp["critic2"],
                              jax.lax.stop_gradient(feats))[..., 0]
                target = _symlog(jax.lax.stop_gradient(rets))
                slow_t = _symlog(jax.lax.stop_gradient(slow_values))
                return (disc * ((v_pred - target) ** 2
                                + 0.3 * (v_pred - slow_t) ** 2)).mean()

            cl, critic_grads = jax.value_and_grad(critic_loss)(
                {"critic1": params["critic1"], "critic2": params["critic2"]})

            grads = dict(wm_grads)
            for k2 in ("actor1", "actor2"):
                grads[k2] = jax.tree_util.tree_map(
                    jnp.add, grads[k2], actor_grads[k2])
            for k2 in ("critic1", "critic2"):
                grads[k2] = jax.tree_util.tree_map(
                    jnp.add, grads[k2], critic_grads[k2])
            aux = {**wm_aux, "actor_loss": al, "critic_loss": cl,
                   "entropy": ent.mean(),
                   "imag_return": rets.mean()}
            return grads, new_ret_scale, aux

        self._update_grads = jax.jit(update)

        def act(params, h, z, prev_action, obs, is_first, key):
            k_post, k_act = jax.random.split(key)
            emb = encode(params, obs[None])  # encode() symlogs internally
            h = h[None] * (1 - is_first)
            z = z[None] * (1 - is_first)
            a_onehot = jax.nn.one_hot(
                jnp.asarray([prev_action]), na) * (1 - is_first)
            h, z, _, _ = obs_step(params, h, z, a_onehot, emb, k_post)
            feat = jnp.concatenate([h, z], -1)
            lgp = actor_logits(params, feat)
            a = jax.random.categorical(k_act, lgp)[0]
            return h[0], z[0], a

        self._act = jax.jit(act)

    def _opt_init(self):
        import optax

        cfg = self.config
        # One optimizer tree with per-head learning rates via masks
        # would complicate checkpointing; a single adam at model_lr with
        # actor/critic heads zero-init works for the small nets here, but
        # keep the paper's separate rates with three labels.
        self._opt = optax.multi_transform(
            {"model": optax.adam(cfg.model_lr),
             "actor": optax.adam(cfg.actor_lr),
             "critic": optax.adam(cfg.critic_lr)},
            {k: ("actor" if k.startswith("actor") else
                 "critic" if k.startswith("critic") else "model")
             for k in self.params})
        self._opt_state = self._opt.init(self.params)
        import jax

        self._slow_critic = {
            "critic1": jax.tree_util.tree_map(np.copy,
                                              self.params["critic1"]),
            "critic2": jax.tree_util.tree_map(np.copy,
                                              self.params["critic2"])}

    # ---------------- env loop + train ----------------

    def _env_steps(self, n: int):
        import jax

        for _ in range(n):
            self._key, k = jax.random.split(self._key)
            h, z, a = self._act(self.params, self._h, self._z,
                                self._prev_action,
                                np.asarray(self._obs, np.float32),
                                self._is_first, k)
            a = int(a)
            next_obs, rew, done, info = self.env.step(a)
            truncated = bool(info.get("truncated", False))
            self.replay.add(self._obs, a, rew, 0.0 if (done and not truncated)
                            else 1.0, self._is_first)
            self._h, self._z = np.asarray(h), np.asarray(z)
            self._prev_action = a
            self._is_first = 0.0
            self._ep_ret += rew
            self.total_env_steps += 1
            if done:
                self._episode_returns.append(self._ep_ret)
                self._ep_ret = 0.0
                self._obs = self.env.reset()
                self._is_first = 1.0
                self._prev_action = 0
            else:
                self._obs = next_obs

    def train(self) -> dict:
        import jax
        import optax

        cfg = self.config
        t0 = time.time()
        self._episode_returns = []
        self._env_steps(cfg.env_steps_per_iter)
        sample_time = time.time() - t0
        t1 = time.time()
        aux = {}
        updates_run = 0
        if self.replay.n > max(cfg.warmup_steps,
                               cfg.batch_length + 1):
            for _ in range(cfg.updates_per_iter):
                batch = self.replay.sample(self.rng, cfg.batch_size,
                                           cfg.batch_length)
                self._key, k = jax.random.split(self._key)
                grads, self._ret_scale, aux = self._update_grads(
                    self.params, self._slow_critic, batch, k,
                    self._ret_scale)
                updates, self._opt_state = self._opt.update(
                    grads, self._opt_state, self.params)
                self.params = optax.apply_updates(self.params, updates)
                # EMA slow critic.
                d = cfg.critic_ema_decay
                for hk in ("critic1", "critic2"):
                    self._slow_critic[hk] = jax.tree_util.tree_map(
                        lambda s, p: d * s + (1 - d) * p,
                        self._slow_critic[hk], self.params[hk])
                updates_run += 1
        self.iteration += 1
        rets = self._episode_returns
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(rets)) if rets else
            float("nan"),
            "episodes_this_iter": len(rets),
            "timesteps_total": self.total_env_steps,
            "num_updates": updates_run,
            "sample_time_s": round(sample_time, 3),
            "learn_time_s": round(time.time() - t1, 3),
            **{k: float(v) for k, v in aux.items()},
        }

    def compute_single_action(self, obs) -> int:
        """Greedy action from a FRESH filter state (evaluation helper)."""
        import jax

        self._key, k = jax.random.split(self._key)
        _, _, a = self._act(self.params,
                            np.zeros_like(self._h), np.zeros_like(self._z),
                            0, np.asarray(obs, np.float32), 1.0, k)
        return int(a)

    def stop(self):
        pass
