"""QMIX: cooperative multi-agent Q-learning with monotonic value mixing.

Parity: reference rllib/algorithms/qmix/ (per-agent Q networks whose
chosen-action values feed a mixing network with non-negative weights —
hypernetworks conditioned on the GLOBAL state — so argmax per agent is
argmax of the team value; trained by TD on the shared team reward).

Ships with `CoopSwitch`, a minimal cooperative env where the team
reward exists only when agents coordinate — independent learners
plateau on it, the mixer's credit assignment does not (the standard
QMIX motivation, miniaturized).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from ray_tpu.rllib.env import ENV_REGISTRY, MultiAgentEnv


class CoopSwitch(MultiAgentEnv):
    """Two agents each observe a private bit; the team earns +1 only
    when their JOINT action matches the XOR of the bits (a matrix game
    per step, re-randomized; episode of fixed length). Global state =
    both bits (the mixer may use it; each agent sees only its own)."""

    agent_ids = ("agent_0", "agent_1")
    observation_size = 2           # own bit (one-hot)
    num_actions = 2
    episode_len = 16

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._bits = (0, 0)

    @property
    def global_state(self) -> np.ndarray:
        return np.asarray(self._bits, np.float32)

    def _obs(self) -> dict:
        return {a: np.eye(2, dtype=np.float32)[self._bits[i]]
                for i, a in enumerate(self.agent_ids)}

    def reset(self, seed: int | None = None) -> dict:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._bits = tuple(self._rng.integers(0, 2, 2))
        return self._obs()

    def step(self, actions: dict):
        want = self._bits[0] ^ self._bits[1]
        team = float(actions["agent_0"] == want and
                     actions["agent_1"] == want)
        self._t += 1
        done = self._t >= self.episode_len
        self._bits = tuple(self._rng.integers(0, 2, 2))
        obs = self._obs()
        rew = {a: team for a in self.agent_ids}   # shared team reward
        dones = {a: done for a in self.agent_ids}
        dones["__all__"] = done
        return obs, rew, dones, {"team_reward": team}


ENV_REGISTRY.setdefault("CoopSwitch-v0", CoopSwitch)


@dataclass
class QMIXConfig:
    """Fluent config (parity: rllib QMIXConfig)."""

    env: Any = "CoopSwitch-v0"
    episodes_per_iter: int = 16
    gamma: float = 0.95
    lr: float = 5e-3
    hidden: int = 32
    mixer_hidden: int = 16
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_iters: int = 15
    target_update_freq: int = 5
    buffer_episodes: int = 256
    train_batches: int = 16
    batch_size: int = 128
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown QMIX option {k!r}")
            setattr(self, k, v)
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown QMIX option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "QMIX":
        return QMIX(self)


class QMIX:
    def __init__(self, config: QMIXConfig):
        self.config = config
        env_cls = (ENV_REGISTRY[config.env]
                   if isinstance(config.env, str) else config.env)
        self.env = env_cls()
        self.n_agents = len(self.env.agent_ids)
        self.obs_size = self.env.observation_size
        self.num_actions = self.env.num_actions
        # Envs without a global_state fall back to concatenated agent
        # observations as the mixer conditioning (reference QMIX does
        # the same when no state space is provided).
        self._has_global_state = hasattr(self.env, "global_state")
        probe_obs = self.env.reset(seed=config.seed)
        self.state_size = len(self._global_state(probe_obs))
        self.params = self._init_params()
        self.target_params = self.params
        self._update = None
        self.iteration = 0
        self.total_steps = 0
        self._buffer: list = []     # transitions across episodes
        self.rng = np.random.default_rng(config.seed)

    def _init_params(self) -> dict:
        cfg = self.config
        rng = np.random.default_rng(cfg.seed)

        def dense(i, o):
            return {"w": (rng.standard_normal((i, o)) *
                          (1.0 / np.sqrt(i))).astype(np.float32),
                    "b": np.zeros(o, np.float32)}

        return {
            # One shared agent network (parameter sharing, the QMIX
            # default) with an agent-id one-hot appended to the obs.
            "q1": dense(self.obs_size + self.n_agents, cfg.hidden),
            "q2": dense(cfg.hidden, self.num_actions),
            # Hypernetworks: global state -> mixer weights (abs => the
            # monotonicity constraint) and biases.
            "hw1": dense(self.state_size, self.n_agents * cfg.mixer_hidden),
            "hb1": dense(self.state_size, cfg.mixer_hidden),
            "hw2": dense(self.state_size, cfg.mixer_hidden),
            "hb2": dense(self.state_size, 1),
        }

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)
        n_agents, M = self.n_agents, cfg.mixer_hidden

        def agent_q(p, obs_aug):
            h = jnp.tanh(obs_aug @ p["q1"]["w"] + p["q1"]["b"])
            return h @ p["q2"]["w"] + p["q2"]["b"]

        def mix(p, qs, state):
            # qs: (B, n_agents); monotonic mixing via abs hyper-weights.
            w1 = jnp.abs(state @ p["hw1"]["w"] + p["hw1"]["b"]) \
                .reshape(-1, n_agents, M)
            b1 = state @ p["hb1"]["w"] + p["hb1"]["b"]
            h = jnp.tanh(jnp.einsum("ba,bam->bm", qs, w1) + b1)
            w2 = jnp.abs(state @ p["hw2"]["w"] + p["hw2"]["b"])
            b2 = state @ p["hb2"]["w"] + p["hb2"]["b"]
            return (h * w2).sum(-1, keepdims=True) + b2  # (B, 1)

        self._agent_q = jax.jit(agent_q)

        def loss_fn(params, target, batch):
            obs, actions, state = batch["obs"], batch["actions"], batch["state"]
            next_obs, next_state = batch["next_obs"], batch["next_state"]
            B = obs.shape[0]
            qs = agent_q(params, obs.reshape(B * n_agents, -1)) \
                .reshape(B, n_agents, -1)
            q_sel = jnp.take_along_axis(qs, actions[..., None],
                                        axis=-1)[..., 0]
            q_tot = mix(params, q_sel, state)[:, 0]
            qs_next = agent_q(target, next_obs.reshape(B * n_agents, -1)) \
                .reshape(B, n_agents, -1)
            q_next = qs_next.max(-1)
            q_tot_next = mix(target, q_next, next_state)[:, 0]
            y = batch["reward"] + cfg.gamma * (1.0 - batch["done"]) \
                * q_tot_next
            td = q_tot - jax.lax.stop_gradient(y)
            return (td * td).mean()

        def update(params, target, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, target, batch)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update_fn = jax.jit(update)
        self._update = True

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _obs_of(self, obs: dict, agent: str) -> np.ndarray:
        """Agents already done may be absent from the obs dict (e.g.
        DualCartPole omits them): zeros stand in."""
        v = obs.get(agent)
        if v is None:
            return np.zeros(self.obs_size, np.float32)
        return np.asarray(v, np.float32).reshape(-1)

    def _global_state(self, obs: dict) -> np.ndarray:
        if self._has_global_state:
            return np.asarray(self.env.global_state,
                              np.float32).reshape(-1)
        return np.concatenate([self._obs_of(obs, a)
                               for a in self.env.agent_ids])

    def _aug_obs(self, obs: dict) -> np.ndarray:
        """(n_agents, obs+id) — shared net with agent-id one-hot."""
        rows = []
        for i, a in enumerate(self.env.agent_ids):
            one = np.zeros(self.n_agents, np.float32)
            one[i] = 1.0
            rows.append(np.concatenate([self._obs_of(obs, a), one]))
        return np.stack(rows)

    def _act(self, obs: dict, eps: float) -> dict:
        aug = self._aug_obs(obs)
        qs = np.asarray(self._agent_q(self.params, aug))
        acts = {}
        for i, a in enumerate(self.env.agent_ids):
            if self.rng.random() < eps:
                acts[a] = int(self.rng.integers(self.num_actions))
            else:
                acts[a] = int(np.argmax(qs[i]))
        return acts

    def train(self) -> dict:
        import jax
        import jax.numpy as jnp

        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        eps = self._epsilon()
        team_returns = []
        for ep in range(cfg.episodes_per_iter):
            obs = self.env.reset(seed=cfg.seed + self.iteration * 1000 + ep)
            total = 0.0
            done = False
            while not done:
                state = self._global_state(obs)
                acts = self._act(obs, eps)
                nxt, rew, dones, info = self.env.step(acts)
                next_state = self._global_state(nxt)
                team_r = float(info.get(
                    "team_reward", np.mean(list(rew.values()))))
                done = dones["__all__"]
                self._buffer.append((
                    self._aug_obs(obs),
                    np.asarray([acts[a] for a in self.env.agent_ids],
                               np.int32),
                    state, team_r, self._aug_obs(nxt), next_state,
                    float(done)))
                total += team_r
                self.total_steps += 1
                obs = nxt
            team_returns.append(total)
        max_tr = cfg.buffer_episodes * getattr(self.env, "episode_len", 64)
        self._buffer = self._buffer[-max_tr:]

        losses = []
        if len(self._buffer) >= cfg.batch_size:
            for _ in range(cfg.train_batches):
                idx = self.rng.integers(0, len(self._buffer),
                                        cfg.batch_size)
                cols = list(zip(*[self._buffer[i] for i in idx]))
                batch = {
                    "obs": jnp.asarray(np.stack(cols[0])),
                    "actions": jnp.asarray(np.stack(cols[1])),
                    "state": jnp.asarray(np.stack(cols[2])),
                    "reward": jnp.asarray(np.asarray(cols[3], np.float32)),
                    "next_obs": jnp.asarray(np.stack(cols[4])),
                    "next_state": jnp.asarray(np.stack(cols[5])),
                    "done": jnp.asarray(np.asarray(cols[6], np.float32)),
                }
                self.params, self._opt_state, loss = self._update_fn(
                    self.params, self.target_params, self._opt_state,
                    batch)
                losses.append(float(loss))
        self.iteration += 1
        if self.iteration % cfg.target_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(
                lambda x: x, self.params)
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(team_returns)),
            "episodes_this_iter": len(team_returns),
            "timesteps_total": self.total_steps,
            "mean_loss": float(np.mean(losses)) if losses else 0.0,
            "epsilon": round(eps, 3),
            "iter_time_s": round(time.time() - t0, 3),
        }

    def compute_actions(self, obs: dict) -> dict:
        if self._update is None:
            self._build_update()
        return self._act(obs, eps=0.0)

    def stop(self):
        pass
