"""A2C: synchronous advantage actor-critic.

Parity: reference rllib/algorithms/a2c/ — synchronous variant of A3C:
every iteration all rollout workers sample with the current policy, the
learner does ONE gradient step on the combined batch (no PPO-style
minibatch epochs, no clipping), then weights broadcast back.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import RolloutWorker, init_policy_params, numpy_forward


@dataclass
class A2CConfig:
    """Fluent config (parity: rllib A2CConfig)."""

    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 256
    gamma: float = 0.99
    lam: float = 1.0              # GAE(λ=1) = Monte-Carlo advantages
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    lr: float = 1e-3
    hidden_size: int = 64
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown A2C option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "A2C":
        return A2C(self)


class A2C:
    """Algorithm driver: sample (sync, all workers) → one gradient step."""

    def __init__(self, config: A2CConfig):
        self.config = config
        probe = make_env(config.env)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.params = init_policy_params(
            self.obs_size, self.num_actions, config.hidden_size, config.seed)
        # PPO's worker computes GAE with (gamma, lam) — with lam=1 that is
        # the plain discounted advantage A2C wants.
        self.workers = [
            RolloutWorker.remote(config.env, i, config.gamma, config.lam)
            for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def loss_fn(params, batch):
            h = jnp.tanh(batch["obs"] @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            logits = h @ params["pi"]["w"] + params["pi"]["b"]
            value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32),
                axis=1)[:, 0]
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pi_loss = -(logp * adv).mean()
            vf_loss = ((value - batch["returns"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pi_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update)

    def train(self) -> dict:
        import jax

        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        batches = ray_tpu.get(
            [w.sample.remote(host_params, cfg.rollout_fragment_length)
             for w in self.workers], timeout=600)
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in ("obs", "actions", "advantages", "returns")}
        episode_returns = sum((b["episode_returns"] for b in batches), [])
        self.params, self._opt_state, loss, aux = self._update(
            self.params, self._opt_state, batch)
        n = len(batch["obs"])
        self.total_steps += n
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "episodes_this_iter": len(episode_returns),
            "timesteps_this_iter": n,
            "timesteps_total": self.total_steps,
            "iter_time_s": round(time.time() - t0, 3),
            **{k: float(v) for k, v in aux.items()},
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def get_policy_params(self) -> dict:
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def compute_single_action(self, obs) -> int:
        logits, _ = numpy_forward(self.get_policy_params(), obs[None, :])
        return int(np.argmax(logits[0]))
