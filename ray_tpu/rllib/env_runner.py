"""EnvRunner: vectorized environment stepping for rollout workers.

Parity: reference rllib/env/env_runner.py + vector envs — one runner
owns N env copies and steps them with BATCHED policy forwards, so the
per-step cost is one matrix multiply over N observations instead of N
python-loop forwards. Episode accounting (returns, resets) is handled
per sub-env; connector pipelines apply per sub-env so stateful
connectors (frame stacks) stay episode-scoped.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ray_tpu.rllib.connectors import ConnectorPipeline
from ray_tpu.rllib.env import make_env


class EnvRunner:
    def __init__(self, env_spec, num_envs: int = 1, *, seed: int = 0,
                 obs_connectors: Callable[[], ConnectorPipeline] | None = None,
                 act_connectors: Callable[[], ConnectorPipeline] | None = None):
        self.envs = [make_env(env_spec) for _ in range(num_envs)]
        self.num_envs = num_envs
        self._obs_pipes = [obs_connectors() if obs_connectors else
                           ConnectorPipeline() for _ in range(num_envs)]
        self._act_pipes = [act_connectors() if act_connectors else
                           ConnectorPipeline() for _ in range(num_envs)]
        self._ep_ret = np.zeros(num_envs)
        self.episode_returns: list[float] = []
        # Connector-transformed observations are computed EXACTLY ONCE per
        # env transition (stateful connectors — frame stacks, running
        # normalizers — advance on every application, so a repeated getter
        # would silently corrupt their state).
        self._cur_obs = [self._obs_pipes[i](e.reset(seed=seed + i))
                         for i, e in enumerate(self.envs)]

    @property
    def observation_size(self) -> int:
        return self.envs[0].observation_size

    def observations(self) -> np.ndarray:
        """Current per-env observations (transformed at transition time;
        safe to call repeatedly)."""
        return np.stack(self._cur_obs)

    def step(self, actions: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Step every sub-env with its (connector-transformed) action.
        Returns (rewards, dones); finished sub-envs auto-reset with their
        connector state cleared, and their returns land in
        self.episode_returns."""
        rewards = np.zeros(self.num_envs, np.float32)
        dones = np.zeros(self.num_envs, np.float32)
        for i, env in enumerate(self.envs):
            act = self._act_pipes[i](actions[i])
            obs, rew, done, _info = env.step(act)
            rewards[i] = rew
            dones[i] = float(done)
            self._ep_ret[i] += rew
            if done:
                self.episode_returns.append(float(self._ep_ret[i]))
                self._ep_ret[i] = 0.0
                self._obs_pipes[i].reset()
                obs = env.reset()
            self._cur_obs[i] = self._obs_pipes[i](obs)
        return rewards, dones

    def drain_episode_returns(self) -> list[float]:
        out, self.episode_returns = self.episode_returns, []
        return out

    def sample_fragment(self, forward: Callable, sample_action: Callable,
                        num_steps: int) -> dict[str, Any]:
        """Collect num_steps per sub-env with batched forwards.

        forward(obs_batch) -> (logits_or_mu, values); sample_action(
        per-row forward outputs, row index) -> (action, logp). Returns
        stacked (num_steps * num_envs) arrays in sub-env-major order
        with per-row episode boundaries preserved via `dones`.
        """
        obs_b, act_b, logp_b, rew_b, val_b, done_b = [], [], [], [], [], []
        for _ in range(num_steps):
            obs = self.observations()
            logits, values = forward(obs)
            acts, logps = [], []
            for i in range(self.num_envs):
                a, lp = sample_action(logits[i], i)
                acts.append(a)
                logps.append(lp)
            actions = np.asarray(acts)
            rewards, dones = self.step(actions)
            obs_b.append(obs)
            act_b.append(actions)
            logp_b.append(np.asarray(logps, np.float32))
            rew_b.append(rewards)
            val_b.append(np.asarray(values, np.float32))
            done_b.append(dones)
        # (T, N, ...) -> sub-env-major (N*T, ...) so GAE can scan each
        # sub-env's fragment contiguously.
        def swap(x):
            a = np.asarray(x)
            return np.swapaxes(a, 0, 1).reshape((-1,) + a.shape[2:])

        return {"obs": swap(obs_b), "actions": swap(act_b),
                "logp": swap(logp_b), "rew": swap(rew_b),
                "val": swap(val_b), "done": swap(done_b),
                "episode_returns": self.drain_episode_returns(),
                "num_envs": self.num_envs, "steps_per_env": num_steps}
