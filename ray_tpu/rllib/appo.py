"""APPO: asynchronous PPO — IMPALA's async sampling architecture with the
PPO clipped-surrogate objective on V-trace-corrected advantages.

Parity: reference rllib/algorithms/appo/ (appo.py, appo_torch_policy.py) —
APPO is IMPALA's actor/learner split where the learner applies the PPO
clip to importance ratios (behavior vs current policy) instead of the
plain V-trace policy-gradient, plus a slowly-updated target network used
as the clipping anchor.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ray_tpu.rllib.impala import Impala, ImpalaConfig


@dataclass
class APPOConfig(ImpalaConfig):
    """Fluent config (parity: rllib APPOConfig)."""

    clip_param: float = 0.2
    use_kl_loss: bool = False
    kl_coeff: float = 0.2
    kl_target: float = 0.01
    target_update_freq: int = 4   # learner steps between target syncs

    def build(self) -> "APPO":
        return APPO(self)


class APPO(Impala):
    """Async PPO driver. Inherits IMPALA's in-flight fragment pipeline;
    only the jitted learner update differs."""

    def __init__(self, config: APPOConfig):
        super().__init__(config)
        self._target_params = None
        self._steps_since_sync = 0

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)
        self._target_params = jax.tree_util.tree_map(np.copy, self.params)

        def forward(params, obs):
            h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            logits = h @ params["pi"]["w"] + params["pi"]["b"]
            value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
            return logits, value

        def vtrace(values, boot_v, rewards, dones, rhos):
            clipped_rho = jnp.minimum(cfg.vtrace_clip_rho, rhos)
            clipped_c = jnp.minimum(cfg.vtrace_clip_c, rhos)
            next_values = jnp.concatenate([values[1:], boot_v[None]])
            next_values = next_values * (1 - dones)
            deltas = clipped_rho * (rewards + cfg.gamma * next_values - values)

            def body(acc, xs):
                delta, c, done = xs
                acc = delta + cfg.gamma * (1 - done) * c * acc
                return acc, acc

            _, advs = jax.lax.scan(body, jnp.zeros(()),
                                   (deltas, clipped_c, dones), reverse=True)
            vs = values + advs
            next_vs = jnp.concatenate([vs[1:], boot_v[None]]) * (1 - dones)
            pg_adv = clipped_rho * (rewards + cfg.gamma * next_vs - values)
            return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

        def loss_fn(params, target_params, batch):
            logits, values = forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            actions = batch["actions"][:, None].astype(jnp.int32)
            logp = jnp.take_along_axis(logp_all, actions, axis=1)[:, 0]

            # V-trace targets/advantages computed with the *target* network
            # (the stable anchor; reference: appo uses target for v-trace).
            t_logits, t_values = forward(target_params, batch["obs"])
            t_logp_all = jax.nn.log_softmax(t_logits)
            t_logp = jnp.take_along_axis(t_logp_all, actions, axis=1)[:, 0]
            _, t_boot_v = forward(target_params, batch["bootstrap_obs"][None, :])
            t_rhos = jnp.exp(t_logp - batch["behavior_logp"])
            vs, pg_adv = vtrace(t_values, t_boot_v[0], batch["rewards"],
                                batch["dones"], t_rhos)
            adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

            # PPO clip on the current/behavior ratio.
            ratio = jnp.exp(logp - batch["behavior_logp"])
            clipped = jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param)
            pi_loss = -jnp.minimum(ratio * adv, clipped * adv).mean()
            vf_loss = ((values - vs) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pi_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * entropy
            aux = {"pi_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy,
                   "mean_ratio": ratio.mean()}
            if cfg.use_kl_loss:
                kl = (jnp.exp(t_logp_all) * (t_logp_all - logp_all)).sum(-1).mean()
                total = total + cfg.kl_coeff * kl
                aux["kl"] = kl
            return total, aux

        def update(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        jitted = jax.jit(update)

        def stepper(params, opt_state, batch):
            out = jitted(params, self._target_params, opt_state, batch)
            self._steps_since_sync += 1
            if self._steps_since_sync >= cfg.target_update_freq:
                self._target_params = out[0]
                self._steps_since_sync = 0
            return out

        self._update = stepper
