"""A3C: asynchronous advantage actor-critic.

Parity: reference rllib/algorithms/a3c/ — the asynchronous ancestor of
A2C: each rollout worker samples with (possibly stale) weights and the
learner applies a gradient step PER ARRIVING batch instead of waiting
for the whole worker set. Here that is a wait-any loop over sample
futures: workers never block on each other or on learning, matching
the hogwild-style staleness tolerance of the original.

Reuses A2C's loss/update (init in A2CConfig terms); only the
synchronization topology differs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

import ray_tpu
from ray_tpu.rllib.a2c import A2C, A2CConfig


@dataclass
class A3CConfig(A2CConfig):
    """Fluent config (parity: rllib A3CConfig)."""

    num_rollout_workers: int = 2
    # how many per-batch async updates make one train() iteration
    batches_per_iter: int = 4

    def build(self) -> "A3C":  # type: ignore[override]
        return A3C(self)


class A3C(A2C):
    def __init__(self, config: A3CConfig):
        super().__init__(config)
        self._inflight: dict = {}

    def _launch(self, i: int):
        import jax

        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        fut = self.workers[i].sample.remote(
            host_params, self.config.rollout_fragment_length)
        self._inflight[fut] = i

    def train(self) -> dict:
        if self._update is None:
            self._build_update()
        cfg: A3CConfig = self.config  # type: ignore[assignment]
        t0 = time.time()
        for i in range(len(self.workers)):
            if i not in self._inflight.values():
                self._launch(i)

        episode_returns: list = []
        losses: list = []
        n_steps = 0
        for _ in range(cfg.batches_per_iter):
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            fut = ready[0]
            i = self._inflight.pop(fut)
            batch = ray_tpu.get(fut, timeout=60)
            episode_returns.extend(batch.pop("episode_returns", []))
            # One async gradient step on this worker's (stale-weight)
            # batch, then hand the worker the NEW weights.
            self.params, self._opt_state, loss, _aux = self._update(
                self.params, self._opt_state,
                {k: batch[k] for k in ("obs", "actions", "advantages",
                                       "returns")})
            losses.append(float(loss))
            n_steps += len(batch["obs"])
            self._launch(i)
        self.total_steps += n_steps
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "episodes_this_iter": len(episode_returns),
            "timesteps_this_iter": n_steps,
            "timesteps_total": self.total_steps,
            "mean_loss": float(np.mean(losses)) if losses else 0.0,
            "iter_time_s": round(time.time() - t0, 3),
        }
