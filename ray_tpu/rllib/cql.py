"""CQL: conservative Q-learning for offline RL (discrete actions).

Parity: reference rllib/algorithms/cql/ — offline batches only (no env
interaction during training), with the conservative penalty
E[logsumexp_a Q(s,a)] - E[Q(s, a_data)] added to the Bellman loss so
out-of-distribution actions are pushed DOWN instead of exploited. Built
on the DQN learner shape (discrete double-Q target) over JsonReader
batches; evaluation rolls the greedy policy in the real env.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.offline import JsonReader


def init_q_params(obs_size: int, num_actions: int, hidden: int = 64,
                  seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)
                      ).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    return {"h1": dense(obs_size, hidden), "h2": dense(hidden, hidden),
            "out": dense(hidden, num_actions)}


def numpy_q(params: dict, obs: np.ndarray) -> np.ndarray:
    h = np.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
    h = np.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    return h @ params["out"]["w"] + params["out"]["b"]


@dataclass
class CQLConfig:
    env: Any = "CartPole-v1"          # evaluation env only
    input_path: str = ""              # offline JSON data (JsonReader)
    train_batch_size: int = 256
    num_updates_per_iter: int = 200
    gamma: float = 0.99
    lr: float = 3e-4
    cql_alpha: float = 1.0            # conservative penalty weight
    target_update_every: int = 100
    hidden_size: int = 64
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def offline_data(self, input_path: str):
        self.input_path = input_path
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown CQL option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "CQL":
        return CQL(self)


class CQL:
    def __init__(self, config: CQLConfig):
        if not config.input_path:
            raise ValueError("CQL is offline-only: set offline_data(path)")
        self.config = config
        probe = make_env(config.env)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.params = init_q_params(self.obs_size, self.num_actions,
                                    config.hidden_size, config.seed)
        import copy

        self.target = copy.deepcopy(self.params)
        data = JsonReader(config.input_path).read_all()
        n = len(data["obs"])
        if n < 2:
            raise ValueError("offline dataset too small")
        # next_obs/dones reconstructed from the flat log (step i -> i+1;
        # a done at i ends the episode, obs[i+1] starts the next).
        self.data = {
            "obs": data["obs"][:-1],
            "actions": data["actions"][:-1],
            "rewards": data["rewards"][:-1],
            "next_obs": data["obs"][1:],
            "dones": data["dones"][:-1].astype(np.float32),
        }
        self._rng = np.random.default_rng(config.seed)
        self._update = None
        self.iteration = 0
        self._updates = 0

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def q_fn(p, obs):
            h = jnp.tanh(obs @ p["h1"]["w"] + p["h1"]["b"])
            h = jnp.tanh(h @ p["h2"]["w"] + p["h2"]["b"])
            return h @ p["out"]["w"] + p["out"]["b"]

        def update(params, target, opt_state, batch):
            q_next = q_fn(target, batch["next_obs"])
            y = jax.lax.stop_gradient(
                batch["rewards"] + cfg.gamma * (1 - batch["dones"])
                * q_next.max(-1))

            def loss_fn(p):
                q = q_fn(p, batch["obs"])
                q_data = jnp.take_along_axis(
                    q, batch["actions"][:, None].astype(jnp.int32), 1)[:, 0]
                bellman = ((q_data - y) ** 2).mean()
                # Conservative penalty: push down the soft-max over ALL
                # actions, push up the dataset action.
                conservative = (jax.scipy.special.logsumexp(q, axis=-1)
                                - q_data).mean()
                return bellman + cfg.cql_alpha * conservative, (
                    bellman, conservative)

            (loss, (bellman, conservative)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"loss": loss, "bellman": bellman,
                                       "cql_penalty": conservative}

        self._update = jax.jit(update)

    def train(self) -> dict:
        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        n = len(self.data["obs"])
        metrics = {}
        for _ in range(cfg.num_updates_per_iter):
            idx = self._rng.integers(0, n, cfg.train_batch_size)
            batch = {k: v[idx] for k, v in self.data.items()}
            self.params, self._opt_state, metrics = self._update(
                self.params, self.target, self._opt_state, batch)
            self._updates += 1
            if self._updates % cfg.target_update_every == 0:
                import copy
                import jax

                self.target = copy.deepcopy(jax.tree_util.tree_map(
                    np.asarray, self.params))
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "learn_time_s": round(time.time() - t0, 3),
                **{k: float(v) for k, v in metrics.items()}}

    def evaluate(self, num_episodes: int = 5) -> dict:
        """Greedy rollout in the real env (offline training never touches
        it — this is the measurement, reference: evaluation workers)."""
        import jax

        params = jax.tree_util.tree_map(np.asarray, self.params)
        env = make_env(self.config.env)
        returns = []
        for ep in range(num_episodes):
            obs = env.reset(seed=1000 + ep)
            ret, done = 0.0, False
            while not done:
                a = int(np.argmax(numpy_q(params, obs[None])[0]))
                obs, rew, done, _ = env.step(a)
                ret += rew
            returns.append(ret)
        return {"episode_reward_mean": float(np.mean(returns)),
                "episodes": num_episodes}

    def stop(self):
        pass
