"""RandomAgent: uniform-random baseline (parity: reference
rllib/algorithms/random_agent.py — the sanity floor every real
algorithm must beat, and a fixture for pipeline tests)."""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from ray_tpu.rllib.env import make_env


@dataclass
class RandomAgentConfig:
    env: Any = "CartPole-v1"
    episodes_per_iter: int = 8
    max_episode_steps: int = 500
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, **kw):
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown RandomAgent option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "RandomAgent":
        return RandomAgent(self)


class RandomAgent:
    def __init__(self, config: RandomAgentConfig):
        self.config = config
        self.env = make_env(config.env)
        self.rng = np.random.default_rng(config.seed)
        self.iteration = 0
        self.total_steps = 0

    def train(self) -> dict:
        cfg = self.config
        t0 = time.time()
        returns = []
        steps = 0
        for ep in range(cfg.episodes_per_iter):
            obs = self.env.reset(seed=cfg.seed + self.iteration * 1000 + ep)
            total = 0.0
            for _ in range(cfg.max_episode_steps):
                a = int(self.rng.integers(self.env.num_actions))
                obs, rew, done, _ = self.env.step(a)
                total += rew
                steps += 1
                if done:
                    break
            returns.append(total)
        self.iteration += 1
        self.total_steps += steps
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(returns)),
            "episodes_this_iter": len(returns),
            "timesteps_this_iter": steps,
            "timesteps_total": self.total_steps,
            "iter_time_s": round(time.time() - t0, 3),
        }

    def compute_single_action(self, obs) -> int:
        return int(self.rng.integers(self.env.num_actions))

    def stop(self):
        pass
