"""Environment API + built-in CartPole.

Parity: reference rllib/env/env_runner.py's gym-style contract. A
dependency-free numpy CartPole (classic Barto-Sutton dynamics) stands in
for gym in tests and examples; any object with the same
reset()/step() surface works.
"""

from __future__ import annotations

import numpy as np


class Env:
    observation_size: int
    num_actions: int

    def reset(self, seed: int | None = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError


class CartPole(Env):
    """Classic cart-pole balancing, matches gym's CartPole-v1 dynamics."""

    observation_size = 4
    num_actions = 2
    max_episode_steps = 500

    def __init__(self):
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.total_mass = self.masspole + self.masscart
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.state = None
        self.steps = 0
        self._rng = np.random.default_rng()

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.state = self._rng.uniform(-0.05, 0.05, size=4)
        self.steps = 0
        return self.state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.polemass_length * theta_dot ** 2 * sintheta
                ) / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta ** 2
                           / self.total_mass))
        xacc = temp - self.polemass_length * thetaacc * costheta / self.total_mass
        x += self.tau * x_dot
        x_dot += self.tau * xacc
        theta += self.tau * theta_dot
        theta_dot += self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.steps += 1
        done = bool(abs(x) > self.x_threshold
                    or abs(theta) > self.theta_threshold
                    or self.steps >= self.max_episode_steps)
        return self.state.astype(np.float32), 1.0, done, {}


class Pendulum(Env):
    """Classic torque-controlled pendulum swing-up, matches gym's
    Pendulum-v1 dynamics. Continuous action in [-2, 2]."""

    observation_size = 3
    num_actions = 0            # continuous
    action_size = 1
    action_low = -2.0
    action_high = 2.0
    max_episode_steps = 200

    def __init__(self):
        self.max_speed = 8.0
        self.dt = 0.05
        self.g = 10.0
        self.m = 1.0
        self.length = 1.0
        self.state = None
        self.steps = 0
        self._rng = np.random.default_rng()

    def _obs(self) -> np.ndarray:
        th, thdot = self.state
        return np.array([np.cos(th), np.sin(th), thdot], np.float32)

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.state = self._rng.uniform([-np.pi, -1.0], [np.pi, 1.0])
        self.steps = 0
        return self._obs()

    def step(self, action):
        th, thdot = self.state
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          self.action_low, self.action_high))
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.g / (2 * self.length) * np.sin(th)
                         + 3.0 / (self.m * self.length ** 2) * u) * self.dt
        thdot = np.clip(thdot, -self.max_speed, self.max_speed)
        th = th + thdot * self.dt
        self.state = np.array([th, thdot])
        self.steps += 1
        done = self.steps >= self.max_episode_steps
        return self._obs(), -cost, done, {}


ENV_REGISTRY = {"CartPole-v1": CartPole, "CartPole": CartPole,
                "Pendulum-v1": Pendulum, "Pendulum": Pendulum}


def make_env(env: str | type) -> Env:
    if isinstance(env, str):
        if env not in ENV_REGISTRY:
            raise ValueError(f"unknown env {env!r}; register it in "
                             "ray_tpu.rllib.env.ENV_REGISTRY")
        return ENV_REGISTRY[env]()
    return env()
