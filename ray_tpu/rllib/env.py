"""Environment API + built-in CartPole.

Parity: reference rllib/env/env_runner.py's gym-style contract. A
dependency-free numpy CartPole (classic Barto-Sutton dynamics) stands in
for gym in tests and examples; any object with the same
reset()/step() surface works.
"""

from __future__ import annotations

import numpy as np


class Env:
    observation_size: int
    num_actions: int

    def reset(self, seed: int | None = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError


class CartPole(Env):
    """Classic cart-pole balancing, matches gym's CartPole-v1 dynamics."""

    observation_size = 4
    num_actions = 2
    max_episode_steps = 500

    def __init__(self):
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.total_mass = self.masspole + self.masscart
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.state = None
        self.steps = 0
        self._rng = np.random.default_rng()

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.state = self._rng.uniform(-0.05, 0.05, size=4)
        self.steps = 0
        return self.state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.polemass_length * theta_dot ** 2 * sintheta
                ) / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta ** 2
                           / self.total_mass))
        xacc = temp - self.polemass_length * thetaacc * costheta / self.total_mass
        x += self.tau * x_dot
        x_dot += self.tau * xacc
        theta += self.tau * theta_dot
        theta_dot += self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.steps += 1
        terminal = bool(abs(x) > self.x_threshold
                        or abs(theta) > self.theta_threshold)
        truncated = self.steps >= self.max_episode_steps
        # info["truncated"]: the episode ended by TIME LIMIT, not failure —
        # off-policy targets should still bootstrap through it (gym's
        # TimeLimit.truncated convention).
        return (self.state.astype(np.float32), 1.0, terminal or truncated,
                {"truncated": truncated and not terminal})


class Pendulum(Env):
    """Classic torque-controlled pendulum swing-up, matches gym's
    Pendulum-v1 dynamics. Continuous action in [-2, 2]."""

    observation_size = 3
    num_actions = 0            # continuous
    action_size = 1
    action_low = -2.0
    action_high = 2.0
    max_episode_steps = 200

    def __init__(self):
        self.max_speed = 8.0
        self.dt = 0.05
        self.g = 10.0
        self.m = 1.0
        self.length = 1.0
        self.state = None
        self.steps = 0
        self._rng = np.random.default_rng()

    def _obs(self) -> np.ndarray:
        th, thdot = self.state
        return np.array([np.cos(th), np.sin(th), thdot], np.float32)

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.state = self._rng.uniform([-np.pi, -1.0], [np.pi, 1.0])
        self.steps = 0
        return self._obs()

    def step(self, action):
        th, thdot = self.state
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          self.action_low, self.action_high))
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.g / (2 * self.length) * np.sin(th)
                         + 3.0 / (self.m * self.length ** 2) * u) * self.dt
        thdot = np.clip(thdot, -self.max_speed, self.max_speed)
        th = th + thdot * self.dt
        self.state = np.array([th, thdot])
        self.steps += 1
        done = self.steps >= self.max_episode_steps
        return self._obs(), -cost, done, {"truncated": done}


ENV_REGISTRY = {"CartPole-v1": CartPole, "CartPole": CartPole,
                "Pendulum-v1": Pendulum, "Pendulum": Pendulum}


def _register_late():  # populated after the classes below are defined
    ENV_REGISTRY.update({
        "VisualCatch-v0": VisualCatch, "VisualCatch": VisualCatch,
        "DualCartPole-v0": DualCartPole, "DualCartPole": DualCartPole,
    })


def make_env(env: str | type) -> Env:
    if isinstance(env, str):
        if env not in ENV_REGISTRY:
            raise ValueError(f"unknown env {env!r}; register it in "
                             "ray_tpu.rllib.env.ENV_REGISTRY")
        return ENV_REGISTRY[env]()
    return env()


class VisualCatch(Env):
    """Atari-style PIXEL control task: a ball falls down a 42x42 frame,
    the agent slides a paddle to catch it (the classic minimal visual-RL
    benchmark). Observations are (42, 42, 1) uint8 frames — exercises the
    full image pipeline (CNN policy under jit, frame normalization)
    without shipping game ROMs. Actions: 0=left 1=stay 2=right."""

    SIZE = 42
    observation_shape = (42, 42, 1)
    observation_size = 42 * 42  # flattened (MLP fallback)
    num_actions = 3

    def __init__(self):
        self.rng = np.random.default_rng(0)
        self.reset()

    def _frame(self) -> np.ndarray:
        f = np.zeros((self.SIZE, self.SIZE, 1), np.uint8)
        f[self.ball_y, self.ball_x, 0] = 255
        x0 = max(0, self.paddle_x - 2)
        x1 = min(self.SIZE, self.paddle_x + 3)
        f[self.SIZE - 1, x0:x1, 0] = 255
        return f

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.ball_x = int(self.rng.integers(0, self.SIZE))
        self.ball_y = 0
        self.paddle_x = self.SIZE // 2
        return self._frame()

    def step(self, action: int):
        self.paddle_x = int(np.clip(self.paddle_x + (int(action) - 1), 2,
                                    self.SIZE - 3))
        self.ball_y += 1
        done = self.ball_y >= self.SIZE - 1
        reward = 0.0
        if done:
            reward = 1.0 if abs(self.ball_x - self.paddle_x) <= 2 else -1.0
        return self._frame(), reward, done, {}


class MultiAgentEnv:
    """Multi-agent env interface (parity: reference rllib MultiAgentEnv):
    reset() -> {agent_id: obs}; step({agent_id: action}) ->
    (obs_dict, reward_dict, done_dict incl. '__all__', info_dict)."""

    agent_ids: tuple = ()

    def reset(self, seed: int | None = None) -> dict:
        raise NotImplementedError

    def step(self, actions: dict):
        raise NotImplementedError


class DualCartPole(MultiAgentEnv):
    """Two independent CartPole agents in one env — the minimal
    multi-agent scaffold (reference: rllib examples' multi-agent
    cartpole). Episode ends when BOTH poles have fallen."""

    agent_ids = ("agent_0", "agent_1")
    observation_size = 4
    num_actions = 2

    def __init__(self):
        self.envs = {a: CartPole() for a in self.agent_ids}
        self.done = {a: False for a in self.agent_ids}

    def reset(self, seed: int | None = None) -> dict:
        self.done = {a: False for a in self.agent_ids}
        return {a: e.reset(None if seed is None else seed + i)
                for i, (a, e) in enumerate(self.envs.items())}

    def step(self, actions: dict):
        obs, rew, done = {}, {}, {}
        for a, e in self.envs.items():
            if self.done[a]:
                continue
            o, r, d, _ = e.step(actions[a])
            obs[a], rew[a], done[a] = o, r, d
            self.done[a] = d
        done["__all__"] = all(self.done.values())
        return obs, rew, done, {}


_register_late()
