"""AlphaZero: self-play MCTS + policy/value network.

Parity: reference rllib/algorithms/alpha_zero/ (PUCT tree search guided
by a policy/value net, Dirichlet root noise, visit-count targets,
self-play replay; the reference ships it with board-game envs). The
search runs on CPU self-play actors with a numpy forward pass; the
policy-CE + value-MSE update is one jitted JAX program on the attached
accelerator. Ships TicTacToe as the built-in two-player zero-sum env.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

import ray_tpu


class TicTacToe:
    """3x3 zero-sum board. State is always encoded from the perspective
    of the player to move: +1 own stones, -1 opponent's."""

    num_actions = 9
    obs_size = 9

    @staticmethod
    def initial() -> np.ndarray:
        return np.zeros(9, np.float32)

    @staticmethod
    def legal(board: np.ndarray) -> np.ndarray:
        return board == 0

    @staticmethod
    def play(board: np.ndarray, action: int) -> np.ndarray:
        """Apply the to-move player's stone, then flip perspective so
        the returned board is again to-move-relative."""
        nxt = board.copy()
        nxt[action] = 1.0
        return -nxt

    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    @classmethod
    def outcome(cls, board: np.ndarray) -> float | None:
        """Terminal value FOR THE PLAYER TO MOVE at `board` (-1 = the
        previous move won), None if the game continues."""
        for a, b, c in cls._LINES:
            s = board[a] + board[b] + board[c]
            if s == 3:
                return 1.0
            if s == -3:
                return -1.0
        if not (board == 0).any():
            return 0.0
        return None


def init_az_params(obs_size: int = 9, num_actions: int = 9,
                   hidden: int = 64, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o))
                      / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    return {"h1": dense(obs_size, hidden), "h2": dense(hidden, hidden),
            "pi": dense(hidden, num_actions), "v": dense(hidden, 1)}


def numpy_forward(params: dict, board: np.ndarray):
    h = np.tanh(board @ params["h1"]["w"] + params["h1"]["b"])
    h = np.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = float(np.tanh(h @ params["v"]["w"] + params["v"]["b"])[0])
    e = np.exp(logits - logits.max())
    return e / e.sum(), value


class MCTS:
    """PUCT search (AlphaZero eq.: a* = argmax Q + c_puct P sqrt(N)/
    (1+n)); values backed up with sign flips at each ply."""

    def __init__(self, params: dict, num_simulations: int = 48,
                 c_puct: float = 1.5, dirichlet_alpha: float = 0.6,
                 noise_frac: float = 0.25, rng=None):
        self.params = params
        self.num_simulations = num_simulations
        self.c_puct = c_puct
        self.dirichlet_alpha = dirichlet_alpha
        self.noise_frac = noise_frac
        self.rng = rng or np.random.default_rng()
        # Tree keyed by board bytes: stats per node.
        self.P: dict[bytes, np.ndarray] = {}
        self.N: dict[bytes, np.ndarray] = {}
        self.W: dict[bytes, np.ndarray] = {}

    def policy(self, board: np.ndarray, temperature: float = 1.0
               ) -> np.ndarray:
        """Visit-count distribution after running the simulations."""
        key = board.tobytes()
        if key not in self.P:
            self._simulate(board.copy())  # expand the root
        if self.noise_frac > 0:
            # Dirichlet noise mixed into the ROOT priors once per
            # search, steering every simulation (AlphaZero's self-play
            # exploration; interior nodes stay noise-free).
            legal = TicTacToe.legal(board)
            noise = self.rng.dirichlet(
                [self.dirichlet_alpha] * int(legal.sum()))
            full = np.zeros(9, np.float32)
            full[legal] = noise
            self.P[key] = ((1 - self.noise_frac) * self.P[key]
                           + self.noise_frac * full).astype(np.float32)
        for _ in range(self.num_simulations):
            self._simulate(board.copy())
        n = self.N[key] * TicTacToe.legal(board)
        if temperature == 0:
            pi = np.zeros_like(n)
            pi[int(np.argmax(n))] = 1.0
            return pi
        n = n ** (1.0 / temperature)
        return (n / n.sum()).astype(np.float32)

    def _simulate(self, board: np.ndarray) -> float:
        """One rollout to a leaf; returns the value from the POV of the
        player to move at `board`."""
        outcome = TicTacToe.outcome(board)
        if outcome is not None:
            return outcome
        key = board.tobytes()
        legal = TicTacToe.legal(board)
        if key not in self.P:
            # Leaf: expand with net priors, return net value.
            priors, value = numpy_forward(self.params, board)
            priors = priors * legal
            s = priors.sum()
            priors = priors / s if s > 0 else legal / legal.sum()
            self.P[key] = priors.astype(np.float32)
            self.N[key] = np.zeros(9, np.float32)
            self.W[key] = np.zeros(9, np.float32)
            return value
        p = self.P[key]
        n_total = self.N[key].sum()
        q = np.where(self.N[key] > 0,
                     self.W[key] / np.maximum(self.N[key], 1), 0.0)
        u = self.c_puct * p * math.sqrt(n_total + 1e-8) / (1 + self.N[key])
        scores = np.where(legal, q + u, -np.inf)
        action = int(np.argmax(scores))
        # Child is from the opponent's perspective: flip the value.
        value = -self._simulate(TicTacToe.play(board, action))
        self.N[key][action] += 1
        self.W[key][action] += value
        return value


@ray_tpu.remote
class SelfPlayWorker:
    """CPU self-play actor: full games of MCTS vs itself, emitting
    (board, visit-count pi, final z from that board's POV)."""

    def __init__(self, worker_index: int, num_simulations: int):
        self.rng = np.random.default_rng(6000 + worker_index)
        self.num_simulations = num_simulations

    def play_games(self, params: dict, num_games: int) -> dict:
        boards, pis, zs = [], [], []
        for _ in range(num_games):
            tree = MCTS(params, self.num_simulations, rng=self.rng)
            board = TicTacToe.initial()
            traj = []
            ply = 0
            while True:
                temp = 1.0 if ply < 4 else 0.25
                pi = tree.policy(board, temperature=temp)
                traj.append((board.copy(), pi))
                action = int(self.rng.choice(9, p=pi))
                board = TicTacToe.play(board, action)
                ply += 1
                outcome = TicTacToe.outcome(board)
                if outcome is not None:
                    # outcome is from the NEW to-move player's POV; walk
                    # back flipping signs.
                    z = outcome
                    for b, p in reversed(traj):
                        z = -z
                        boards.append(b)
                        pis.append(p)
                        zs.append(z)
                    break
        return {"boards": np.asarray(boards, np.float32),
                "pis": np.asarray(pis, np.float32),
                "zs": np.asarray(zs, np.float32),
                "games": num_games}


@dataclass
class AlphaZeroConfig:
    """Parity: rllib AlphaZeroConfig (mcts_config + sgd settings)."""

    num_rollout_workers: int = 2
    games_per_iteration: int = 8
    num_simulations: int = 48
    buffer_capacity: int = 8_000
    train_batch_size: int = 128
    num_sgd_iter: int = 24
    lr: float = 3e-3
    hidden_size: int = 64
    weight_decay: float = 1e-4
    seed: int = 0

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown AlphaZero option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "AlphaZero":
        return AlphaZero(self)


class AlphaZero:
    """Algorithm driver (parity: Algorithm.step / AlphaZero
    training_step): parallel self-play -> replay -> jitted update."""

    def __init__(self, config: AlphaZeroConfig):
        self.config = config
        self.params = init_az_params(hidden=config.hidden_size,
                                     seed=config.seed)
        cap = config.buffer_capacity
        self.boards = np.zeros((cap, 9), np.float32)
        self.pis = np.zeros((cap, 9), np.float32)
        self.zs = np.zeros(cap, np.float32)
        self.pos = 0
        self.size = 0
        self.rng = np.random.default_rng(config.seed)
        self.workers = [
            SelfPlayWorker.remote(i, config.num_simulations)
            for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_games = 0

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adamw(cfg.lr, weight_decay=cfg.weight_decay)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def forward(params, boards):
            h = jnp.tanh(boards @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            logits = h @ params["pi"]["w"] + params["pi"]["b"]
            value = jnp.tanh(h @ params["v"]["w"] + params["v"]["b"])[:, 0]
            return logits, value

        def loss_fn(params, batch):
            logits, value = forward(params, batch["boards"])
            ce = -(batch["pis"]
                   * jax.nn.log_softmax(logits, -1)).sum(-1).mean()
            mse = jnp.mean((value - batch["zs"]) ** 2)
            return ce + mse

        @jax.jit
        def update(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = update

    def train(self) -> dict:
        cfg = self.config
        if self._update is None:
            self._build_update()
        per = max(1, cfg.games_per_iteration // len(self.workers))
        rollout_params = {k: {kk: np.asarray(vv) for kk, vv in v.items()}
                          for k, v in self.params.items()}
        outs = ray_tpu.get([w.play_games.remote(rollout_params, per)
                            for w in self.workers])
        for out in outs:
            n = len(out["boards"])
            idx = (self.pos + np.arange(n)) % cfg.buffer_capacity
            self.boards[idx] = out["boards"]
            self.pis[idx] = out["pis"]
            self.zs[idx] = out["zs"]
            self.pos = int((self.pos + n) % cfg.buffer_capacity)
            self.size = int(min(self.size + n, cfg.buffer_capacity))
            self.total_games += out["games"]
        losses = []
        if self.size >= cfg.train_batch_size:
            for _ in range(cfg.num_sgd_iter):
                idx = self.rng.integers(0, self.size,
                                        cfg.train_batch_size)
                batch = {"boards": self.boards[idx], "pis": self.pis[idx],
                         "zs": self.zs[idx]}
                self.params, self._opt_state, loss = self._update(
                    self.params, self._opt_state, batch)
                losses.append(float(loss))
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "games_played": self.total_games,
                "loss": float(np.mean(losses)) if losses else None}

    def eval_vs_random(self, num_games: int = 40,
                       num_simulations: int | None = None) -> float:
        """Fraction of non-lost games (win=1, draw=0.5) playing half the
        games as each side against a uniform-random opponent."""
        sims = num_simulations or self.config.num_simulations
        params = {k: {kk: np.asarray(vv) for kk, vv in v.items()}
                  for k, v in self.params.items()}
        rng = np.random.default_rng(123)
        score = 0.0
        for g in range(num_games):
            az_to_move = (g % 2 == 0)
            board = TicTacToe.initial()
            tree = MCTS(params, sims, noise_frac=0.0, rng=rng)
            while True:
                if az_to_move:
                    pi = tree.policy(board, temperature=0.0)
                    action = int(np.argmax(pi))
                else:
                    legal = np.flatnonzero(TicTacToe.legal(board))
                    action = int(rng.choice(legal))
                board = TicTacToe.play(board, action)
                outcome = TicTacToe.outcome(board)
                mover_was_az = az_to_move
                az_to_move = not az_to_move
                if outcome is not None:
                    # outcome is for the player NOW to move; -outcome is
                    # the mover's result.
                    res = -outcome
                    if res > 0:
                        score += 1.0 if mover_was_az else 0.0
                    elif res == 0:
                        score += 0.5
                    break
        return score / num_games
