"""TD3 / DDPG: deterministic-policy continuous control.

Parity: reference rllib/algorithms/td3/ and /ddpg/ rebuilt on the
rollout/learner split — numpy deterministic-policy rollout actors with
Gaussian exploration noise feed a replay buffer; the learner runs the
(twin-)Q Bellman update and delayed deterministic policy-gradient step
as ONE jitted jax program. DDPG is TD3 with twin_q=False,
policy_delay=1 and no target-policy smoothing — one implementation,
two algorithm names, the reference's own lineage.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.dqn import ReplayBuffer
from ray_tpu.rllib.env import make_env


def init_td3_params(obs_size: int, act_size: int, hidden: int = 64,
                    seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o)) / np.sqrt(i)
                      ).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    def q_net():
        return {"h1": dense(obs_size + act_size, hidden),
                "h2": dense(hidden, hidden), "out": dense(hidden, 1)}

    return {
        "pi": {"h1": dense(obs_size, hidden), "h2": dense(hidden, hidden),
               "mu": dense(hidden, act_size)},
        "q1": q_net(),
        "q2": q_net(),
    }


def numpy_actor(params: dict, obs: np.ndarray) -> np.ndarray:
    pi = params["pi"]
    h = np.tanh(obs @ pi["h1"]["w"] + pi["h1"]["b"])
    h = np.tanh(h @ pi["h2"]["w"] + pi["h2"]["b"])
    return np.tanh(h @ pi["mu"]["w"] + pi["mu"]["b"])


@ray_tpu.remote
class TD3RolloutWorker:
    """CPU sampling actor: deterministic policy + exploration noise."""

    def __init__(self, env_spec, worker_index: int, explore_noise: float):
        self.env = make_env(env_spec)
        self.index = worker_index
        self.noise = explore_noise
        self.rng = np.random.default_rng(3000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)
        self.scale = (self.env.action_high - self.env.action_low) / 2.0
        self.mid = (self.env.action_high + self.env.action_low) / 2.0

    def sample(self, params: dict, num_steps: int,
               random_policy: bool = False) -> dict:
        obs_b, act_b, rew_b, next_b, done_b = [], [], [], [], []
        episode_returns, ep_ret = [], 0.0
        for _ in range(num_steps):
            if random_policy:
                a = self.rng.uniform(-1.0, 1.0, self.env.action_size)
            else:
                a = numpy_actor(params, self.obs[None, :])[0]
                a = np.clip(a + self.noise
                            * self.rng.standard_normal(a.shape), -1.0, 1.0)
            next_obs, reward, done, info = self.env.step(
                self.mid + self.scale * a)
            obs_b.append(self.obs)
            act_b.append(a.astype(np.float32))
            rew_b.append(reward)
            next_b.append(next_obs)
            # True terminals block bootstrapping; time-limit truncations
            # (info["truncated"]) still bootstrap through the cut.
            done_b.append(bool(done) and not info.get("truncated", False))
            ep_ret += reward
            if done:
                episode_returns.append(ep_ret)
                ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {
            "obs": np.asarray(obs_b, np.float32),
            "actions": np.asarray(act_b, np.float32),
            "rewards": np.asarray(rew_b, np.float32),
            "next_obs": np.asarray(next_b, np.float32),
            "dones": np.asarray(done_b, np.float32),
            "episode_returns": episode_returns,
        }


@dataclass
class TD3Config:
    """Parity: rllib TD3Config fluent-config object."""

    env: Any = "Pendulum-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 200
    train_batch_size: int = 256
    num_updates_per_iter: int = 64
    replay_buffer_capacity: int = 100_000
    learning_starts: int = 500
    gamma: float = 0.99
    tau: float = 0.005
    lr: float = 3e-4
    explore_noise: float = 0.1
    # TD3 tricks; DDPGConfig flips them off.
    twin_q: bool = True
    policy_delay: int = 2
    target_noise: float = 0.2
    target_noise_clip: float = 0.5
    hidden_size: int = 64
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown TD3/DDPG option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "TD3":
        return TD3(self)


@dataclass
class DDPGConfig(TD3Config):
    """DDPG = TD3 minus the three addressing tricks."""

    twin_q: bool = False
    policy_delay: int = 1
    target_noise: float = 0.0

    def build(self) -> "TD3":
        return TD3(self)


class TD3:
    """Algorithm driver (parity: Algorithm.step for TD3/DDPG)."""

    def __init__(self, config: TD3Config):
        self.config = config
        probe = make_env(config.env)
        if getattr(probe, "action_size", 0) < 1:
            raise ValueError("TD3/DDPG needs a continuous-action env")
        self.obs_size = probe.observation_size
        self.act_size = probe.action_size
        self._action_mid = (probe.action_high + probe.action_low) / 2.0
        self._action_scale = (probe.action_high - probe.action_low) / 2.0
        self.params = init_td3_params(self.obs_size, self.act_size,
                                      config.hidden_size, config.seed)
        import copy

        self.target = copy.deepcopy(self.params)
        self.buffer = ReplayBuffer(config.replay_buffer_capacity,
                                   self.obs_size, seed=config.seed,
                                   action_shape=(self.act_size,),
                                   action_dtype=np.float32)
        self.workers = [
            TD3RolloutWorker.remote(config.env, i, config.explore_noise)
            for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0
        self._update_calls = 0

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def mlp(net, x):
            h = jnp.tanh(x @ net["h1"]["w"] + net["h1"]["b"])
            return jnp.tanh(h @ net["h2"]["w"] + net["h2"]["b"])

        def q_val(net, obs, act):
            h = mlp(net, jnp.concatenate([obs, act], -1))
            return (h @ net["out"]["w"] + net["out"]["b"])[..., 0]

        def actor(pi, obs):
            return jnp.tanh(mlp(pi, obs) @ pi["mu"]["w"] + pi["mu"]["b"])

        def update(params, target, opt_state, batch, key, do_policy):
            # Target action with clipped smoothing noise (TD3 trick #3).
            next_a = actor(target["pi"], batch["next_obs"])
            if cfg.target_noise > 0:
                noise = jnp.clip(
                    cfg.target_noise * jax.random.normal(key, next_a.shape),
                    -cfg.target_noise_clip, cfg.target_noise_clip)
                next_a = jnp.clip(next_a + noise, -1.0, 1.0)
            tq1 = q_val(target["q1"], batch["next_obs"], next_a)
            if cfg.twin_q:  # TD3 trick #1: clipped double-Q
                tq = jnp.minimum(tq1, q_val(target["q2"],
                                            batch["next_obs"], next_a))
            else:
                tq = tq1
            y = jax.lax.stop_gradient(
                batch["rewards"] + cfg.gamma * (1 - batch["dones"]) * tq)

            def critic_loss(p):
                l = ((q_val(p["q1"], batch["obs"], batch["actions"]) - y)
                     ** 2).mean()
                if cfg.twin_q:
                    l = l + ((q_val(p["q2"], batch["obs"], batch["actions"])
                              - y) ** 2).mean()
                return l

            def actor_loss(p):
                a = actor(p["pi"], batch["obs"])
                return -q_val(jax.lax.stop_gradient(p["q1"]),
                              batch["obs"], a).mean()

            closs, cgrads = jax.value_and_grad(critic_loss)(params)
            aloss, agrads = jax.value_and_grad(actor_loss)(params)

            # Delayed policy update (TD3 trick #2): actor + targets move
            # only every policy_delay critic steps — lax.cond keeps one
            # compiled program.
            def with_actor(_):
                return {"pi": agrads["pi"], "q1": cgrads["q1"],
                        "q2": cgrads["q2"]}

            def critic_only(_):
                zero_pi = jax.tree_util.tree_map(jnp.zeros_like,
                                                 agrads["pi"])
                return {"pi": zero_pi, "q1": cgrads["q1"],
                        "q2": cgrads["q2"]}

            grads = jax.lax.cond(do_policy, with_actor, critic_only, None)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)

            def polyak(_):
                return jax.tree_util.tree_map(
                    lambda t, p: (1 - cfg.tau) * t + cfg.tau * p,
                    target, params)

            target = jax.lax.cond(do_policy, polyak, lambda _: target, None)
            return params, target, opt_state, {
                "critic_loss": closs, "actor_loss": aloss}

        self._update = jax.jit(update)

    def train(self) -> dict:
        import jax

        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        host = jax.tree_util.tree_map(np.asarray, self.params)
        random_phase = self.total_steps < cfg.learning_starts
        batches = ray_tpu.get(
            [w.sample.remote(host, cfg.rollout_fragment_length, random_phase)
             for w in self.workers], timeout=600)
        episode_returns = []
        for b in batches:
            episode_returns += b.pop("episode_returns")
            self.buffer.add_batch(b)
            self.total_steps += len(b["obs"])
        sample_time = time.time() - t0

        t1 = time.time()
        metrics = {}
        if self.total_steps >= cfg.learning_starts:
            for i in range(cfg.num_updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                key = jax.random.PRNGKey(cfg.seed * 99991
                                         + self.iteration * 613 + i)
                self._update_calls += 1
                do_policy = (self._update_calls % cfg.policy_delay) == 0
                self.params, self.target, self._opt_state, metrics = \
                    self._update(self.params, self.target, self._opt_state,
                                 batch, key, do_policy)
        learn_time = time.time() - t1
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else float("nan"),
            "episodes_this_iter": len(episode_returns),
            "timesteps_total": self.total_steps,
            "sample_time_s": round(sample_time, 3),
            "learn_time_s": round(learn_time, 3),
            **{k: float(v) for k, v in metrics.items()},
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def get_policy_params(self) -> dict:
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def compute_single_action(self, obs) -> np.ndarray:
        a = numpy_actor(self.get_policy_params(), obs[None, :])[0]
        return self._action_mid + self._action_scale * a


DDPG = TD3  # algorithm alias: construct via DDPGConfig
