"""Rainbow: DQN with the six classic extensions combined.

Parity: reference rllib/algorithms/dqn/ with the Rainbow options on
(DQNConfig: num_atoms>1 -> distributional C51, dueling=True,
noisy=True, n_step>1, prioritized replay; double-Q always) — the
reference exposes Rainbow as a DQN configuration, this module gives it
the dedicated driver the paper describes. JAX-native: the categorical
projection, dueling aggregation, and factorized noisy layers are one
jitted update on the attached accelerator; sampling stays on CPU
rollout actors like dqn.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.replay import PrioritizedReplayBuffer
from ray_tpu.rllib.utils import tree_copy as _copy_tree
from ray_tpu.rllib.utils import tree_numpy as _to_numpy


def init_rainbow_params(obs_size: int, num_actions: int, num_atoms: int,
                        hidden: int = 64, seed: int = 0) -> dict:
    """Dueling trunk: shared hidden -> (value stream, advantage stream),
    each emitting per-atom logits; final heads are factorized-noisy
    (mu/sigma pairs, NoisyNet): params carry both."""
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o))
                      / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    def noisy(i, o):
        bound = 1.0 / np.sqrt(i)
        return {
            "w_mu": rng.uniform(-bound, bound, (i, o)).astype(np.float32),
            "w_sigma": np.full((i, o), 0.5 * bound, np.float32),
            "b_mu": rng.uniform(-bound, bound, o).astype(np.float32),
            "b_sigma": np.full(o, 0.5 * bound, np.float32),
        }

    return {"h1": dense(obs_size, hidden), "h2": dense(hidden, hidden),
            "value": noisy(hidden, num_atoms),
            "adv": noisy(hidden, num_actions * num_atoms)}


def _noisy_apply(layer, x, eps_in, eps_out, jnp):
    """Factorized Gaussian noise: eps_w = f(eps_in) f(eps_out)^T,
    f(x) = sign(x) sqrt(|x|) (NoisyNet eq. 10-11)."""
    f = lambda v: jnp.sign(v) * jnp.sqrt(jnp.abs(v))  # noqa: E731
    fi, fo = f(eps_in), f(eps_out)
    w = layer["w_mu"] + layer["w_sigma"] * jnp.outer(fi, fo)
    b = layer["b_mu"] + layer["b_sigma"] * fo
    return x @ w + b


def rainbow_logits(params, obs, eps, num_actions, num_atoms, jnp):
    """Per-action atom logits with dueling aggregation:
    logits(s,a) = value(s) + adv(s,a) - mean_a adv(s,a)."""
    h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
    h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    value = _noisy_apply(params["value"], h, eps["v_in"], eps["v_out"],
                         jnp)                       # [B, atoms]
    adv = _noisy_apply(params["adv"], h, eps["a_in"], eps["a_out"], jnp)
    adv = adv.reshape(-1, num_actions, num_atoms)   # [B, A, atoms]
    return (value[:, None, :] + adv
            - adv.mean(axis=1, keepdims=True))      # [B, A, atoms]


def numpy_rainbow_q(params: dict, obs: np.ndarray, z: np.ndarray,
                    num_actions: int) -> np.ndarray:
    """Greedy-action Q for CPU rollouts: noise OFF (mu weights only),
    Q(s,a) = sum_i z_i p_i(s,a)."""
    num_atoms = len(z)
    h = np.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
    h = np.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    value = h @ params["value"]["w_mu"] + params["value"]["b_mu"]
    adv = (h @ params["adv"]["w_mu"] + params["adv"]["b_mu"]).reshape(
        -1, num_actions, num_atoms)
    logits = value[:, None, :] + adv - adv.mean(axis=1, keepdims=True)
    e = np.exp(logits - logits.max(axis=-1, keepdims=True))
    probs = e / e.sum(axis=-1, keepdims=True)
    return (probs * z).sum(axis=-1)


@ray_tpu.remote
class RainbowRolloutWorker:
    """CPU sampler (parity: rollout_worker.py). Exploration comes from
    the noisy heads, not epsilon — rollouts act greedily on the
    noise-free (mu) distributionally-expected Q, with a tiny epsilon
    floor against early determinism."""

    def __init__(self, env_spec, worker_index: int, z):
        self.env = make_env(env_spec)
        self.index = worker_index
        self.z = np.asarray(z, np.float32)
        self.rng = np.random.default_rng(3000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)
        self.ep_ret = 0.0

    def sample(self, params: dict, num_steps: int, epsilon: float) -> dict:
        obs_b, act_b, rew_b, next_b, done_b = [], [], [], [], []
        reset_b = []
        episode_returns = []
        for _ in range(num_steps):
            if self.rng.random() < epsilon:
                action = int(self.rng.integers(self.env.num_actions))
            else:
                q = numpy_rainbow_q(params, self.obs[None, :], self.z,
                                    self.env.num_actions)[0]
                action = int(np.argmax(q))
            next_obs, reward, done, info = self.env.step(action)
            obs_b.append(self.obs)
            act_b.append(action)
            rew_b.append(reward)
            next_b.append(next_obs)
            # Two signals: "dones" is the BOOTSTRAP mask — time-limit
            # cuts (info["truncated"]) still bootstrap through the cut
            # (gym TimeLimit convention, env.py) — while "resets" marks
            # where the episode actually ended (n-step folding must not
            # run across a reset into the next episode).
            done_b.append(float(bool(done)
                                and not info.get("truncated", False)))
            reset_b.append(float(done))
            self.ep_ret += reward
            if done:
                episode_returns.append(self.ep_ret)
                self.ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {"obs": np.asarray(obs_b, np.float32),
                "actions": np.asarray(act_b, np.int32),
                "rewards": np.asarray(rew_b, np.float32),
                "next_obs": np.asarray(next_b, np.float32),
                "dones": np.asarray(done_b, np.float32),
                "resets": np.asarray(reset_b, np.float32),
                "episode_returns": episode_returns}


class _NStepBuffer(PrioritizedReplayBuffer):
    """Prioritized buffer fed n-step transitions: the rollout batch is
    rewritten so reward_t = sum_{k<n} gamma^k r_{t+k} and next_obs_t =
    obs_{t+n} (truncated at dones; reference: n_step folding in the
    DQN sample pipeline)."""

    def add_nstep(self, batch: dict, n: int, gamma: float) -> None:
        obs = batch["obs"]
        size = len(obs)
        rewards = np.zeros(size, np.float32)
        next_obs = np.array(batch["next_obs"])
        dones = np.zeros(size, np.float32)
        keep = np.ones(size, bool)
        resets = batch.get("resets", batch["dones"])
        for t in range(size):
            acc, discount = 0.0, 1.0
            folded = 0
            for k in range(n):
                j = t + k
                if j >= size:
                    break
                acc += discount * batch["rewards"][j]
                discount *= gamma
                folded += 1
                next_obs[t] = batch["next_obs"][j]
                if resets[j]:
                    # Episode boundary: never fold into the next episode.
                    # The bootstrap mask comes from the STOPPING step (a
                    # time-limit cut keeps bootstrapping, dones[j]=0).
                    dones[t] = batch["dones"][j]
                    break
            rewards[t] = acc
            # The update applies gamma^n to the bootstrap uniformly, so
            # any window cut short (fragment boundary, or a time-limit
            # cut that still bootstraps) would get the wrong discount —
            # drop those few transitions instead of biasing them.
            # Terminal stops are exact: the bootstrap term is zeroed.
            if folded < n and not dones[t]:
                keep[t] = False
        self.add_batch({"obs": obs[keep],
                        "actions": batch["actions"][keep],
                        "rewards": rewards[keep],
                        "next_obs": next_obs[keep],
                        "dones": dones[keep]})


@dataclass
class RainbowConfig:
    """Parity: rllib DQNConfig with the Rainbow switches on."""

    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 256
    buffer_capacity: int = 50_000
    train_batch_size: int = 128
    num_sgd_iter: int = 32
    gamma: float = 0.99
    lr: float = 1e-3
    hidden_size: int = 64
    target_network_update_freq: int = 4
    num_atoms: int = 51
    v_min: float = 0.0
    v_max: float = 200.0
    n_step: int = 3
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown Rainbow option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "Rainbow":
        return Rainbow(self)


class Rainbow:
    """Algorithm driver (parity: Algorithm.step with Rainbow's DQN
    training_step): noisy-net exploration (no epsilon schedule),
    distributional double-Q target projection, prioritized sampling with
    IS weights, priorities updated from the categorical TD error."""

    def __init__(self, config: RainbowConfig):
        self.config = config
        probe = make_env(config.env)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.z = np.linspace(config.v_min, config.v_max,
                             config.num_atoms).astype(np.float32)
        self.params = init_rainbow_params(
            self.obs_size, self.num_actions, config.num_atoms,
            config.hidden_size, config.seed)
        self.target_params = _copy_tree(self.params)
        self.buffer = _NStepBuffer(config.buffer_capacity, self.obs_size,
                                   config.seed)
        self.workers = [RainbowRolloutWorker.remote(config.env, i, self.z)
                        for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        atoms, num_actions = cfg.num_atoms, self.num_actions
        z = jnp.asarray(self.z)
        dz = (cfg.v_max - cfg.v_min) / (atoms - 1)
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def sample_eps(key):
            kv1, kv2, ka1, ka2 = jax.random.split(key, 4)
            return {
                "v_in": jax.random.normal(kv1, (cfg.hidden_size,)),
                "v_out": jax.random.normal(kv2, (atoms,)),
                "a_in": jax.random.normal(ka1, (cfg.hidden_size,)),
                "a_out": jax.random.normal(ka2, (num_actions * atoms,)),
            }

        def loss_fn(params, target_params, batch, key):
            k1, k2, k3 = jax.random.split(key, 3)
            logits = rainbow_logits(params, batch["obs"], sample_eps(k1),
                                    num_actions, atoms, jnp)
            logits_a = jnp.take_along_axis(
                logits, batch["actions"][:, None, None].astype(jnp.int32)
                .repeat(atoms, axis=2), axis=1)[:, 0]      # [B, atoms]
            # Double-Q: online net (fresh noise) picks a*, target net
            # evaluates its distribution.
            next_online = rainbow_logits(params, batch["next_obs"],
                                         sample_eps(k2), num_actions,
                                         atoms, jnp)
            next_q = (jax.nn.softmax(next_online, -1) * z).sum(-1)
            a_star = jnp.argmax(next_q, axis=1)
            next_target = rainbow_logits(target_params, batch["next_obs"],
                                         sample_eps(k3), num_actions,
                                         atoms, jnp)
            p_next = jax.nn.softmax(jnp.take_along_axis(
                next_target, a_star[:, None, None].repeat(atoms, axis=2),
                axis=1)[:, 0], -1)                         # [B, atoms]
            # Categorical projection (C51 eq. 7) of r + gamma^n z onto z.
            gamma_n = cfg.gamma ** cfg.n_step
            tz = jnp.clip(batch["rewards"][:, None] + gamma_n
                          * (1.0 - batch["dones"][:, None]) * z[None, :],
                          cfg.v_min, cfg.v_max)
            b = (tz - cfg.v_min) / dz
            lo = jnp.floor(b).astype(jnp.int32)
            hi = jnp.ceil(b).astype(jnp.int32)
            # lo==hi (b integral) would drop mass: give it all to lo.
            frac_hi = b - lo
            frac_lo = 1.0 - frac_hi
            m = jnp.zeros_like(p_next)
            bidx = jnp.arange(p_next.shape[0])[:, None].repeat(atoms, 1)
            m = m.at[bidx, lo].add(p_next * frac_lo)
            m = m.at[bidx, jnp.minimum(hi, atoms - 1)].add(
                p_next * frac_hi)
            ce = -(m * jax.nn.log_softmax(logits_a, -1)).sum(-1)  # [B]
            loss = (batch["weights"] * ce).mean()
            return loss, ce

        @jax.jit
        def update(params, target_params, opt_state, batch, key):
            (loss, ce), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch, key)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, \
                loss, ce

        self._update = update
        self._key = jax.random.PRNGKey(cfg.seed)

    def train(self) -> dict:
        """One iteration: parallel rollouts -> n-step prioritized buffer
        -> num_sgd_iter jitted distributional updates -> priority sync."""
        import jax

        cfg = self.config
        if self._update is None:
            self._build_update()
        rollout_params = _to_numpy(self.params)
        outs = ray_tpu.get([
            w.sample.remote(rollout_params, cfg.rollout_fragment_length,
                            0.02)  # tiny epsilon floor; noise explores
            for w in self.workers])
        returns = []
        for out in outs:
            self.buffer.add_nstep(out, cfg.n_step, cfg.gamma)
            returns += out["episode_returns"]
            self.total_steps += len(out["obs"])
        losses = []
        if self.buffer.size >= cfg.train_batch_size:
            for _ in range(cfg.num_sgd_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                jb = {k: v for k, v in batch.items() if k != "indices"}
                self._key, sub = jax.random.split(self._key)
                self.params, self._opt_state, loss, ce = self._update(
                    self.params, self.target_params, self._opt_state,
                    jb, sub)
                self.buffer.update_priorities(batch["indices"],
                                              np.asarray(ce))
                losses.append(float(loss))
        self.iteration += 1
        if self.iteration % cfg.target_network_update_freq == 0:
            self.target_params = _copy_tree(_to_numpy(self.params))
        return {"training_iteration": self.iteration,
                "episode_reward_mean":
                    float(np.mean(returns)) if returns else float("nan"),
                "num_env_steps_sampled": self.total_steps,
                "loss": float(np.mean(losses)) if losses else None}




