"""Offline RL: behavior cloning (BC) and MARWIL from logged experience.

Parity: reference rllib/offline/ (json_reader.py, the BC and MARWIL
algorithms under rllib/algorithms/{bc,marwil}/). Experience is consumed
from JSONL sample files or a ray_tpu.data Dataset; no environment
interaction is needed to train (an env is only used for optional
evaluation rollouts).

MARWIL (Wang et al. 2018) generalizes BC: actions are weighted by
exp(beta * advantage); beta=0 reduces to plain BC (reference:
rllib/algorithms/marwil/marwil.py — BC subclasses MARWIL with beta=0).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import init_policy_params, numpy_forward


def write_offline_json(path: str, batches: list[dict]) -> None:
    """Log sample batches to a JSONL file readable by JsonReader
    (reference: rllib/offline/json_writer.py)."""
    with open(path, "w") as f:
        for b in batches:
            f.write(json.dumps({
                "obs": np.asarray(b["obs"], np.float32).tolist(),
                "actions": np.asarray(b["actions"], np.int32).tolist(),
                "rewards": np.asarray(b.get(
                    "rewards", np.zeros(len(b["obs"]))), np.float32).tolist(),
                "dones": np.asarray(b.get(
                    "dones", np.zeros(len(b["obs"]))), np.float32).tolist(),
            }) + "\n")


class JsonReader:
    """Reads logged experience (reference: rllib/offline/json_reader.py)."""

    def __init__(self, path: str):
        self.paths = ([os.path.join(path, p) for p in sorted(os.listdir(path))]
                      if os.path.isdir(path) else [path])

    def read_all(self) -> dict:
        fields = {"obs": [], "actions": [], "rewards": [], "dones": []}
        for p in self.paths:
            with open(p) as f:
                for line in f:
                    if not line.strip():
                        continue
                    rec = json.loads(line)
                    for k in fields:
                        fields[k].extend(rec.get(k, []))
        return {
            "obs": np.asarray(fields["obs"], np.float32),
            "actions": np.asarray(fields["actions"], np.int32),
            "rewards": np.asarray(fields["rewards"], np.float32),
            "dones": np.asarray(fields["dones"], np.float32),
        }


@dataclass
class MARWILConfig:
    """Fluent config (parity: rllib MARWILConfig)."""

    env: Any = "CartPole-v1"   # for obs/action spaces + optional eval
    input_path: str | None = None   # JSONL file/dir of logged experience
    input_dataset: Any = None       # or a ray_tpu.data Dataset of records
    beta: float = 1.0               # 0 => behavior cloning
    gamma: float = 0.99
    vf_coeff: float = 1.0
    train_batch_size: int = 512
    num_sgd_iter_per_train: int = 10
    lr: float = 1e-3
    hidden_size: int = 64
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def offline_data(self, input_path: str | None = None, input_dataset=None):
        self.input_path = input_path
        self.input_dataset = input_dataset
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown MARWIL option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "MARWIL":
        return MARWIL(self)


@dataclass
class BCConfig(MARWILConfig):
    """Behavior cloning = MARWIL with beta=0 (reference: rllib BC)."""

    beta: float = 0.0

    def build(self) -> "BC":
        return BC(self)


class MARWIL:
    """Offline learner: advantage-weighted action imitation."""

    def __init__(self, config: MARWILConfig):
        self.config = config
        probe = make_env(config.env)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.params = init_policy_params(
            self.obs_size, self.num_actions, config.hidden_size, config.seed)
        self.data = self._load_data()
        self._update = None
        self.iteration = 0

    def _load_data(self) -> dict:
        cfg = self.config
        if cfg.input_dataset is not None:
            rows = cfg.input_dataset.take_all() \
                if hasattr(cfg.input_dataset, "take_all") else list(cfg.input_dataset)
            return {
                "obs": np.asarray([r["obs"] for r in rows], np.float32),
                "actions": np.asarray([r["actions"] for r in rows], np.int32),
                "rewards": np.asarray(
                    [r.get("rewards", 0.0) for r in rows], np.float32),
                "dones": np.asarray(
                    [r.get("dones", 0.0) for r in rows], np.float32),
            }
        if cfg.input_path is not None:
            return JsonReader(cfg.input_path).read_all()
        raise ValueError("MARWIL/BC needs input_path or input_dataset")

    def _returns(self) -> np.ndarray:
        """Discounted reward-to-go per step (targets for the value head and
        the MARWIL advantage baseline)."""
        cfg = self.config
        rews, dones = self.data["rewards"], self.data["dones"]
        out = np.zeros(len(rews), np.float32)
        acc = 0.0
        for t in range(len(rews) - 1, -1, -1):
            acc = rews[t] + cfg.gamma * acc * (1.0 - dones[t])
            out[t] = acc
        return out

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def loss_fn(params, batch):
            h = jnp.tanh(batch["obs"] @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            logits = h @ params["pi"]["w"] + params["pi"]["b"]
            value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32),
                axis=1)[:, 0]
            if cfg.beta == 0.0:
                weight = jnp.ones_like(logp)     # plain BC
                vf_loss = jnp.zeros(())
            else:
                adv = batch["returns"] - value
                weight = jnp.exp(cfg.beta * jax.lax.stop_gradient(
                    adv / (jnp.abs(adv).mean() + 1e-8)))
                vf_loss = (adv ** 2).mean()
            pi_loss = -(weight * logp).mean()
            total = pi_loss + cfg.vf_coeff * vf_loss
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "mean_weight": weight.mean()}

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update)

    def train(self) -> dict:
        if self._update is None:
            self._build_update()
            self._ret = self._returns()
        cfg = self.config
        rng = np.random.default_rng(cfg.seed + self.iteration)
        n = len(self.data["obs"])
        t0 = time.time()
        last_aux, losses = {}, []
        for _ in range(cfg.num_sgd_iter_per_train):
            idx = rng.integers(0, n, min(cfg.train_batch_size, n))
            mb = {"obs": self.data["obs"][idx],
                  "actions": self.data["actions"][idx],
                  "returns": self._ret[idx]}
            self.params, self._opt_state, loss, last_aux = self._update(
                self.params, self._opt_state, mb)
            losses.append(float(loss))
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "loss": float(np.mean(losses)),
            "num_samples": n,
            "iter_time_s": round(time.time() - t0, 3),
            **{k: float(v) for k, v in last_aux.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> dict:
        """Greedy-policy rollouts in the config env."""
        env = make_env(self.config.env)
        params = self.get_policy_params()
        returns = []
        for ep in range(num_episodes):
            obs = env.reset(seed=10_000 + ep)
            done, total = False, 0.0
            while not done:
                logits, _ = numpy_forward(params, obs[None, :])
                obs, r, done, _ = env.step(int(np.argmax(logits[0])))
                total += r
            returns.append(total)
        return {"episode_reward_mean": float(np.mean(returns)),
                "episodes": num_episodes}

    def stop(self):
        pass

    def get_policy_params(self) -> dict:
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def compute_single_action(self, obs) -> int:
        logits, _ = numpy_forward(self.get_policy_params(), obs[None, :])
        return int(np.argmax(logits[0]))


class BC(MARWIL):
    """Behavior cloning (reference: rllib/algorithms/bc/)."""
