"""CRR: Critic-Regularized Regression (offline RL).

Parity: reference rllib/algorithms/crr/ — learn a Q critic on the
logged transitions, then imitate only advantage-positive actions:
policy loss = -w(s,a) * log pi(a|s) with w = exp(A/beta) ("exp" mode,
clipped) or w = 1[A > 0] ("binary" mode). Sits between BC (no critic)
and CQL (pessimistic critic + SAC) in the offline family.

Discrete-action variant over the same JSONL/Dataset inputs as
BC/MARWIL (offline.py); the critic's advantage baseline is the
policy-expected Q under the current policy distribution.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ray_tpu.rllib.dqn import init_q_params
from ray_tpu.rllib.offline import MARWIL, MARWILConfig


@dataclass
class CRRConfig(MARWILConfig):
    """Fluent config (parity: rllib CRRConfig)."""

    weight_mode: str = "exp"      # "exp" | "binary"
    beta: float = 1.0             # temperature for exp weights
    weight_clip: float = 20.0
    critic_lr: float = 1e-3
    target_update_freq: int = 4   # iterations between critic target syncs

    def build(self) -> "CRR":  # type: ignore[override]
        return CRR(self)


class CRR(MARWIL):
    def __init__(self, config: CRRConfig):
        super().__init__(config)
        self.q_params = init_q_params(self.obs_size, self.num_actions,
                                      config.hidden_size, config.seed + 1)
        self.q_target = self.q_params
        self._q_update = None

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg: CRRConfig = self.config  # type: ignore[assignment]
        pi_opt = optax.adam(cfg.lr)
        q_opt = optax.adam(cfg.critic_lr)
        self._opt = pi_opt
        self._opt_state = pi_opt.init(self.params)
        self._q_opt = q_opt
        self._q_opt_state = q_opt.init(self.q_params)

        def q_fn(params, obs):
            h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            return h @ params["q"]["w"] + params["q"]["b"]

        def pi_logits(params, obs):
            h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            return h @ params["pi"]["w"] + params["pi"]["b"]

        def q_loss(q_params, q_target, pi_params, batch):
            q = q_fn(q_params, batch["obs"])
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
            # SARSA-style bootstrap through the CURRENT policy's
            # expectation at s' (the offline-safe choice: no max over
            # out-of-distribution actions).
            probs_next = jax.nn.softmax(pi_logits(pi_params,
                                                  batch["next_obs"]))
            v_next = (probs_next * q_fn(q_target, batch["next_obs"])).sum(-1)
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) \
                * v_next
            return ((q_sel - jax.lax.stop_gradient(target)) ** 2).mean()

        def pi_loss(pi_params, q_params, batch):
            logits = pi_logits(pi_params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32),
                axis=1)[:, 0]
            q = q_fn(q_params, batch["obs"])
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
            v = (jax.nn.softmax(logits) * q).sum(-1)
            adv = jax.lax.stop_gradient(q_sel - v)
            if cfg.weight_mode == "binary":
                w = (adv > 0).astype(jnp.float32)
            else:
                w = jnp.minimum(jnp.exp(adv / cfg.beta), cfg.weight_clip)
            return -(jax.lax.stop_gradient(w) * logp).mean()

        def update(pi_params, q_params, q_target, pi_state, q_state, batch):
            ql, q_grads = jax.value_and_grad(q_loss)(
                q_params, q_target, pi_params, batch)
            q_up, q_state = q_opt.update(q_grads, q_state)
            q_params = optax.apply_updates(q_params, q_up)
            pl, pi_grads = jax.value_and_grad(pi_loss)(
                pi_params, q_params, batch)
            pi_up, pi_state = pi_opt.update(pi_grads, pi_state)
            pi_params = optax.apply_updates(pi_params, pi_up)
            return pi_params, q_params, pi_state, q_state, ql, pl

        self._update = jax.jit(update)

    def train(self) -> dict:
        if self._update is None:
            self._build_update()
        cfg: CRRConfig = self.config  # type: ignore[assignment]
        t0 = time.time()
        n = len(self.data["obs"])
        rng = np.random.default_rng(cfg.seed + self.iteration)
        q_losses, pi_losses = [], []
        for _ in range(cfg.num_sgd_iter_per_train):
            idx = rng.integers(0, n, cfg.train_batch_size)
            # Logged steps are sequential, so obs[i+1] is next_obs within
            # an episode; at boundaries (dones=1) the bootstrap is masked,
            # so the wrong-next-obs there never enters the target.
            batch = {
                "obs": self.data["obs"][idx],
                "actions": self.data["actions"][idx],
                "rewards": self.data["rewards"][idx],
                "next_obs": self.data["obs"][np.minimum(idx + 1, n - 1)],
                "dones": self.data["dones"][idx].astype(np.float32),
            }
            (self.params, self.q_params, self._opt_state, self._q_opt_state,
             ql, pl) = self._update(self.params, self.q_params, self.q_target,
                                    self._opt_state, self._q_opt_state, batch)
            q_losses.append(float(ql))
            pi_losses.append(float(pl))
        self.iteration += 1
        if self.iteration % cfg.target_update_freq == 0:
            self.q_target = self.q_params
        return {
            "training_iteration": self.iteration,
            "critic_loss": float(np.mean(q_losses)),
            "policy_loss": float(np.mean(pi_losses)),
            "num_samples_trained": cfg.num_sgd_iter_per_train
            * cfg.train_batch_size,
            "iter_time_s": round(time.time() - t0, 3),
        }
