"""MADDPG: multi-agent DDPG with centralized critics.

Parity: reference rllib/algorithms/maddpg/ (per-agent deterministic
actor over its OWN observation; per-agent critic over ALL observations
and ALL actions — centralized training, decentralized execution; target
networks with polyak averaging; shared replay of joint transitions).
JAX-native: all agents' actor+critic updates run in one jitted program.

Ships CoopNav, the cooperative continuous testbed (two agents on a
line steering to their targets, shared reward) standing in for the
reference's MPE simple_spread.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.utils import tree_copy as _copy_tree
from ray_tpu.rllib.utils import tree_numpy as _to_numpy


def resolve_ma_env(spec):
    """Environment spec -> instance: "CoopNav" (built-in), a class, or a
    zero-arg factory. The env must expose the CoopNav contract
    (n_agents/observation_size/action_size, list-per-agent obs, shared
    scalar reward)."""
    if spec == "CoopNav" or spec is None:
        return CoopNav()
    if callable(spec):
        return spec()
    raise ValueError(f"unsupported multi-agent env spec {spec!r}; pass "
                     "'CoopNav' or an env class/factory")


class CoopNav:
    """Two agents on [-1, 1] each steering to its own target; shared
    reward -(|p0-t0| + |p1-t1|). Obs_i = [own pos, own target, other
    pos, other target]; action_i = velocity in [-1, 1]."""

    n_agents = 2
    observation_size = 4
    action_size = 1
    horizon = 25

    def __init__(self):
        self.rng = np.random.default_rng(0)

    def reset(self, seed: int | None = None) -> list[np.ndarray]:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.pos = self.rng.uniform(-1, 1, 2).astype(np.float32)
        self.targets = self.rng.uniform(-1, 1, 2).astype(np.float32)
        self.t = 0
        return self._obs()

    def _obs(self) -> list[np.ndarray]:
        out = []
        for i in range(2):
            j = 1 - i
            out.append(np.array([self.pos[i], self.targets[i],
                                 self.pos[j], self.targets[j]],
                                np.float32))
        return out

    def step(self, actions: list[float]):
        self.pos = np.clip(
            self.pos + 0.1 * np.clip(np.asarray(actions, np.float32)
                                     .reshape(2), -1, 1), -1, 1)
        self.t += 1
        reward = -float(np.abs(self.pos - self.targets).sum())
        done = self.t >= self.horizon
        # Episodes end ONLY by time limit — flag it so off-policy
        # targets bootstrap through the cut (env.py convention).
        return self._obs(), reward, done, {"truncated": done}


def init_maddpg_params(n_agents: int, obs_size: int, act_size: int,
                       hidden: int = 64, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o))
                      / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    joint = n_agents * (obs_size + act_size)
    agents = []
    for _ in range(n_agents):
        agents.append({
            "actor": {"h": dense(obs_size, hidden),
                      "out": dense(hidden, act_size)},
            "critic": {"h1": dense(joint, hidden),
                       "h2": dense(hidden, hidden),
                       "out": dense(hidden, 1)},
        })
    return {"agents": agents}


def numpy_actor(actor: dict, obs: np.ndarray) -> np.ndarray:
    h = np.tanh(obs @ actor["h"]["w"] + actor["h"]["b"])
    return np.tanh(h @ actor["out"]["w"] + actor["out"]["b"])


@ray_tpu.remote
class MADDPGRolloutWorker:
    """CPU sampler: decentralized execution — each agent acts from its
    own actor + exploration noise (parity: rollout_worker.py)."""

    def __init__(self, env_spec, worker_index: int):
        self.env = resolve_ma_env(env_spec)
        self.rng = np.random.default_rng(5000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)
        self.ep_ret = 0.0

    def sample(self, params: dict, num_steps: int, noise: float) -> dict:
        n = self.env.n_agents
        buf = {"obs": [], "actions": [], "rewards": [], "next_obs": [],
               "dones": []}
        episode_returns = []
        for _ in range(num_steps):
            acts = []
            for i in range(n):
                a = numpy_actor(params["agents"][i]["actor"],
                                self.obs[i][None, :])[0]
                a = np.clip(a + noise * self.rng.standard_normal(a.shape),
                            -1, 1)
                acts.append(a.astype(np.float32))
            next_obs, reward, done, info = self.env.step(
                [float(a[0]) for a in acts])
            buf["obs"].append(np.stack(self.obs))
            buf["actions"].append(np.stack(acts))
            buf["rewards"].append(reward)
            buf["next_obs"].append(np.stack(next_obs))
            # Time-limit cuts bootstrap through (env.py convention).
            buf["dones"].append(float(bool(done)
                                and not info.get("truncated", False)))
            self.ep_ret += reward
            if done:
                episode_returns.append(self.ep_ret)
                self.ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {k: np.asarray(v, np.float32) for k, v in buf.items()} | {
            "episode_returns": episode_returns}


@dataclass
class MADDPGConfig:
    """Parity: rllib MADDPGConfig."""

    env: Any = "CoopNav"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 200
    buffer_capacity: int = 50_000
    train_batch_size: int = 128
    num_sgd_iter: int = 16
    gamma: float = 0.95
    actor_lr: float = 1e-3
    critic_lr: float = 1e-3
    tau: float = 0.02  # polyak
    hidden_size: int = 64
    exploration_noise: float = 0.3
    noise_decay_iters: int = 20
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown MADDPG option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "MADDPG":
        return MADDPG(self)


class MADDPG:
    """Algorithm driver (parity: Algorithm.step / MADDPG training_step)."""

    def __init__(self, config: MADDPGConfig):
        self.config = config
        env = resolve_ma_env(config.env)
        self.n_agents = env.n_agents
        self.obs_size = env.observation_size
        self.act_size = env.action_size
        self.params = init_maddpg_params(
            self.n_agents, self.obs_size, self.act_size,
            config.hidden_size, config.seed)
        self.target_params = _copy_tree(self.params)
        cap = config.buffer_capacity
        self.buf = {
            "obs": np.zeros((cap, self.n_agents, self.obs_size),
                            np.float32),
            "actions": np.zeros((cap, self.n_agents, self.act_size),
                                np.float32),
            "rewards": np.zeros(cap, np.float32),
            "next_obs": np.zeros((cap, self.n_agents, self.obs_size),
                                 np.float32),
            "dones": np.zeros(cap, np.float32),
        }
        self.buf_pos = 0
        self.buf_size = 0
        self.rng = np.random.default_rng(config.seed)
        self.workers = [MADDPGRolloutWorker.remote(config.env, i)
                        for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0

    def _add(self, batch: dict) -> None:
        n = len(batch["obs"])
        cap = self.config.buffer_capacity
        idx = (self.buf_pos + np.arange(n)) % cap
        for k in self.buf:
            self.buf[k][idx] = batch[k]
        self.buf_pos = int((self.buf_pos + n) % cap)
        self.buf_size = int(min(self.buf_size + n, cap))

    def _sample(self, batch_size: int) -> dict:
        idx = self.rng.integers(0, self.buf_size, batch_size)
        return {k: v[idx] for k, v in self.buf.items()}

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        n = self.n_agents
        opt_a = optax.adam(cfg.actor_lr)
        opt_c = optax.adam(cfg.critic_lr)
        self._opt_a, self._opt_c = opt_a, opt_c
        self._opt_a_state = opt_a.init(self.params)
        self._opt_c_state = opt_c.init(self.params)

        def actor(p, obs):
            h = jnp.tanh(obs @ p["h"]["w"] + p["h"]["b"])
            return jnp.tanh(h @ p["out"]["w"] + p["out"]["b"])

        def critic(p, joint):
            h = jnp.tanh(joint @ p["h1"]["w"] + p["h1"]["b"])
            h = jnp.tanh(h @ p["h2"]["w"] + p["h2"]["b"])
            return (h @ p["out"]["w"] + p["out"]["b"])[:, 0]

        def joint_in(obs, acts):
            B = obs.shape[0]
            return jnp.concatenate(
                [obs.reshape(B, -1), acts.reshape(B, -1)], axis=1)

        def critic_loss(params, target_params, batch):
            # Centralized TD target: all target actors act on next obs.
            next_acts = jnp.stack(
                [actor(target_params["agents"][i]["actor"],
                       batch["next_obs"][:, i]) for i in range(n)], axis=1)
            total = 0.0
            for i in range(n):
                q_next = critic(target_params["agents"][i]["critic"],
                                joint_in(batch["next_obs"], next_acts))
                target = batch["rewards"] + cfg.gamma * \
                    (1.0 - batch["dones"]) * jax.lax.stop_gradient(q_next)
                q = critic(params["agents"][i]["critic"],
                           joint_in(batch["obs"], batch["actions"]))
                total = total + jnp.mean((q - target) ** 2)
            return total

        def actor_loss(params, batch):
            # Each agent maximizes ITS centralized critic with its own
            # action re-derived from its actor, others' from replay.
            total = 0.0
            for i in range(n):
                my_act = actor(params["agents"][i]["actor"],
                               batch["obs"][:, i])
                acts = batch["actions"].at[:, i].set(my_act)
                q = critic(jax.lax.stop_gradient(
                    params["agents"][i]["critic"]),
                    joint_in(batch["obs"], acts))
                total = total - jnp.mean(q)
            return total

        def polyak(target, online):
            return jax.tree_util.tree_map(
                lambda t, o: (1.0 - cfg.tau) * t + cfg.tau * o,
                target, online)

        @jax.jit
        def update(params, target_params, oa, oc, batch):
            closs, cgrads = jax.value_and_grad(critic_loss)(
                params, target_params, batch)
            cupd, oc = opt_c.update(cgrads, oc, params)
            params = optax.apply_updates(params, cupd)
            aloss, agrads = jax.value_and_grad(actor_loss)(params, batch)
            aupd, oa = opt_a.update(agrads, oa, params)
            params = optax.apply_updates(params, aupd)
            target_params = polyak(target_params, params)
            return params, target_params, oa, oc, closs, aloss

        self._update = update

    def train(self) -> dict:
        cfg = self.config
        if self._update is None:
            self._build_update()
        frac = min(1.0, self.iteration / max(1, cfg.noise_decay_iters))
        noise = cfg.exploration_noise * (1.0 - 0.9 * frac)
        rollout_params = _to_numpy(self.params)
        outs = ray_tpu.get([
            w.sample.remote(rollout_params, cfg.rollout_fragment_length,
                            noise) for w in self.workers])
        returns = []
        for out in outs:
            self._add(out)
            returns += out["episode_returns"]
            self.total_steps += len(out["obs"])
        closses = []
        if self.buf_size >= cfg.train_batch_size:
            for _ in range(cfg.num_sgd_iter):
                batch = {k: v for k, v in
                         self._sample(cfg.train_batch_size).items()}
                import jax.numpy as jnp

                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                (self.params, self.target_params, self._opt_a_state,
                 self._opt_c_state, closs, _aloss) = self._update(
                    self.params, self.target_params, self._opt_a_state,
                    self._opt_c_state, batch)
                closses.append(float(closs))
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "episode_reward_mean":
                    float(np.mean(returns)) if returns else float("nan"),
                "num_env_steps_sampled": self.total_steps,
                "critic_loss":
                    float(np.mean(closses)) if closses else None}


