"""Distributed learner gang: N learner actors with synchronized updates.

Parity: reference rllib/core/learner/learner_group.py — remote Learner
workers each hold a model replica, compute gradients on their shard of
every batch, and synchronize via an allreduce before applying updates
(the reference wraps modules in torch DDP, torch_learner.py:368). Here
the gradient plane is the repo's collective ring (util/collective —
peer-to-peer ring host plane; XLA collectives when learners share a
mesh), and each learner applies the SAME reduced gradient with the same
jitted optimizer math, so parameters stay bit-identical across the gang
without any parameter server.

Update cycle per minibatch:
  1. each learner jits grads on its 1/N shard of the batch
  2. grads flatten to ONE contiguous vector -> ring allreduce (mean)
  3. each learner applies the reduced grads (jitted optax step)
Optimizer state lives sharded-by-replication: every learner holds the
full optimizer state, advanced identically (the degenerate but exact
form of replicated data parallelism; ZeRO-style sharding of the state
belongs to the Train SPMD path, train/spmd.py).
"""

from __future__ import annotations

import hashlib
import pickle
from typing import Any

import numpy as np

import ray_tpu


@ray_tpu.remote(num_cpus=1)
class LearnerActor:
    """One member of the gang (reference: Learner, rllib/core/learner)."""

    def __init__(self, rank: int, world: int, group_name: str, model: str,
                 obs_size, num_actions: int, hidden: int, lr: float,
                 clip_param: float, vf_coeff: float, entropy_coeff: float,
                 seed: int, algo: str = "ppo",
                 algo_kwargs: dict | None = None):
        import jax
        import optax

        from ray_tpu.rllib.catalog import get_model

        self.rank, self.world, self.group = rank, world, group_name
        spec = get_model(model)
        # Same seed everywhere => bit-identical initial replicas (the
        # reference broadcasts from rank 0; identical init is equivalent
        # and needs no traffic).
        self.params = spec.init_params(obs_size, num_actions, hidden, seed)
        opt = optax.adam(lr)
        self.opt_state = opt.init(self.params)
        # Pluggable loss: sync algos shard one batch row-wise (PPO);
        # async algos feed whole trajectory fragments per learner
        # (IMPALA/APPO — V-trace needs intact sequences). Reference:
        # rllib/core/learner builds per-algo Learner classes over one
        # LearnerGroup.
        if algo == "ppo":
            from ray_tpu.rllib.ppo import make_ppo_loss

            loss_fn = make_ppo_loss(spec.jax_forward, clip_param, vf_coeff,
                                    entropy_coeff)
        elif algo == "impala":
            from ray_tpu.rllib.impala import make_impala_loss

            loss_fn = make_impala_loss(
                vf_coeff=vf_coeff, entropy_coeff=entropy_coeff,
                **(algo_kwargs or {}))
        else:
            raise ValueError(f"unknown learner algo {algo!r}")

        def grad_fn(params, batch):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            return grads, loss, aux

        def apply_fn(params, opt_state, grads):
            updates, opt_state = opt.update(grads, opt_state)
            import optax as _optax

            return _optax.apply_updates(params, updates), opt_state

        self._grad = jax.jit(grad_fn)
        self._apply = jax.jit(apply_fn)
        self._tree_def = None

    def join_group(self) -> bool:
        from ray_tpu.util import collective

        collective.init_collective_group(self.world, self.rank,
                                         backend="xla",
                                         group_name=self.group)
        return True

    def _flatten(self, tree):
        import jax

        leaves, tree_def = jax.tree_util.tree_flatten(tree)
        self._tree_def = tree_def
        self._shapes = [np.asarray(x).shape for x in leaves]
        self._sizes = [int(np.prod(s)) if s else 1 for s in self._shapes]
        return np.concatenate(
            [np.asarray(x, np.float32).ravel() for x in leaves])

    def _unflatten(self, flat):
        import jax

        out, off = [], 0
        for shape, size in zip(self._shapes, self._sizes):
            out.append(flat[off:off + size].reshape(shape))
            off += size
        return jax.tree_util.tree_unflatten(self._tree_def, out)

    def update(self, batch: dict) -> dict:
        """One synchronized step on this learner's shard of the batch:
        local grads -> ring allreduce(mean) -> identical apply."""
        from ray_tpu.util import collective

        grads, loss, aux = self._grad(self.params, batch)
        flat = self._flatten(grads)
        if self.world > 1:
            flat = np.asarray(
                collective.allreduce(flat, group_name=self.group),
                np.float32) / self.world
        self.params, self.opt_state = self._apply(
            self.params, self.opt_state, self._unflatten(flat))
        return {"loss": float(loss),
                **{k: float(v) for k, v in aux.items()}}

    def get_params(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def params_fingerprint(self) -> str:
        """SHA1 over every parameter byte — the gang-sync check."""
        import jax

        h = hashlib.sha1()
        for leaf in jax.tree_util.tree_leaves(self.params):
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
        return h.hexdigest()

    def get_state(self) -> bytes:
        import jax

        return pickle.dumps({
            "params": jax.tree_util.tree_map(np.asarray, self.params),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
        })

    def set_state(self, blob: bytes) -> bool:
        st = pickle.loads(blob)
        self.params = st["params"]
        self.opt_state = st["opt_state"]
        return True


class LearnerGroup:
    """Owns the gang (reference: LearnerGroup — spawn, rendezvous,
    sharded update fan-out, checkpoint)."""

    _seq = 0

    def __init__(self, *, num_learners: int, model: str, obs_size,
                 num_actions: int, hidden: int, lr: float,
                 clip_param: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.0, seed: int = 0,
                 algo: str = "ppo", algo_kwargs: dict | None = None):
        LearnerGroup._seq += 1
        self.group_name = f"learner-gang-{LearnerGroup._seq}"
        self.num_learners = num_learners
        self.learners = [
            LearnerActor.remote(rank, num_learners, self.group_name, model,
                                obs_size, num_actions, hidden, lr,
                                clip_param, vf_coeff, entropy_coeff, seed,
                                algo, algo_kwargs)
            for rank in range(num_learners)]
        # Rendezvous: every member joins the ring before the first update.
        ray_tpu.get([a.join_group.remote() for a in self.learners],
                    timeout=120)

    def update(self, batch: dict) -> dict:
        """One synchronized SGD step over the whole batch: each learner
        takes its 1/N shard; gradients allreduce inside the actors."""
        n = self.num_learners
        shards = [
            {k: np.array_split(v, n)[i] for k, v in batch.items()}
            for i in range(n)]
        return self.update_shards(shards)

    def update_shards(self, shards: list[dict]) -> dict:
        """One synchronized step with an EXPLICIT batch per learner —
        the async-algo path (IMPALA/APPO hand each learner a whole
        trajectory fragment; V-trace sequences cannot be row-split)."""
        assert len(shards) == self.num_learners
        metrics = ray_tpu.get(
            [a.update.remote(s) for a, s in zip(self.learners, shards)],
            timeout=600)
        # Means across learners (each reports its local loss).
        return {k: float(np.mean([m[k] for m in metrics]))
                for k in metrics[0]}

    def get_params(self):
        return ray_tpu.get(self.learners[0].get_params.remote(), timeout=120)

    def fingerprints(self) -> list[str]:
        return ray_tpu.get(
            [a.params_fingerprint.remote() for a in self.learners],
            timeout=120)

    def save_state(self) -> bytes:
        """Checkpoint (params + optimizer state) from rank 0 — state is
        bit-identical across the gang by construction."""
        return ray_tpu.get(self.learners[0].get_state.remote(), timeout=120)

    def restore_state(self, blob: bytes) -> None:
        ray_tpu.get([a.set_state.remote(blob) for a in self.learners],
                    timeout=120)

    def shutdown(self) -> None:
        from ray_tpu.util import collective

        for a in self.learners:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self.learners = []
