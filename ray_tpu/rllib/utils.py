"""Small shared helpers for RLlib algorithm modules.

Parity: reference rllib/utils/ (tree utilities over nested param
structures — the reference uses torch/tf nest; here plain
dict/list-of-ndarray trees shared by every JAX algorithm driver)."""

from __future__ import annotations

import numpy as np


def tree_copy(t):
    """Deep copy of a nested dict/list/tuple tree of arrays (device
    arrays become fresh host ndarrays)."""
    if isinstance(t, dict):
        return {k: tree_copy(v) for k, v in t.items()}
    if isinstance(t, (list, tuple)):
        out = [tree_copy(v) for v in t]
        return type(t)(out) if isinstance(t, tuple) else out
    return np.array(t).copy()


def tree_numpy(t):
    """Nested tree with every leaf viewed as a host ndarray (no copy
    when already numpy) — the form CPU rollout workers consume."""
    if isinstance(t, dict):
        return {k: tree_numpy(v) for k, v in t.items()}
    if isinstance(t, (list, tuple)):
        out = [tree_numpy(v) for v in t]
        return type(t)(out) if isinstance(t, tuple) else out
    return np.asarray(t)
