"""IMPALA: asynchronous sampling with V-trace off-policy correction.

Parity: reference rllib/algorithms/impala/ — rollout actors sample
continuously with a (stale) behavior policy while the learner consumes
whatever trajectories are ready (`ray.wait`-style async consumption,
reference: impala.py's aggregation of in-flight sample requests). The
staleness gap is corrected by V-trace (Espeholt et al. 2018) importance
weights, computed inside the jitted learner step.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import init_policy_params, numpy_forward


@ray_tpu.remote
class ImpalaRolloutWorker:
    """CPU sampling actor emitting fixed-length trajectory fragments with
    behavior logits (needed for the V-trace importance ratios)."""

    def __init__(self, env_spec, worker_index: int):
        self.env = make_env(env_spec)
        self.index = worker_index
        self.rng = np.random.default_rng(3000 + worker_index)
        self.obs = self.env.reset(seed=worker_index)

    def sample(self, params: dict, num_steps: int) -> dict:
        obs_b, act_b, logp_b, rew_b, done_b = [], [], [], [], []
        episode_returns, ep_ret = [], 0.0
        for _ in range(num_steps):
            logits, _ = numpy_forward(params, self.obs[None, :])
            logits = logits[0]
            p = np.exp(logits - logits.max())
            p /= p.sum()
            action = int(self.rng.choice(len(p), p=p))
            next_obs, reward, done, _ = self.env.step(action)
            obs_b.append(self.obs)
            act_b.append(action)
            logp_b.append(float(np.log(p[action] + 1e-8)))
            rew_b.append(reward)
            done_b.append(done)
            ep_ret += reward
            if done:
                episode_returns.append(ep_ret)
                ep_ret = 0.0
                self.obs = self.env.reset()
            else:
                self.obs = next_obs
        return {
            "obs": np.asarray(obs_b, np.float32),
            "actions": np.asarray(act_b, np.int32),
            "behavior_logp": np.asarray(logp_b, np.float32),
            "rewards": np.asarray(rew_b, np.float32),
            "dones": np.asarray(done_b, np.float32),
            "bootstrap_obs": np.asarray(self.obs, np.float32),
            "episode_returns": episode_returns,
        }


@dataclass
class ImpalaConfig:
    """Parity: rllib ImpalaConfig fluent-config object."""

    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 128
    num_fragments_per_iter: int = 4   # learner consumes this many per train()
    gamma: float = 0.99
    vtrace_clip_rho: float = 1.0      # rho-bar: value-target IS clip
    vtrace_clip_c: float = 1.0        # c-bar: trace-cutting IS clip
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    lr: float = 5e-4
    hidden_size: int = 64
    seed: int = 0
    # >1: updates run on a LearnerGroup of remote learner actors, one
    # whole trajectory fragment per learner per step, ring-allreduced
    # gradients (reference: impala's LearnerGroup fan-out).
    num_learners: int = 1

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "Impala":
        return Impala(self)


def make_impala_loss(*, gamma: float, vf_coeff: float, entropy_coeff: float,
                     clip_rho: float, clip_c: float):
    """The V-trace actor-critic loss as a free function, shared by the
    in-process learner and the distributed LearnerGroup's learner actors
    (same factoring as make_ppo_loss; reference: impala/impala_learner
    builds one loss both local and remote learners jit)."""
    import jax
    import jax.numpy as jnp

    def forward(params, obs):
        h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
        h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
        logits = h @ params["pi"]["w"] + params["pi"]["b"]
        value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        return logits, value

    def vtrace(values, boot_v, rewards, dones, rhos):
        """V-trace targets (Espeholt et al. 2018, eq. 1): backward scan
        building vs_t = V(x_t) + Σ γ^k c_[t..] δ_k V."""
        clipped_rho = jnp.minimum(clip_rho, rhos)
        clipped_c = jnp.minimum(clip_c, rhos)
        next_values = jnp.concatenate([values[1:], boot_v[None]])
        next_values = next_values * (1 - dones)  # terminal: V=0
        deltas = clipped_rho * (rewards + gamma * next_values - values)

        def body(acc, xs):
            delta, c, done = xs
            acc = delta + gamma * (1 - done) * c * acc
            return acc, acc

        _, advs = jax.lax.scan(body, jnp.zeros(()),
                               (deltas, clipped_c, dones), reverse=True)
        vs = values + advs
        next_vs = jnp.concatenate([vs[1:], boot_v[None]]) * (1 - dones)
        pg_adv = clipped_rho * (rewards + gamma * next_vs - values)
        return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

    def loss_fn(params, batch):
        logits, values = forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None].astype(jnp.int32),
            axis=1)[:, 0]
        _, boot_v = forward(params, batch["bootstrap_obs"][None, :])
        rhos = jnp.exp(logp - batch["behavior_logp"])
        vs, pg_adv = vtrace(values, boot_v[0], batch["rewards"],
                            batch["dones"], rhos)
        pi_loss = -(logp * pg_adv).mean()
        vf_loss = ((values - vs) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": entropy,
                       "mean_rho": rhos.mean()}

    return loss_fn


class Impala:
    """Algorithm driver. Sampling stays in flight across train() calls —
    the learner never waits for ALL workers, only for the next ready
    fragments (the async gap V-trace corrects)."""

    def __init__(self, config: ImpalaConfig):
        self.config = config
        probe = make_env(config.env)
        self.obs_size = probe.observation_size
        self.num_actions = probe.num_actions
        self.params = init_policy_params(
            self.obs_size, self.num_actions, config.hidden_size, config.seed)
        self.workers = [ImpalaRolloutWorker.remote(config.env, i)
                        for i in range(config.num_rollout_workers)]
        self._inflight: dict = {}   # ref -> worker
        self._update = None
        self._learner_group = None
        if config.num_learners > 1:
            if config.num_fragments_per_iter % config.num_learners:
                # A partial cohort would be silently discarded at the end
                # of every train() — with num_learners > fragments the
                # params would NEVER update.
                raise ValueError(
                    f"num_fragments_per_iter={config.num_fragments_per_iter}"
                    f" must be a multiple of num_learners="
                    f"{config.num_learners}")
            from ray_tpu.rllib.learner_group import LearnerGroup

            self._learner_group = LearnerGroup(
                num_learners=config.num_learners, model="mlp",
                obs_size=self.obs_size, num_actions=self.num_actions,
                hidden=config.hidden_size, lr=config.lr,
                vf_coeff=config.vf_coeff,
                entropy_coeff=config.entropy_coeff, seed=config.seed,
                algo="impala",
                algo_kwargs={"gamma": config.gamma,
                             "clip_rho": config.vtrace_clip_rho,
                             "clip_c": config.vtrace_clip_c})
        self.iteration = 0
        self.total_steps = 0

    def _build_update(self):
        import jax
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)
        loss_fn = make_impala_loss(
            gamma=cfg.gamma, vf_coeff=cfg.vf_coeff,
            entropy_coeff=cfg.entropy_coeff,
            clip_rho=cfg.vtrace_clip_rho, clip_c=cfg.vtrace_clip_c)

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update)

    def _host_params(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def _launch(self, worker):
        ref = worker.sample.remote(self._host_params(),
                                   self.config.rollout_fragment_length)
        self._inflight[ref] = worker

    def train(self) -> dict:
        if self._update is None and self._learner_group is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        if self._learner_group is not None:
            self.params = self._learner_group.get_params()
        # Keep every worker busy; collect only the fragments that are ready
        # (workers that aren't done keep running — async by construction).
        for w in self.workers:
            if w not in self._inflight.values():
                self._launch(w)
        episode_returns, last_aux, consumed = [], {}, 0
        gang_batches: list = []
        while consumed < cfg.num_fragments_per_iter:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            ref = ready[0]
            worker = self._inflight.pop(ref)
            batch = ray_tpu.get(ref)
            self._launch(worker)  # immediately resample with fresh params
            episode_returns += batch.pop("episode_returns")
            if self._learner_group is not None:
                # Whole fragments accumulate until every learner has one,
                # then ONE synchronized allreduced step consumes them
                # (V-trace sequences cannot be row-split across learners).
                gang_batches.append(batch)
                if len(gang_batches) == self._learner_group.num_learners:
                    last_aux = self._learner_group.update_shards(gang_batches)
                    gang_batches = []
                    self.params = self._learner_group.get_params()
            else:
                self.params, self._opt_state, loss, last_aux = self._update(
                    self.params, self._opt_state, batch)
            consumed += 1
            self.total_steps += cfg.rollout_fragment_length
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else float("nan"),
            "episodes_this_iter": len(episode_returns),
            "timesteps_total": self.total_steps,
            "iter_time_s": round(time.time() - t0, 3),
            **{k: float(v) for k, v in last_aux.items()},
        }

    def stop(self):
        # Drain in-flight samples before killing (avoids error spam).
        for ref in list(self._inflight):
            try:
                ray_tpu.get(ref, timeout=30)
            except Exception:
                pass
        self._inflight.clear()
        if self._learner_group is not None:
            self._learner_group.shutdown()
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def get_policy_params(self) -> dict:
        return self._host_params()

    def compute_single_action(self, obs) -> int:
        logits, _ = numpy_forward(self.get_policy_params(), obs[None, :])
        return int(np.argmax(logits[0]))
