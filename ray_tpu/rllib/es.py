"""ES + ARS: gradient-free population search over policy weights.

Parity: reference rllib/algorithms/es/ (OpenAI Evolution Strategies —
antithetic Gaussian perturbations, rank-normalized update) and
rllib/algorithms/ars/ (Augmented Random Search — top-k directions
weighted by reward std). Both map cleanly onto the rollout-actor plane:
each worker evaluates perturbed policies episode-by-episode on CPU; the
driver does the (tiny) parameter update in numpy — there is no gradient
step to put on an accelerator, so no learner program is built at all.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import init_policy_params, numpy_forward


def _flatten(params: dict) -> tuple[np.ndarray, list]:
    """Flatten the nested param dict into one vector + a rebuild spec."""
    parts, spec = [], []
    for layer in sorted(params):
        for name in sorted(params[layer]):
            arr = np.asarray(params[layer][name], np.float64)
            spec.append((layer, name, arr.shape))
            parts.append(arr.reshape(-1))
    return np.concatenate(parts), spec


def _unflatten(vec: np.ndarray, spec: list) -> dict:
    out: dict = {}
    pos = 0
    for layer, name, shape in spec:
        n = int(np.prod(shape))
        out.setdefault(layer, {})[name] = (
            vec[pos:pos + n].reshape(shape).astype(np.float32))
        pos += n
    return out


@ray_tpu.remote
class _EvalWorker:
    """Evaluates policy weight vectors for whole episodes (no learning)."""

    def __init__(self, env_spec, worker_index: int):
        self.env = make_env(env_spec)
        self._seed = 1000 + worker_index

    def evaluate(self, vec: np.ndarray, spec: list, episodes: int,
                 max_steps: int) -> tuple[float, int]:
        params = _unflatten(vec, spec)
        total, steps = 0.0, 0
        for ep in range(episodes):
            self._seed += 1
            obs = self.env.reset(seed=self._seed)
            for _ in range(max_steps):
                logits, _ = numpy_forward(params, obs[None, :])
                obs, rew, done, _info = self.env.step(int(np.argmax(logits)))
                total += rew
                steps += 1
                if done:
                    break
        return total / episodes, steps


@dataclass
class ESConfig:
    """Fluent config (parity: rllib ESConfig)."""

    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    population: int = 16          # perturbation PAIRS per iteration
    sigma: float = 0.05           # perturbation stddev
    lr: float = 0.02
    episodes_per_eval: int = 1
    max_episode_steps: int = 500
    hidden_size: int = 32
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown ES option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "ES":
        return ES(self)


class ES:
    """Antithetic ES: theta += lr/(n*sigma) * sum_i rank(r_i) * eps_i."""

    def __init__(self, config: ESConfig):
        self.config = config
        probe = make_env(config.env)
        params = init_policy_params(probe.observation_size,
                                    probe.num_actions, config.hidden_size,
                                    config.seed)
        # The full dict (incl. the unused value head) flattens into the
        # search space — numpy_forward wants every layer present, and a
        # few dead dims are cheaper than a special-cased forward.
        self.theta, self.spec = _flatten(params)
        self.rng = np.random.default_rng(config.seed)
        self.workers = [_EvalWorker.remote(config.env, i)
                        for i in range(config.num_rollout_workers)]
        self.iteration = 0
        self.total_steps = 0

    def _center_weights(self, rewards: np.ndarray) -> np.ndarray:
        """Centered-rank transform in [-0.5, 0.5] (reference ES utility)."""
        ranks = np.empty_like(rewards)
        ranks[np.argsort(rewards)] = np.arange(len(rewards))
        return ranks / (len(rewards) - 1) - 0.5

    def train(self) -> dict:
        cfg = self.config
        t0 = time.time()
        eps = self.rng.standard_normal((cfg.population, self.theta.size))
        candidates = np.concatenate([self.theta + cfg.sigma * eps,
                                     self.theta - cfg.sigma * eps])
        futs = [self.workers[i % len(self.workers)].evaluate.remote(
                    candidates[i], self.spec, cfg.episodes_per_eval,
                    cfg.max_episode_steps)
                for i in range(len(candidates))]
        results = ray_tpu.get(futs, timeout=600)
        rewards = np.array([r for r, _ in results])
        self.total_steps += sum(s for _, s in results)

        w = self._center_weights(rewards)
        pos, neg = w[:cfg.population], w[cfg.population:]
        grad = ((pos - neg)[:, None] * eps).sum(0) / (
            cfg.population * cfg.sigma)
        self.theta = self.theta + cfg.lr * grad
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(rewards.mean()),
            "episode_reward_max": float(rewards.max()),
            "timesteps_this_iter": int(sum(s for _, s in results)),
            "timesteps_total": self.total_steps,
            "iter_time_s": round(time.time() - t0, 3),
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    def get_policy_params(self) -> dict:
        return _unflatten(self.theta, self.spec)

    def compute_single_action(self, obs) -> int:
        logits, _ = numpy_forward(self.get_policy_params(), obs[None, :])
        return int(np.argmax(logits[0]))


@dataclass
class ARSConfig(ESConfig):
    """ARS: like ES but only the top-k directions update, scaled by the
    reward std of those directions (parity: rllib ARSConfig)."""

    top_directions: int = 8

    def build(self) -> "ARS":  # type: ignore[override]
        return ARS(self)


class ARS(ES):
    def train(self) -> dict:
        cfg: ARSConfig = self.config  # type: ignore[assignment]
        t0 = time.time()
        eps = self.rng.standard_normal((cfg.population, self.theta.size))
        candidates = np.concatenate([self.theta + cfg.sigma * eps,
                                     self.theta - cfg.sigma * eps])
        futs = [self.workers[i % len(self.workers)].evaluate.remote(
                    candidates[i], self.spec, cfg.episodes_per_eval,
                    cfg.max_episode_steps)
                for i in range(len(candidates))]
        results = ray_tpu.get(futs, timeout=600)
        rewards = np.array([r for r, _ in results])
        self.total_steps += sum(s for _, s in results)

        r_pos, r_neg = rewards[:cfg.population], rewards[cfg.population:]
        k = min(cfg.top_directions, cfg.population)
        order = np.argsort(-np.maximum(r_pos, r_neg))[:k]
        used = np.concatenate([r_pos[order], r_neg[order]])
        sigma_r = used.std() + 1e-8
        grad = ((r_pos[order] - r_neg[order])[:, None] * eps[order]).sum(0)
        self.theta = self.theta + cfg.lr / (k * sigma_r) * grad
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(rewards.mean()),
            "episode_reward_max": float(rewards.max()),
            "timesteps_this_iter": int(sum(s for _, s in results)),
            "timesteps_total": self.total_steps,
            "iter_time_s": round(time.time() - t0, 3),
        }
