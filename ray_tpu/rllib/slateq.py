"""SlateQ: Q-learning over recommendation slates via itemwise
decomposition.

Parity: reference rllib/algorithms/slateq/ (RecSim-style environment;
the SlateQ decomposition Q(s, A) = sum_{i in A} P(i | s, A) q(s, i)
with a known conditional-choice model; itemwise q trained by SARSA-style
TD on the CLICKED item; greedy slate building by choice-weighted
top-k). JAX-native: the itemwise q over all candidates is one batched
jitted update. Ships SlateDocEnv, the synthetic user/document simulator
standing in for RecSim interest-evolution.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import ray_tpu


class SlateDocEnv:
    """Synthetic recommender: a user interest vector over `dim` topics,
    `num_docs` fixed documents with topic features. Each step the agent
    shows a slate of `slate_size` docs; the user clicks doc i with
    P ∝ exp(interest·doc_i) against a no-click alternative, engagement
    reward = sigmoid(interest·doc) of the click, and interests drift
    toward clicked topics (interest evolution). Horizon fixed."""

    dim = 6
    num_docs = 20
    slate_size = 3
    horizon = 20
    no_click_mass = 1.0

    def __init__(self, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.docs = rng.standard_normal(
            (self.num_docs, self.dim)).astype(np.float32)
        self.docs /= np.linalg.norm(self.docs, axis=1, keepdims=True)
        self.rng = rng

    @property
    def observation_size(self) -> int:
        return self.dim

    def reset(self, seed: int | None = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.user = self.rng.standard_normal(self.dim).astype(np.float32)
        self.user /= np.linalg.norm(self.user)
        self.t = 0
        return self.user.copy()

    def choice_probs(self, slate: np.ndarray) -> np.ndarray:
        """P(click each slate item) + trailing P(no click) — the known
        conditional choice model SlateQ assumes."""
        scores = np.exp(self.docs[slate] @ self.user)
        denom = scores.sum() + self.no_click_mass
        return np.concatenate([scores / denom,
                               [self.no_click_mass / denom]])

    def step(self, slate: np.ndarray):
        probs = self.choice_probs(slate)
        pick = int(self.rng.choice(len(probs), p=probs))
        reward = 0.0
        clicked = -1
        if pick < len(slate):
            clicked = int(slate[pick])
            affinity = float(self.docs[clicked] @ self.user)
            reward = 1.0 / (1.0 + np.exp(-affinity))
            # Interest evolution: drift toward the clicked topic.
            self.user = 0.9 * self.user + 0.1 * self.docs[clicked]
            self.user /= np.linalg.norm(self.user)
        self.t += 1
        return self.user.copy(), reward, self.t >= self.horizon, \
            {"clicked": clicked}


def init_slateq_params(dim: int, hidden: int = 64, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def dense(i, o):
        return {"w": (rng.standard_normal((i, o))
                      / np.sqrt(i)).astype(np.float32),
                "b": np.zeros(o, np.float32)}

    # Itemwise q(s, d): input [user ; doc] -> scalar.
    return {"h1": dense(2 * dim, hidden), "h2": dense(hidden, hidden),
            "q": dense(hidden, 1)}


def numpy_item_q(params: dict, user: np.ndarray,
                 docs: np.ndarray) -> np.ndarray:
    """q(s, d) for every candidate doc: [D]."""
    x = np.concatenate(
        [np.repeat(user[None, :], len(docs), 0), docs], axis=1)
    h = np.tanh(x @ params["h1"]["w"] + params["h1"]["b"])
    h = np.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
    return (h @ params["q"]["w"] + params["q"]["b"])[:, 0]


def greedy_slate(params: dict, user: np.ndarray, docs: np.ndarray,
                 slate_size: int) -> np.ndarray:
    """SlateQ's greedy construction: rank docs by choice-model score
    times itemwise q (the top-k approximation of the fractional LP the
    paper shows is optimal for this choice model)."""
    v = np.exp(docs @ user)
    q = numpy_item_q(params, user, docs)
    return np.argsort(-(v * q))[:slate_size].astype(np.int64)


def slate_value(params: dict, user: np.ndarray, docs: np.ndarray,
                slate: np.ndarray, no_click_mass: float) -> float:
    """Decomposed Q(s, A) = sum_i P(i|s,A) q(s,i)."""
    scores = np.exp(docs[slate] @ user)
    denom = scores.sum() + no_click_mass
    q = numpy_item_q(params, user, docs[slate])
    return float((scores / denom) @ q)


@ray_tpu.remote
class SlateQRolloutWorker:
    """CPU sampler: epsilon-greedy over slates (random slate vs greedy
    choice-weighted top-k)."""

    def __init__(self, worker_index: int, env_seed: int):
        self.env = SlateDocEnv(env_seed)
        self.rng = np.random.default_rng(7000 + worker_index)
        self.user = self.env.reset(seed=worker_index)
        self.ep_ret = 0.0

    def sample(self, params: dict, num_steps: int, epsilon: float) -> dict:
        env = self.env
        buf = {"user": [], "slate": [], "clicked": [], "reward": [],
               "next_user": [], "done": []}
        episode_returns = []
        for _ in range(num_steps):
            if self.rng.random() < epsilon:
                slate = self.rng.choice(env.num_docs, env.slate_size,
                                        replace=False).astype(np.int64)
            else:
                slate = greedy_slate(params, self.user, env.docs,
                                     env.slate_size)
            next_user, reward, done, info = env.step(slate)
            buf["user"].append(self.user)
            buf["slate"].append(slate)
            buf["clicked"].append(info["clicked"])
            buf["reward"].append(reward)
            buf["next_user"].append(next_user)
            buf["done"].append(float(done))
            self.ep_ret += reward
            if done:
                episode_returns.append(self.ep_ret)
                self.ep_ret = 0.0
                self.user = env.reset()
            else:
                self.user = next_user
        return {"user": np.asarray(buf["user"], np.float32),
                "slate": np.asarray(buf["slate"], np.int64),
                "clicked": np.asarray(buf["clicked"], np.int64),
                "reward": np.asarray(buf["reward"], np.float32),
                "next_user": np.asarray(buf["next_user"], np.float32),
                "done": np.asarray(buf["done"], np.float32),
                "episode_returns": episode_returns}


@dataclass
class SlateQConfig:
    """Parity: rllib SlateQConfig."""

    num_rollout_workers: int = 2
    rollout_fragment_length: int = 200
    buffer_capacity: int = 50_000
    train_batch_size: int = 128
    num_sgd_iter: int = 16
    gamma: float = 0.95
    lr: float = 1e-3
    hidden_size: int = 64
    target_network_update_freq: int = 4
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_iters: int = 12
    env_seed: int = 0
    seed: int = 0

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown SlateQ option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "SlateQ":
        return SlateQ(self)


class SlateQ:
    """Algorithm driver (parity: Algorithm.step / SlateQ
    training_step): the itemwise q is trained SARSA-style on clicked
    transitions toward r + gamma * Q(s', greedy slate), with Q'
    decomposed through the known choice model."""

    def __init__(self, config: SlateQConfig):
        self.config = config
        self.env = SlateDocEnv(config.env_seed)  # doc catalog (fixed)
        dim = self.env.dim
        self.params = init_slateq_params(dim, config.hidden_size,
                                         config.seed)
        self.target_params = {k: {kk: vv.copy() for kk, vv in v.items()}
                              for k, v in self.params.items()}
        cap = config.buffer_capacity
        self.buf = {
            "user": np.zeros((cap, dim), np.float32),
            "clicked_doc": np.zeros((cap, dim), np.float32),
            "reward": np.zeros(cap, np.float32),
            "next_user": np.zeros((cap, dim), np.float32),
            "done": np.zeros(cap, np.float32),
        }
        self.pos = 0
        self.size = 0
        self.rng = np.random.default_rng(config.seed)
        self.workers = [
            SlateQRolloutWorker.remote(i, config.env_seed)
            for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        opt = optax.adam(self.config.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        docs = jnp.asarray(self.env.docs)          # [D, dim], fixed
        slate_size = self.env.slate_size
        no_click = self.env.no_click_mass

        def item_q(params, users, doc_feats):
            x = jnp.concatenate([users, doc_feats], axis=1)
            h = jnp.tanh(x @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            return (h @ params["q"]["w"] + params["q"]["b"])[:, 0]

        def item_q_all(params, users):
            """q(s, d) for every candidate doc: [B, D]."""
            B, D = users.shape[0], docs.shape[0]
            u = jnp.repeat(users, D, axis=0)
            d = jnp.tile(docs, (B, 1))
            return item_q(params, u, d).reshape(B, D)

        def next_slate_value(target_params, next_users):
            """Greedy choice-weighted slate + decomposed Q(s', A') — the
            SlateQ bootstrap, recomputed at TRAIN time with the current
            target net (stored scalars would anchor old entries to
            init-era targets)."""
            v = jnp.exp(next_users @ docs.T)           # [B, D]
            q = item_q_all(target_params, next_users)  # [B, D]
            _, top = jax.lax.top_k(v * q, slate_size)  # [B, k]
            v_sel = jnp.take_along_axis(v, top, axis=1)
            q_sel = jnp.take_along_axis(q, top, axis=1)
            denom = v_sel.sum(axis=1, keepdims=True) + no_click
            return (v_sel / denom * q_sel).sum(axis=1)

        def loss_fn(params, target_params, batch):
            q = item_q(params, batch["user"], batch["clicked_doc"])
            next_q = jax.lax.stop_gradient(
                next_slate_value(target_params, batch["next_user"]))
            target = batch["reward"] + self.config.gamma * \
                (1.0 - batch["done"]) * next_q
            return jnp.mean((q - target) ** 2)

        @jax.jit
        def update(params, target_params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, target_params, batch)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = update

    def _ingest(self, out: dict) -> None:
        """Clicked transitions only (SlateQ's SARSA decomposition trains
        q(s, clicked); no-click steps carry no itemwise target). Only
        raw (s, clicked doc, r, s') is stored — the bootstrap slate
        value is recomputed inside the jitted update with the CURRENT
        target net, so replayed entries never carry stale targets."""
        cfg = self.config
        mask = out["clicked"] >= 0
        users = out["user"][mask]
        clicked = out["clicked"][mask]
        n = len(users)
        if n == 0:
            return
        cap = cfg.buffer_capacity
        idx = (self.pos + np.arange(n)) % cap
        self.buf["user"][idx] = users
        self.buf["clicked_doc"][idx] = self.env.docs[clicked]
        self.buf["reward"][idx] = out["reward"][mask]
        self.buf["next_user"][idx] = out["next_user"][mask]
        self.buf["done"][idx] = out["done"][mask]
        self.pos = int((self.pos + n) % cap)
        self.size = int(min(self.size + n, cap))

    def train(self) -> dict:
        cfg = self.config
        if self._update is None:
            self._build_update()
        eps = self._epsilon()
        rollout_params = {k: {kk: np.asarray(vv) for kk, vv in v.items()}
                          for k, v in self.params.items()}
        outs = ray_tpu.get([
            w.sample.remote(rollout_params, cfg.rollout_fragment_length,
                            eps) for w in self.workers])
        returns = []
        for out in outs:
            self._ingest(out)
            returns += out["episode_returns"]
            self.total_steps += len(out["user"])
        losses = []
        if self.size >= cfg.train_batch_size:
            for _ in range(cfg.num_sgd_iter):
                idx = self.rng.integers(0, self.size,
                                        cfg.train_batch_size)
                batch = {k: v[idx] for k, v in self.buf.items()}
                self.params, self._opt_state, loss = self._update(
                    self.params, self.target_params, self._opt_state,
                    batch)
                losses.append(float(loss))
        self.iteration += 1
        if self.iteration % cfg.target_network_update_freq == 0:
            self.target_params = {
                k: {kk: np.asarray(vv).copy() for kk, vv in v.items()}
                for k, v in self.params.items()}
        return {"training_iteration": self.iteration,
                "episode_reward_mean":
                    float(np.mean(returns)) if returns else float("nan"),
                "num_env_steps_sampled": self.total_steps,
                "loss": float(np.mean(losses)) if losses else None}
