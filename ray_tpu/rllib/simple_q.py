"""SimpleQ and Ape-X DQN: the two ends of the Q-learning family.

Parity: reference rllib/algorithms/simple_q/ (vanilla Q-learning —
uniform replay, no double-Q, periodic hard target sync) and
rllib/algorithms/apex_dqn/ (Ape-X — MANY rollout workers with a
per-worker epsilon ladder feeding a shared prioritized replay buffer
asynchronously; the learner consumes batches as they arrive instead of
lock-stepping with sampling).

Both reuse the DQN machinery (models, rollout workers, jitted update);
what differs is the replay/synchronization topology — which in this
runtime is exactly the actor topology, so each variant is a short
driver.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

import ray_tpu
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNRolloutWorker
from ray_tpu.rllib.replay import PrioritizedReplayBuffer


@dataclass
class SimpleQConfig(DQNConfig):
    """Vanilla Q-learning (the reference's relationship is mirrored:
    there DQN extends SimpleQ; here SimpleQ restricts DQN)."""

    double_q: bool = False
    num_sgd_iter: int = 8

    def build(self) -> "SimpleQ":  # type: ignore[override]
        return SimpleQ(self)


class SimpleQ(DQN):
    """DQN driver with the vanilla loss (no double-Q selection)."""


@dataclass
class ApexDQNConfig(DQNConfig):
    """Ape-X: async sampling + prioritized replay (reference:
    apex_dqn.py; the epsilon ladder is per-worker and constant,
    eps_i = base ** (1 + i/(n-1) * alpha) — exploration diversity comes
    from the ladder, not a schedule)."""

    num_rollout_workers: int = 4
    buffer_capacity: int = 100_000
    per_alpha: float = 0.6
    per_beta: float = 0.4
    epsilon_base: float = 0.4
    epsilon_alpha: float = 7.0
    # learner sgd steps per arriving rollout batch
    sgd_steps_per_batch: int = 8
    batches_per_iter: int = 8

    def build(self) -> "ApexDQN":  # type: ignore[override]
        return ApexDQN(self)


class ApexDQN(DQN):
    def __init__(self, config: ApexDQNConfig):
        super().__init__(config)
        # Prioritized buffer replaces the uniform one.
        self.buffer = PrioritizedReplayBuffer(
            config.buffer_capacity, self.obs_size, config.seed,
            alpha=config.per_alpha, beta=config.per_beta)
        n = max(1, config.num_rollout_workers)
        self._epsilons = [
            config.epsilon_base ** (1 + i / max(1, n - 1) *
                                    config.epsilon_alpha)
            for i in range(n)]
        self._inflight: dict = {}

    def _launch(self, i: int, host_params):
        fut = self.workers[i].sample.remote(
            host_params, self.config.rollout_fragment_length,
            self._epsilons[i])
        self._inflight[fut] = i

    def _build_update(self):
        """Ape-X update: IS-weighted Huber loss that also RETURNS the
        per-sample TD errors (they become the new priorities)."""
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def q_fn(params, obs):
            h = jnp.tanh(obs @ params["h1"]["w"] + params["h1"]["b"])
            h = jnp.tanh(h @ params["h2"]["w"] + params["h2"]["b"])
            return h @ params["q"]["w"] + params["q"]["b"]

        def loss_fn(params, target_params, batch):
            q = q_fn(params, batch["obs"])
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
            q_next_target = q_fn(target_params, batch["next_obs"])
            a_star = jnp.argmax(q_fn(params, batch["next_obs"]), axis=1)
            q_next = jnp.take_along_axis(
                q_next_target, a_star[:, None], axis=1)[:, 0]
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) \
                * q_next
            td = q_sel - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td,
                              jnp.abs(td) - 0.5)
            loss = (batch["weights"] * huber).mean()
            return loss, td

        def update(params, target_params, opt_state, batch):
            (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._update = jax.jit(update)

    def _sgd_step(self, sample: dict) -> dict:
        batch = {k: v for k, v in sample.items() if k != "indices"}
        self.params, self._opt_state, loss, td = self._update(
            self.params, self.target_params, self._opt_state, batch)
        return {"loss": float(loss), "td_error": np.asarray(td)}

    def train(self) -> dict:
        import jax

        if self._update is None:
            self._build_update()
        cfg: ApexDQNConfig = self.config  # type: ignore[assignment]
        t0 = time.time()
        host_params = jax.tree_util.tree_map(np.asarray, self.params)
        for i in range(len(self.workers)):
            if i not in self._inflight.values():
                self._launch(i, host_params)

        episode_returns: list = []
        losses: list = []
        consumed = 0
        while consumed < cfg.batches_per_iter:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
            fut = ready[0]
            i = self._inflight.pop(fut)
            batch = ray_tpu.get(fut, timeout=60)
            episode_returns.extend(batch.pop("episode_returns", []))
            self.buffer.add_batch(batch)
            self.total_steps += len(batch["obs"])
            consumed += 1
            # Relaunch immediately with fresh weights: sampling never
            # blocks on learning (the Ape-X point).
            host_params = jax.tree_util.tree_map(np.asarray, self.params)
            self._launch(i, host_params)
            if self.buffer.size >= max(cfg.train_batch_size,
                                       cfg.learning_starts):
                for _ in range(cfg.sgd_steps_per_batch):
                    sample = self.buffer.sample(cfg.train_batch_size)
                    out = self._sgd_step(sample)
                    losses.append(out["loss"])
                    self.buffer.update_priorities(
                        sample["indices"], np.abs(out["td_error"]))
        self.iteration += 1
        if self.iteration % cfg.target_network_update_freq == 0:
            # Functional updates never mutate in place, so aliasing the
            # current tree IS a snapshot (same as DQN's sync).
            self.target_params = jax.tree_util.tree_map(
                lambda x: x, self.params)
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else 0.0,
            "episodes_this_iter": len(episode_returns),
            "timesteps_this_iter": cfg.batches_per_iter
            * cfg.rollout_fragment_length,
            "timesteps_total": self.total_steps,
            "mean_loss": float(np.mean(losses)) if losses else 0.0,
            "iter_time_s": round(time.time() - t0, 3),
        }
