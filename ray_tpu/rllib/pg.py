"""PG: vanilla policy gradient (REINFORCE with value baseline).

Parity: reference rllib/algorithms/pg/ — the minimal on-policy
algorithm, sharing PPO's rollout actors (GAE advantages double as the
return-minus-baseline signal) with a plain -logp * advantage learner
update; no clipping, no multiple epochs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.rllib.ppo import RolloutWorker, init_policy_params


@dataclass
class PGConfig:
    env: Any = "CartPole-v1"
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 256
    gamma: float = 0.99
    lambda_: float = 1.0             # pure returns by default
    lr: float = 5e-3
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.0
    hidden_size: int = 64
    model: str = "mlp"
    seed: int = 0

    def environment(self, env):
        self.env = env
        return self

    def rollouts(self, num_rollout_workers: int | None = None, **kw):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown PG option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "PG":
        return PG(self)


class PG:
    def __init__(self, config: PGConfig):
        from ray_tpu.rllib.env import make_env

        self.config = config
        probe = make_env(config.env)
        self.params = init_policy_params(
            probe.observation_size, probe.num_actions, config.hidden_size,
            config.seed)
        self.workers = [
            RolloutWorker.remote(config.env, i, config.gamma,
                                 config.lambda_, config.model)
            for i in range(config.num_rollout_workers)]
        self._update = None
        self.iteration = 0
        self.total_steps = 0

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        opt = optax.adam(cfg.lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def forward(p, obs):
            h = jnp.tanh(obs @ p["h1"]["w"] + p["h1"]["b"])
            h = jnp.tanh(h @ p["h2"]["w"] + p["h2"]["b"])
            logits = h @ p["pi"]["w"] + p["pi"]["b"]
            value = (h @ p["vf"]["w"] + p["vf"]["b"])[..., 0]
            return logits, value

        def update(params, opt_state, batch):
            def loss_fn(p):
                logits, value = forward(p, batch["obs"])
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, batch["actions"][:, None], 1)[:, 0]
                adv = batch["advantages"]
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
                pg_loss = -(logp * adv).mean()
                vf_loss = ((value - batch["returns"]) ** 2).mean()
                entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
                return (pg_loss + cfg.vf_coeff * vf_loss
                        - cfg.entropy_coeff * entropy), (pg_loss, vf_loss,
                                                         entropy)

            (loss, (pg_l, vf_l, ent)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"loss": loss, "pg_loss": pg_l,
                                       "vf_loss": vf_l, "entropy": ent}

        self._update = jax.jit(update)

    def train(self) -> dict:
        import jax

        if self._update is None:
            self._build_update()
        cfg = self.config
        t0 = time.time()
        host = jax.tree_util.tree_map(np.asarray, self.params)
        frags = ray_tpu.get(
            [w.sample.remote(host, cfg.rollout_fragment_length)
             for w in self.workers], timeout=600)
        episode_returns = []
        batch = {}
        for f in frags:
            episode_returns += f.pop("episode_returns")
            for k, v in f.items():
                batch.setdefault(k, []).append(np.asarray(v))
        batch = {k: np.concatenate(v) for k, v in batch.items()}
        self.total_steps += len(batch["obs"])
        sample_time = time.time() - t0

        t1 = time.time()
        self.params, self._opt_state, metrics = self._update(
            self.params, self._opt_state, batch)
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(episode_returns))
            if episode_returns else float("nan"),
            "episodes_this_iter": len(episode_returns),
            "timesteps_total": self.total_steps,
            "timesteps_this_iter": len(batch["obs"]),
            "sample_time_s": round(sample_time, 3),
            "learn_time_s": round(time.time() - t1, 3),
            **{k: float(v) for k, v in metrics.items()},
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
