"""Fake multi-node cluster on one machine — THE key test harness.

Parity: reference python/ray/cluster_utils.py:108 (Cluster) — add_node:174
spawns extra raylets (own object store, own resources) against one GCS;
remove_node:247 SIGKILLs a raylet for failure testing. This is what makes
spillback scheduling, cross-node object transfer, and node-death recovery
testable without a real cluster (SURVEY.md §4).
"""

from __future__ import annotations

import time

import ray_tpu
from ray_tpu._private.config import Config
from ray_tpu._private.node import NodeHandle, RuntimeNode


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: dict | None = None,
                 connect: bool = False, config: Config | None = None):
        self._node = RuntimeNode(config)
        self.gcs_address: str | None = None
        self.head_node: NodeHandle | None = None
        self.connected = False
        if initialize_head:
            host, port = self._node.start_gcs()
            self.gcs_address = f"{host}:{port}"
            self.head_node = self.add_node(**(head_node_args or {}), _head=True)
            if connect:
                self.connect()

    @property
    def address(self) -> str | None:
        """GCS address (reference parity: cluster_utils.Cluster.address)."""
        return self.gcs_address

    def add_node(self, resources: dict | None = None, num_cpus: float | None = None,
                 labels: dict | None = None, _head: bool = False,
                 gcs_addr: tuple[str, int] | None = None) -> NodeHandle:
        """gcs_addr routes THIS node's raylet->GCS control traffic
        through an alternate endpoint (a test_utils.NetChaos proxy) so
        partition tests can fault one link without touching the rest of
        the cluster."""
        if self.gcs_address is None:
            host, port = self._node.start_gcs()
            self.gcs_address = f"{host}:{port}"
            _head = True
        res = dict(resources or {})
        if num_cpus is not None:
            res.setdefault("CPU", num_cpus)
        handle = self._node.start_raylet(resources=res or None, labels=labels,
                                         is_head=_head, gcs_addr=gcs_addr)
        if _head and self.head_node is None:
            self.head_node = handle
        return handle

    def remove_node(self, node: NodeHandle, allow_graceful: bool = False) -> None:
        node.kill()
        if node in self._node.nodes:
            self._node.nodes.remove(node)

    def drain_node(self, node: NodeHandle, *, deadline_s: float = 10.0,
                   reason: str = "manual", wait: bool = True) -> dict:
        """Gracefully drain one raylet through the GCS (DrainNode with
        reason + deadline) and, by default, wait until the node table
        reports DRAINED — after which remove_node() is a non-event (no
        lineage storms, no actor-death errors). Requires a connected
        driver."""
        from ray_tpu._private.api_internal import get_core_worker

        from ray_tpu._private.common import wait_for_drained

        cw = get_core_worker()
        resp = cw._run(cw.gcs.call(
            "DrainNode", {"node_id": node.node_id, "reason": reason,
                          "deadline_s": deadline_s}, timeout=30))
        if wait and resp.get("ok"):
            outcome, me = wait_for_drained(
                ray_tpu.nodes, node.node_id, deadline_s,
                poll_s=0.05, slack_s=15.0)
            resp = dict(resp)
            resp["state"] = (me.get("state") if me else "GONE") \
                if outcome != "DRAINED" else "DRAINED"
        return resp

    def connect(self):
        assert self.head_node is not None
        ray_tpu.init(
            address=self.gcs_address,
            _head_raylet=(self.head_node.host, self.head_node.port),
            _store_path=self.head_node.store_path,
            _node_id=self.head_node.node_id,
            config=self._node.config)
        self.connected = True
        return self

    def wait_for_nodes(self, num_nodes: int | None = None, timeout: float = 30.0):
        """Block until all started raylets are registered and alive in GCS."""
        want = num_nodes if num_nodes is not None else len(self._node.nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                alive = [n for n in ray_tpu.nodes() if n["alive"]]
                if len(alive) >= want:
                    return
            except Exception:
                pass
            time.sleep(0.05)
        raise TimeoutError(f"cluster did not reach {want} alive nodes")

    def shutdown(self):
        if self.connected:
            ray_tpu.shutdown()
            self.connected = False
        self._node.shutdown()
