"""Per-worker training session: report() / rank info / gradient sync.

Parity: reference python/ray/train/_internal/session.py:132 (_TrainSession;
session.report streams metrics+checkpoints to the trainer) and
train/train_loop_utils.py (prepare_model/prepare_data_loader — here the
TPU-native equivalents are mesh/sharding helpers plus a host-plane gradient
allreduce for multi-process data parallelism).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any

_local = threading.local()


@dataclass
class _Session:
    rank: int
    world_size: int
    report_queue: "queue.Queue"
    collective_group: str | None = None
    # Set when this run restores: a retried trainer attempt (elastic
    # restart) or a Tune trial resuming/exploiting a checkpoint.
    restore_checkpoint_path: str | None = None
    # Durable root for dict checkpoints (RunConfig.storage_path); None =
    # node-local tempdir (single-host semantics).
    storage_path: str | None = None


def _set_session(s: _Session | None) -> None:
    _local.session = s


def _get_session() -> _Session:
    s = getattr(_local, "session", None)
    if s is None:
        raise RuntimeError(
            "No active train session: this API must be called inside "
            "train_loop_per_worker")
    return s


def get_checkpoint():
    """The checkpoint this run should resume from, or None on a fresh
    start (reference: ray.train.get_checkpoint() — set on elastic
    restarts and Tune restore/exploit)."""
    s = _get_session()
    if s.restore_checkpoint_path is None:
        return None
    from ray_tpu.train.checkpoint import Checkpoint

    return Checkpoint(s.restore_checkpoint_path)


def report(metrics: dict, checkpoint=None) -> None:
    """Stream metrics (and optionally a Checkpoint) to the trainer.
    A plain dict is wrapped via Checkpoint.from_dict (reference: air
    Checkpoint dict form)."""
    s = _get_session()
    payload = {"metrics": dict(metrics), "rank": s.rank}
    if checkpoint is not None:
        if isinstance(checkpoint, dict):
            import os
            import uuid

            from ray_tpu.train.checkpoint import Checkpoint

            path = None
            if s.storage_path:
                path = os.path.join(s.storage_path, "checkpoints",
                                    f"ckpt-{uuid.uuid4().hex[:12]}")
            checkpoint = Checkpoint.from_dict(checkpoint, path)
        payload["checkpoint_path"] = checkpoint.path
    s.report_queue.put(payload)


class _TrainContext:
    """Reference-shaped context object (ray.train.get_context() —
    python/ray/train/context.py): rank/size accessors bundled."""

    def get_world_rank(self) -> int:
        return get_world_rank()

    def get_world_size(self) -> int:
        return get_world_size()

    def get_local_rank(self) -> int:
        return get_local_rank()

    def get_local_world_size(self) -> int:
        return 1  # one worker per host in this topology

    def get_node_rank(self) -> int:
        return get_world_rank()


def get_context() -> _TrainContext:
    _get_session()  # raise outside a train loop, like the reference
    return _TrainContext()


def get_world_rank() -> int:
    return _get_session().rank


def get_world_size() -> int:
    return _get_session().world_size


def get_local_rank() -> int:
    return _get_session().rank  # one worker per host in this topology


def set_collective_group(name: str) -> None:
    _get_session().collective_group = name


def allreduce_gradients(grads, group_name: str | None = None):
    """Host-plane gradient mean across train workers (the CPU/DP path —
    the reference's gloo DDP equivalent). On a TPU pod, prefer compiling
    dp into the mesh instead; this exists for multi-process CPU training
    and cross-slice DCN averaging."""
    import jax
    import numpy as np

    from ray_tpu.util.collective import allreduce

    s = _get_session()
    group = group_name or s.collective_group
    if group is None or s.world_size == 1:
        return grads
    flat, treedef = jax.tree_util.tree_flatten(grads)
    out = []
    for g in flat:
        arr = np.asarray(g, dtype=np.float32)
        red = allreduce(arr, group_name=group) / s.world_size
        out.append(red.astype(np.asarray(g).dtype))
    return jax.tree_util.tree_unflatten(treedef, out)
