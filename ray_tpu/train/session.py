"""Per-worker training session: report() / rank info / gradient sync.

Parity: reference python/ray/train/_internal/session.py:132 (_TrainSession;
session.report streams metrics+checkpoints to the trainer) and
train/train_loop_utils.py (prepare_model/prepare_data_loader — here the
TPU-native equivalents are mesh/sharding helpers plus a host-plane gradient
allreduce for multi-process data parallelism).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any

_local = threading.local()


class ElasticPauseInterrupt(BaseException):
    """Raised inside the user loop at a step boundary (report() /
    keep_state()) when the trainer requested a pause for an elastic
    resize. A BaseException so user `except Exception` blocks cannot
    swallow it; TrainWorker.run catches it and parks the worker in the
    `paused` state — it is not an error."""


class SessionStopped(BaseException):
    """Raised at the next step boundary after TrainWorker.stop():
    graceful session shutdown, never mid-report()."""


class _SessionControl:
    """Trainer→worker control plane shared between the actor thread
    (request_pause/stop) and the user-loop thread (boundary checks)."""

    def __init__(self):
        self.pause_requested = threading.Event()
        self.stop_requested = threading.Event()


@dataclass
class _Session:
    rank: int
    world_size: int
    report_queue: "queue.Queue"
    collective_group: str | None = None
    # Set when this run restores: a retried trainer attempt (elastic
    # restart) or a Tune trial resuming/exploiting a checkpoint.
    restore_checkpoint_path: str | None = None
    # Durable root for dict checkpoints (RunConfig.storage_path); None =
    # node-local tempdir (single-host semantics).
    storage_path: str | None = None
    # Elastic gang training: pause/stop control, the state tree this
    # worker preserved across the last pause, peer state handed over
    # from departed ranks, and the resize epoch (0 = never resized).
    control: Any = None
    elastic_state: Any = None
    elastic_state_step: int | None = None
    peer_states: dict | None = None
    elastic_epoch: int = 0
    on_keep_state: Any = None


def _check_boundary(s: _Session) -> None:
    """Step-boundary control check: stop wins over pause."""
    c = s.control
    if c is None:
        return
    if c.stop_requested.is_set():
        raise SessionStopped()
    if c.pause_requested.is_set():
        raise ElasticPauseInterrupt()


def _set_session(s: _Session | None) -> None:
    _local.session = s


def _get_session() -> _Session:
    s = getattr(_local, "session", None)
    if s is None:
        raise RuntimeError(
            "No active train session: this API must be called inside "
            "train_loop_per_worker")
    return s


def get_checkpoint():
    """The checkpoint this run should resume from, or None on a fresh
    start (reference: ray.train.get_checkpoint() — set on elastic
    restarts and Tune restore/exploit)."""
    s = _get_session()
    if s.restore_checkpoint_path is None:
        return None
    from ray_tpu.train.checkpoint import Checkpoint

    return Checkpoint(s.restore_checkpoint_path)


def report(metrics: dict, checkpoint=None) -> None:
    """Stream metrics (and optionally a Checkpoint) to the trainer.
    A plain dict is wrapped via Checkpoint.from_dict (reference: air
    Checkpoint dict form)."""
    s = _get_session()
    payload = {"metrics": dict(metrics), "rank": s.rank}
    if checkpoint is not None:
        if isinstance(checkpoint, dict):
            import os
            import uuid

            from ray_tpu.train.checkpoint import Checkpoint

            path = None
            if s.storage_path:
                path = os.path.join(s.storage_path, "checkpoints",
                                    f"ckpt-{uuid.uuid4().hex[:12]}")
            checkpoint = Checkpoint.from_dict(checkpoint, path)
        payload["checkpoint_path"] = checkpoint.path
    s.report_queue.put(payload)
    # report() is THE step boundary: an elastic pause or a graceful stop
    # lands here, after the metrics (and checkpoint pointer) are safely
    # on the queue — never mid-report.
    _check_boundary(s)


def keep_state(state, step: int | None = None) -> None:
    """Preserve `state` (params/opt-state pytree) for elastic resume.

    The worker pins the tree's jax.Array leaves in its device registry
    with the trainer as ref owner, so a node drain evacuates them via
    the device plane (device_objects.evacuate → DeviceObjectRepin) and a
    resize re-shards them to the surviving gang — no checkpoint
    write/read. Survivors get their own tree back via
    get_elastic_state(); departed ranks' trees arrive at the survivors
    through get_peer_states(). Also a step boundary (pause/stop land
    here), so call it once per step, after report()."""
    s = _get_session()
    s.elastic_state = state
    s.elastic_state_step = int(step) if step is not None \
        else (s.elastic_state_step or 0) + 1
    if s.on_keep_state is not None:
        s.on_keep_state(state, s.elastic_state_step)
    _check_boundary(s)


def get_elastic_state():
    """This worker's own preserved state tree (from keep_state) when the
    run is resuming after an elastic pause; None on a fresh start."""
    return _get_session().elastic_state


def get_elastic_state_step() -> int | None:
    """Step recorded with the preserved state, or None."""
    return _get_session().elastic_state_step


def get_peer_states() -> dict:
    """{old_rank: state_tree} handed over from ranks that left (shrink)
    or, on a freshly grown worker, seeded from a survivor. Empty on a
    fresh start and for survivors whose membership didn't change."""
    return dict(_get_session().peer_states or {})


def get_elastic_epoch() -> int:
    """How many elastic resizes this run has been through (0 = none;
    bumps on every shrink/grow the gang survived)."""
    return _get_session().elastic_epoch


class _TrainContext:
    """Reference-shaped context object (ray.train.get_context() —
    python/ray/train/context.py): rank/size accessors bundled."""

    def get_world_rank(self) -> int:
        return get_world_rank()

    def get_world_size(self) -> int:
        return get_world_size()

    def get_local_rank(self) -> int:
        return get_local_rank()

    def get_local_world_size(self) -> int:
        return 1  # one worker per host in this topology

    def get_node_rank(self) -> int:
        return get_world_rank()


def get_context() -> _TrainContext:
    _get_session()  # raise outside a train loop, like the reference
    return _TrainContext()


def get_world_rank() -> int:
    return _get_session().rank


def get_world_size() -> int:
    return _get_session().world_size


def get_local_rank() -> int:
    return _get_session().rank  # one worker per host in this topology


def set_collective_group(name: str) -> None:
    _get_session().collective_group = name


def allreduce_gradients(grads, group_name: str | None = None):
    """Host-plane gradient mean across train workers (the CPU/DP path —
    the reference's gloo DDP equivalent). On a TPU pod, prefer compiling
    dp into the mesh instead; this exists for multi-process CPU training
    and cross-slice DCN averaging."""
    import jax
    import numpy as np

    from ray_tpu.util.collective import allreduce

    s = _get_session()
    group = group_name or s.collective_group
    if group is None or s.world_size == 1:
        return grads
    flat, treedef = jax.tree_util.tree_flatten(grads)
    out = []
    for g in flat:
        arr = np.asarray(g, dtype=np.float32)
        red = allreduce(arr, group_name=group) / s.world_size
        out.append(red.astype(np.asarray(g).dtype))
    return jax.tree_util.tree_unflatten(treedef, out)
