"""HuggingFace Transformers integration for the trainer gang.

Parity: reference python/ray/train/huggingface/transformers/
(transformers_trainer.py / the modern `prepare_trainer` +
`RayTrainReportCallback` surface): run an unmodified `transformers.Trainer`
inside `train_loop_per_worker`; the callback streams its logs and
checkpoints into the ray_tpu train session so Tune/Result plumbing sees
them.

    def train_loop(config):
        trainer = transformers.Trainer(...)
        trainer = prepare_trainer(trainer)
        trainer.train()

    TorchTrainer(train_loop, scaling_config=ScalingConfig(num_workers=2)).fit()
"""

from __future__ import annotations

from ray_tpu.train import session

__all__ = ["RayTrainReportCallback", "prepare_trainer"]


def _transformers():
    try:
        import transformers
    except ImportError as e:  # pragma: no cover - soft dep
        raise ImportError(
            "transformers is required for ray_tpu.train.huggingface") from e
    return transformers


class RayTrainReportCallback:
    """transformers TrainerCallback reporting logs + checkpoints to the
    session (reference: RayTrainReportCallback)."""

    def __new__(cls):
        transformers = _transformers()

        class _Callback(transformers.TrainerCallback):
            _is_ray_tpu_report_cb = True

            def on_log(self, args, state, control, logs=None, **kwargs):
                if logs and state.is_world_process_zero:
                    metrics = {k: v for k, v in logs.items()
                               if isinstance(v, (int, float))}
                    metrics.setdefault("step", state.global_step)
                    session.report(metrics)

            def on_save(self, args, state, control, **kwargs):
                if state.is_world_process_zero:
                    from ray_tpu.train.checkpoint import Checkpoint

                    ckpt_dir = f"{args.output_dir}/checkpoint-{state.global_step}"
                    session.report(
                        {"checkpoint_step": state.global_step},
                        checkpoint=Checkpoint.from_directory(ckpt_dir))

        return _Callback()


def prepare_trainer(trainer):
    """Attach the report callback (idempotent — adding twice would
    double-report every log line). Returns the same trainer."""
    _transformers()
    already = any(getattr(cb, "_is_ray_tpu_report_cb", False)
                  for cb in trainer.callback_handler.callbacks)
    if not already:
        trainer.add_callback(RayTrainReportCallback())
    return trainer
