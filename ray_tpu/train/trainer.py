"""JaxTrainer: the Train entry point.

Parity: reference python/ray/train/data_parallel_trainer.py:59
(DataParallelTrainer.fit → BackendExecutor → WorkerGroup → per-worker
session) and base_trainer.py:608 (fit). The torch backend's
`dist.init_process_group(nccl)` (reference: train/torch/config.py:63)
becomes: (a) a host-plane collective group for multi-process DP, and
(b) on TPU pods, `jax.distributed.initialize` coordinator env wiring so
every worker joins one multi-host SPMD program.

Elastic mode (ScalingConfig.elastic): a gang member's node entering
DRAINING is a resize, not a failure. The trainer subscribes to GCS NODE
state transitions, pauses every worker at its next step boundary,
re-homes the departing ranks' params/opt-state through the device
object plane (the same re-pin machinery the drain pipeline uses —
device_objects.evacuate → DeviceObjectRepin), rebuilds the collective
rendezvous for the smaller world, and resumes at step N+1. Grow-back
re-seeds new members from rank 0 the same way. Fallback ladder:
re-shard → checkpoint restart (counted) → fail.
"""

from __future__ import annotations

import queue as _queue
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import serialization
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (ElasticConfig, FailureConfig, RunConfig,
                                  ScalingConfig)
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class Result:
    """Parity: ray.air.result.Result."""

    metrics: dict
    checkpoint: Checkpoint | None
    error: str | None
    metrics_history: list = field(default_factory=list)

    @property
    def best_checkpoint(self):
        return self.checkpoint


class JaxTrainer:
    """Runs `train_loop_per_worker` on a gang of workers.

    collective_backend: "cpu" (host-plane allreduce group, the gloo-DDP
    analog) or "xla" (workers form one multi-host jax.distributed world;
    each worker then compiles the SPMD step over the global mesh) or None.
    """

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 collective_backend: str | None = "cpu"):
        self._train_loop = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.collective_backend = collective_backend
        # Run telemetry (also exported through util/metrics gauges):
        # resizes the gang survived, steps lost to them, checkpoint
        # fallbacks (elastic resume failed) and full restarts.
        self.telemetry = {"resizes": 0, "shrinks": 0, "grows": 0,
                          "steps_lost": 0, "elastic_fallbacks": 0,
                          "full_restarts": 0}
        # Rank-0's newest report, readable while fit() runs (chaos
        # harnesses key their step schedules off it).
        self.latest_metrics: dict = {}

    def fit(self) -> Result:
        max_failures = self.run_config.failure_config.max_failures
        elastic = self.scaling_config.elastic
        attempt = 0
        restore_from: Checkpoint | None = None
        # Survives retries AND resizes: Result.metrics_history reflects
        # the whole run, not just the last attempt.
        history: list[dict] = []
        while True:
            try:
                if elastic is not None:
                    return self._fit_elastic(restore_from, history)
                return self._fit_once(restore_from, history)
            except exc.RayTpuError as e:
                attempt += 1
                if attempt > max_failures:
                    raise
                # Checkpoint restart (reference: FailureConfig retries
                # restore from the latest reported checkpoint). In
                # elastic mode this is the COUNTED fallback rung: the
                # happy path resumes via device-plane re-shard and never
                # lands here.
                restore_from = getattr(e, "_last_checkpoint", None) \
                    or restore_from
                self.telemetry["full_restarts"] += 1
                if elastic is not None:
                    self.telemetry["elastic_fallbacks"] += 1
                    _note_elastic("fallback")
                time.sleep(1.0)

    # ---------- fixed-gang path (unchanged semantics) ----------

    def _fit_once(self, restore_from: "Checkpoint | None" = None,
                  history: list | None = None) -> Result:
        run_id = uuid.uuid4().hex[:8]
        group = WorkerGroup(self.scaling_config)
        try:
            if self.collective_backend and self.scaling_config.num_workers > 1:
                group_name = f"train:{run_id}"
                group.run_on_all("setup_collective", group_name,
                                 self.collective_backend)
                cfg = dict(self._config)
                cfg["_collective_group"] = group_name
            else:
                cfg = dict(self._config)
            if restore_from is not None:
                cfg["_checkpoint_path"] = restore_from.path
            if self.run_config.storage_path:
                # Dict checkpoints land under durable storage instead of a
                # node-local tempdir — on real node loss the retry gang (on
                # other hosts) must still reach them (shared-fs semantics,
                # same as the reference's storage_path contract).
                cfg["_storage_path"] = self.run_config.storage_path
            blob = serialization.dumps_func(self._train_loop)
            group.run_on_all("run", blob, cfg)
            return self._drive(group, history if history is not None else [])
        finally:
            group.shutdown()

    def _drive(self, group: WorkerGroup, history: list) -> Result:
        """Poll workers, surface rank-0 reports (reference:
        TrainingIterator in data_parallel_trainer.py:429)."""
        last_ckpt: Checkpoint | None = None
        done = [False] * len(group.workers)
        error: str | None = None
        final_metrics: dict = dict(history[-1]) if history else {}
        while not all(done):
            try:
                polls = ray_tpu.get(
                    [w.poll.remote() for w in group.workers], timeout=300)
            except exc.RayTpuError as e:
                # Worker actor died (node loss, OOM kill): the retry loop
                # needs the newest checkpoint seen before the crash.
                e._last_checkpoint = last_ckpt
                raise
            for i, p in enumerate(polls):
                for rep in p["reports"]:
                    if rep["rank"] == 0:
                        history.append(rep["metrics"])
                        final_metrics = rep["metrics"]
                        self.latest_metrics = final_metrics
                        if rep.get("checkpoint_path"):
                            last_ckpt = Checkpoint(rep["checkpoint_path"])
                if p["done"]:
                    done[i] = True
                    if p["error"] and error is None:
                        error = f"worker {i}: {p['error']}"
            if error:
                err = exc.RayTpuError(f"training failed: {error}")
                # Carried to fit()'s retry loop for checkpoint restore.
                err._last_checkpoint = last_ckpt
                raise err
            if not all(done):
                time.sleep(0.05)
        return Result(metrics=final_metrics, checkpoint=last_ckpt,
                      error=None, metrics_history=history)

    # ---------- elastic path ----------

    def _fit_elastic(self, restore_from: "Checkpoint | None",
                     history: list) -> Result:
        from ray_tpu._private.api_internal import get_core_worker

        cw = get_core_worker()
        run_id = uuid.uuid4().hex[:8]
        blob = serialization.dumps_func(self._train_loop)
        node_events: "_queue.Queue" = _queue.Queue()
        listener = node_events.put
        cw.add_node_event_listener(listener)
        group = WorkerGroup(self.scaling_config)
        try:
            self._start_epoch(group, run_id, 0, blob, restore_from)
            return self._drive_elastic(group, node_events, history,
                                       run_id, blob)
        finally:
            cw.remove_node_event_listener(listener)
            group.shutdown()

    def _start_epoch(self, group: WorkerGroup, run_id: str, epoch: int,
                     blob: bytes, restore_from: "Checkpoint | None",
                     workers=None) -> None:
        """(Re-)launch the user loop on `workers` (default: the whole
        gang) for one membership epoch."""
        from ray_tpu._private.api_internal import get_core_worker

        cw = get_core_worker()
        cfg = dict(self._config)
        cfg["_elastic"] = True
        cfg["_elastic_epoch"] = epoch
        if cw.address is not None:
            # Makes the trainer the device-plane ref owner of every
            # keep_state pin: a node drain then evacuates the pins HERE
            # (DeviceObjectRepin), off the dying node.
            cfg["_elastic_owner"] = cw.address.to_wire()
        if self.collective_backend and len(group.workers) > 1:
            group_name = f"train:{run_id}:{epoch}"
            group.run_on_all("setup_collective", group_name,
                             self.collective_backend)
            cfg["_collective_group"] = group_name
        if restore_from is not None and epoch == 0:
            cfg["_checkpoint_path"] = restore_from.path
        if self.run_config.storage_path:
            cfg["_storage_path"] = self.run_config.storage_path
        targets = group.workers if workers is None else workers
        ray_tpu.get([w.run.remote(blob, cfg) for w in targets], timeout=300)

    def _drive_elastic(self, group: WorkerGroup,
                       node_events: "_queue.Queue",
                       history: list, run_id: str, blob: bytes) -> Result:
        elastic: ElasticConfig = self.scaling_config.elastic
        target_size = elastic.max_workers or self.scaling_config.num_workers
        last_ckpt: Checkpoint | None = None
        final_metrics: dict = dict(history[-1]) if history else {}
        epoch = 0
        node_of = dict(zip(group.workers, group.run_on_all("node_id")))
        next_grow_check = time.monotonic() + elastic.grow_poll_s
        grow_hint = False

        def fold(w_polls):
            nonlocal final_metrics, last_ckpt
            for p in w_polls:
                if p is None:
                    continue
                for rep in p.get("reports", []):
                    if rep.get("rank") == 0 and "metrics" in rep:
                        history.append(rep["metrics"])
                        final_metrics = rep["metrics"]
                        self.latest_metrics = final_metrics
                        if rep.get("checkpoint_path"):
                            last_ckpt = Checkpoint(rep["checkpoint_path"])

        while True:
            # 1. Pre-death signals: NODE state transitions from the GCS.
            shrink_nodes: set[str] = set()
            while True:
                try:
                    ev = node_events.get_nowait()
                except _queue.Empty:
                    break
                nid = ev.get("node_id") \
                    or (ev.get("node") or {}).get("node_id")
                if ev.get("event") in ("draining", "dead") \
                        and nid in node_of.values():
                    shrink_nodes.add(nid)
                elif ev.get("event") == "alive":
                    grow_hint = True  # capacity restored: probe now

            # 2. Poll the gang — per worker, because a drained member may
            # be killed (deadline expiry / spot reclaim) between the
            # pre-death signal and our resize. A death WITH a pre-death
            # signal (its node is draining or already recorded dead) is
            # still a resize; a death with no signal at all is the next
            # rung of the ladder.
            polls = []
            for w in list(group.workers):
                try:
                    polls.append(ray_tpu.get(w.poll.remote(), timeout=300))
                except exc.RayTpuError as e:
                    nid = node_of.get(w)
                    if nid and (nid in shrink_nodes
                                or not _node_is_alive(nid)):
                        shrink_nodes.add(nid)
                        polls.append(None)
                        continue
                    e._last_checkpoint = last_ckpt
                    raise
            fold(polls)
            error = next((f"worker {i}: {p['error']}"
                          for i, p in enumerate(polls)
                          if p and p["done"] and p["error"]), None)
            if error:
                err = exc.RayTpuError(f"training failed: {error}")
                err._last_checkpoint = last_ckpt
                raise err
            if not shrink_nodes and all(p["done"] for p in polls):
                return Result(metrics=final_metrics, checkpoint=last_ckpt,
                              error=None, metrics_history=history)

            # 3. Shrink: re-shard off the draining members.
            if shrink_nodes:
                survivors = [w for w in group.workers
                             if node_of.get(w) not in shrink_nodes]
                if len(survivors) < elastic.min_workers:
                    err = exc.RayTpuError(
                        f"elastic shrink would leave {len(survivors)} < "
                        f"min_workers={elastic.min_workers} workers")
                    err._last_checkpoint = last_ckpt
                    raise err
                epoch += 1
                self._resize(group, survivors, 0, elastic, run_id, blob,
                             epoch, fold, last_ckpt, direction="shrink")
                node_of = dict(zip(group.workers,
                                   group.run_on_all("node_id")))
                continue

            # 4. Grow back when capacity returns.
            now = time.monotonic()
            if (grow_hint or now >= next_grow_check) \
                    and len(group.workers) < target_size \
                    and not any(p["done"] for p in polls if p):
                grow_hint = False
                next_grow_check = now + elastic.grow_poll_s
                room = _free_worker_slots(self.scaling_config,
                                          exclude=set(node_of.values()))
                n_new = min(room, target_size - len(group.workers))
                if n_new > 0:
                    epoch += 1
                    self._resize(group, list(group.workers), n_new,
                                 elastic, run_id, blob, epoch, fold,
                                 last_ckpt, direction="grow")
                    node_of = dict(zip(group.workers,
                                       group.run_on_all("node_id")))
            time.sleep(0.05)

    def _resize(self, group: WorkerGroup, survivors: list, n_new: int,
                elastic: ElasticConfig, run_id: str, blob: bytes,
                epoch: int, fold, last_ckpt, *, direction: str) -> None:
        """One membership change: pause at the step boundary, re-home
        state through the device plane, rebuild the rendezvous, resume.
        Any failure raises RayTpuError carrying the newest checkpoint —
        fit()'s retry loop is the (counted) fallback rung."""
        from ray_tpu._private import device_objects
        from ray_tpu._private.api_internal import get_core_worker

        cw = get_core_worker()
        deadline = time.monotonic() + elastic.reshard_timeout_s
        departing = [w for w in group.workers if w not in survivors]

        def fallback(why: str):
            err = exc.RayTpuError(f"elastic {direction} failed: {why}")
            err._last_checkpoint = last_ckpt
            return err

        # a. Pause everyone at the next step boundary.
        for w in group.workers:
            w.request_pause.remote()
        lost_alive: set = set()
        max_step = -1
        survivor_steps: list[int] = []
        park_detail: list = []
        while True:
            parked = True
            survivor_steps = []
            park_detail = []
            for i, w in enumerate(group.workers):
                if w in lost_alive:
                    continue
                try:
                    p = ray_tpu.get(w.poll.remote(), timeout=30)
                except exc.RayTpuError:
                    # Died mid-pause. A departing member may already have
                    # been killed by an expired drain deadline; survivors
                    # dying here means the elastic path is off the table.
                    if w in departing:
                        lost_alive.add(w)
                        continue
                    raise fallback("survivor died during pause")
                fold([p])
                max_step = max(max_step, p.get("state_step", -1))
                park_detail.append({"i": i, "departing": w in departing,
                                    "paused": p.get("paused"),
                                    "done": p.get("done"),
                                    "state_step": p.get("state_step")})
                if not (p.get("paused") or p.get("done")):
                    parked = False
                elif w in survivors:
                    s_step = p.get("state_step", -1)
                    # state_step < 0 = still warming up (never reached
                    # keep_state): zero steps computed, zero lost.
                    if s_step >= 0:
                        survivor_steps.append(s_step)
            if parked:
                break
            if time.monotonic() > deadline:
                raise fallback("gang did not reach a step boundary "
                               f"within {elastic.reshard_timeout_s:g}s")
            time.sleep(0.02)

        # b. Re-home departing state: resolve each departing rank's kept
        # tree through the device plane — pulled from the worker while
        # it lives, or found re-pinned in OUR registry if the drain
        # pipeline already evacuated it (same keys either way).
        peer_states: dict[int, Any] = {}
        for w in departing:
            if w in lost_alive:
                continue
            old_rank = group.workers.index(w)
            try:
                exp = ray_tpu.get(w.export_state.remote(),
                                  timeout=max(5.0, deadline - time.monotonic()))
            except exc.RayTpuError:
                lost_alive.add(w)
                continue
            if exp.get("stub") is None:
                continue
            try:
                peer_states[old_rank] = device_objects.resolve_value(
                    exp["stub"], cw)
            except Exception as e:
                raise fallback(f"could not re-shard rank {old_rank} "
                               f"state: {e}") from e
        if lost_alive and not peer_states and direction == "shrink":
            # The departing members died before handing anything over
            # and nothing was evacuated: survivors resume from their own
            # kept state; DP-style loops tolerate a lost shard. Counted
            # via steps_lost below.
            pass

        # c. Retire departing members NOW — frees their leases so the
        # draining raylet's bounded lease wait ends promptly.
        for w in departing:
            group.remove_worker(w, stop_timeout_s=1.0)

        # d. Grow: schedule the new members (DRAINING nodes are already
        # excluded from placement).
        new_world = len(survivors) + n_new
        new_workers = [group.add_worker(len(survivors) + j, new_world)
                       for j in range(n_new)]

        # e. New gang shape: ranks follow list order.
        ray_tpu.get([w.reconfigure.remote(i, new_world)
                     for i, w in enumerate(group.workers)], timeout=60)

        # f. Hand the re-homed state over. Shrink: every survivor gets
        # the departed ranks' trees through ONE device object. Grow: new
        # members get rank 0's stub tree and pull the arrays straight
        # from rank 0's process (no extra driver hop).
        try:
            if peer_states:
                ref = device_objects.device_put(peer_states)
                try:
                    ray_tpu.get([w.receive_peer_states.remote(ref)
                                 for w in survivors], timeout=120)
                finally:
                    del ref
            if new_workers:
                seed = ray_tpu.get(survivors[0].export_state.remote(),
                                   timeout=30)
                if seed.get("stub") is not None:
                    ray_tpu.get([w.receive_peer_states.remote(
                        {0: seed["stub"]}) for w in new_workers],
                        timeout=120)
        except exc.RayTpuError as e:
            raise fallback(f"state hand-off failed: {e}") from e

        # g. Rebuild the rendezvous + resume at step N+1.
        self._start_epoch(group, run_id, epoch, blob, None)

        resumed_from = min(survivor_steps) if survivor_steps else -1
        lost = max(0, max_step - resumed_from) \
            if (max_step >= 0 and survivor_steps) else 0
        self.telemetry.setdefault("resize_log", []).append(
            {"direction": direction, "lost": lost, "max_step": max_step,
             "resumed_from": resumed_from,
             "survivor_steps": list(survivor_steps),
             "park_detail": park_detail})
        self.telemetry["resizes"] += 1
        self.telemetry[direction + "s"] += 1
        self.telemetry["steps_lost"] += lost
        _note_elastic(direction, steps_lost=lost)


def _node_is_alive(node_id: str) -> bool:
    try:
        for node in ray_tpu.nodes():
            if node.get("node_id") == node_id:
                return bool(node.get("alive")) \
                    and node.get("state") in (None, "ALIVE")
    except Exception:
        pass
    return False


def _free_worker_slots(scaling: ScalingConfig, exclude: set) -> int:
    """How many more workers the cluster could place right now, from
    the GCS node table's available resources (ALIVE, not draining, and
    not already hosting this gang's members when PACK-per-node
    semantics apply — excluded node_ids are simply skipped)."""
    need = scaling.worker_resources()
    slots = 0
    try:
        nodes = ray_tpu.nodes()
    except Exception:
        return 0
    for node in nodes:
        if not node.get("alive", False):
            continue
        if node.get("state") not in (None, "ALIVE"):
            continue
        if node.get("node_id") in exclude:
            continue
        avail = node.get("available_resources") or {}
        per_node = None
        for res, amount in need.items():
            if amount <= 0:
                continue
            fit = int(avail.get(res, 0.0) // amount)
            per_node = fit if per_node is None else min(per_node, fit)
        slots += per_node if per_node is not None else 0
    return slots


def _note_elastic(event: str, steps_lost: int = 0) -> None:
    try:
        from ray_tpu.util import metrics

        metrics.note_train_elastic(event, steps_lost=steps_lost)
    except Exception:
        pass
