"""JaxTrainer: the Train entry point.

Parity: reference python/ray/train/data_parallel_trainer.py:59
(DataParallelTrainer.fit → BackendExecutor → WorkerGroup → per-worker
session) and base_trainer.py:608 (fit). The torch backend's
`dist.init_process_group(nccl)` (reference: train/torch/config.py:63)
becomes: (a) a host-plane collective group for multi-process DP, and
(b) on TPU pods, `jax.distributed.initialize` coordinator env wiring so
every worker joins one multi-host SPMD program.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import serialization
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class Result:
    """Parity: ray.air.result.Result."""

    metrics: dict
    checkpoint: Checkpoint | None
    error: str | None
    metrics_history: list = field(default_factory=list)

    @property
    def best_checkpoint(self):
        return self.checkpoint


class JaxTrainer:
    """Runs `train_loop_per_worker` on a gang of workers.

    collective_backend: "cpu" (host-plane allreduce group, the gloo-DDP
    analog) or "xla" (workers form one multi-host jax.distributed world;
    each worker then compiles the SPMD step over the global mesh) or None.
    """

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 collective_backend: str | None = "cpu"):
        self._train_loop = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.collective_backend = collective_backend

    def fit(self) -> Result:
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        restore_from: Checkpoint | None = None
        while True:
            try:
                return self._fit_once(restore_from)
            except exc.RayTpuError as e:
                attempt += 1
                if attempt > max_failures:
                    raise
                # Elastic restart (reference: FailureConfig retries restore
                # from the latest reported checkpoint — XLA programs are
                # fixed-shape over a fixed mesh, so elasticity IS
                # checkpoint-restart): the fresh worker gang resumes via
                # session.get_checkpoint().
                restore_from = getattr(e, "_last_checkpoint", None) \
                    or restore_from
                time.sleep(1.0)

    def _fit_once(self, restore_from: "Checkpoint | None" = None) -> Result:
        run_id = uuid.uuid4().hex[:8]
        group = WorkerGroup(self.scaling_config)
        try:
            if self.collective_backend and self.scaling_config.num_workers > 1:
                group_name = f"train:{run_id}"
                group.run_on_all("setup_collective", group_name,
                                 self.collective_backend)
                cfg = dict(self._config)
                cfg["_collective_group"] = group_name
            else:
                cfg = dict(self._config)
            if restore_from is not None:
                cfg["_checkpoint_path"] = restore_from.path
            if self.run_config.storage_path:
                # Dict checkpoints land under durable storage instead of a
                # node-local tempdir — on real node loss the retry gang (on
                # other hosts) must still reach them (shared-fs semantics,
                # same as the reference's storage_path contract).
                cfg["_storage_path"] = self.run_config.storage_path
            blob = serialization.dumps_func(self._train_loop)
            group.run_on_all("run", blob, cfg)
            return self._drive(group)
        finally:
            group.shutdown()

    def _drive(self, group: WorkerGroup) -> Result:
        """Poll workers, surface rank-0 reports (reference:
        TrainingIterator in data_parallel_trainer.py:429)."""
        history: list[dict] = []
        last_ckpt: Checkpoint | None = None
        done = [False] * len(group.workers)
        error: str | None = None
        final_metrics: dict = {}
        while not all(done):
            try:
                polls = ray_tpu.get(
                    [w.poll.remote() for w in group.workers], timeout=300)
            except exc.RayTpuError as e:
                # Worker actor died (node loss, OOM kill): the retry loop
                # needs the newest checkpoint seen before the crash.
                e._last_checkpoint = last_ckpt
                raise
            for i, p in enumerate(polls):
                for rep in p["reports"]:
                    if rep["rank"] == 0:
                        history.append(rep["metrics"])
                        final_metrics = rep["metrics"]
                        if rep.get("checkpoint_path"):
                            last_ckpt = Checkpoint(rep["checkpoint_path"])
                if p["done"]:
                    done[i] = True
                    if p["error"] and error is None:
                        error = f"worker {i}: {p['error']}"
            if error:
                err = exc.RayTpuError(f"training failed: {error}")
                # Carried to fit()'s retry loop for checkpoint restore.
                err._last_checkpoint = last_ckpt
                raise err
            if not all(done):
                time.sleep(0.05)
        return Result(metrics=final_metrics, checkpoint=last_ckpt,
                      error=None, metrics_history=history)
