"""Train/AIR-style configuration dataclasses.

Parity: reference python/ray/air/config.py — ScalingConfig:94,
RunConfig:723, CheckpointConfig:574, FailureConfig:523. TPU-first change:
ScalingConfig speaks chips/hosts and ICI topology instead of GPUs, and
carries the SPMD mesh shape (which the reference cannot express at all).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class ElasticConfig:
    """Resize envelope for elastic gang training (tentpole of the drain
    ladder: a DRAINING member triggers a pause → device-plane re-shard →
    resume on the survivors, never a checkpoint restart).

    min_workers: smallest gang that keeps training (below it the elastic
      path gives up and falls back to checkpoint restart).
    max_workers: grow-back ceiling (defaults to ScalingConfig.num_workers).
    reshard_timeout_s: budget for one resize (pause + state hand-off +
      rendezvous rebuild); overrunning it falls back to checkpoint.
    grow_poll_s: how often the trainer probes for restored capacity.
    """

    min_workers: int = 1
    max_workers: int | None = None
    reshard_timeout_s: float = 30.0
    grow_poll_s: float = 2.0


@dataclass
class ScalingConfig:
    """How many workers, what resources, and (TPU-first) the mesh.

    num_workers: worker processes (one per TPU host for multi-host SPMD).
    use_tpu: schedule each worker with `tpu_chips_per_worker` TPU chips.
    mesh: logical mesh axis sizes for the in-worker SPMD program
      (dp/fsdp/tp/pp/sp/ep), passed to ray_tpu.parallel.make_mesh.
    placement_strategy: PACK/SPREAD/STRICT_PACK/STRICT_SPREAD/STRICT_ICI —
      STRICT_ICI gang-places all workers on one ICI-connected slice.
    elastic: opt the gang into drain-driven resize. Elastic gangs are
      scheduled without a placement group (membership changes at runtime;
      DRAINING nodes are already excluded from placement), so elastic
      excludes the STRICT_* strategies.
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpu_chips_per_worker: int = 4
    resources_per_worker: dict | None = None
    mesh: dict | None = None
    placement_strategy: str = "PACK"
    trainer_resources: dict | None = None
    elastic: ElasticConfig | None = None

    def worker_resources(self) -> dict:
        res = dict(self.resources_per_worker or {})
        if self.use_tpu:
            res.setdefault("TPU", float(self.tpu_chips_per_worker))
            res.setdefault("CPU", 1.0)
        else:
            res.setdefault("CPU", 1.0)
        return res

    def as_placement_group_bundles(self) -> list[dict]:
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclass
class CheckpointConfig:
    num_to_keep: int | None = None
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = True


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    verbose: int = 1
