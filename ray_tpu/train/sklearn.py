"""SklearnTrainer: fit a scikit-learn estimator as a Train run.

Parity: reference python/ray/train/sklearn/sklearn_trainer.py — the
estimator fits on ONE remote worker (sklearn has no distributed
engine; `parallelize_cv` maps CV folds over joblib workers, which the
ray_tpu joblib backend can in turn fan out), metrics report through
the session, and the fitted estimator lands in the checkpoint.
"""

from __future__ import annotations

import pickle
from typing import Any

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import JaxTrainer, Result


class SklearnTrainer(JaxTrainer):
    """fit() runs estimator.fit(X, y) in a worker; the Result carries
    scores and a checkpoint holding the pickled fitted estimator
    (load it back with `SklearnTrainer.get_model(result.checkpoint)`).
    """

    def __init__(self, *, estimator: Any, datasets: dict,
                 label_column: str | None = None,
                 scoring: str | None = None,
                 params: dict | None = None,
                 run_config: RunConfig | None = None):
        est_blob = pickle.dumps(estimator)

        def rows_to_xy(rows, label):
            import numpy as np

            if label is None:
                X = np.asarray([[r[k] for k in sorted(r)] for r in rows])
                return X, None
            feats = [k for k in sorted(rows[0]) if k != label]
            X = np.asarray([[r[k] for k in feats] for r in rows],
                           np.float64)
            y = np.asarray([r[label] for r in rows])
            return X, y

        def materialize(ds):
            # Datasets ship LAZY (the plan pickles with the loop) and
            # execute on the worker at fit time — constructing the
            # trainer must not pull rows onto the driver.
            if ds is None:
                return None
            return ds.take_all() if hasattr(ds, "take_all") else list(ds)

        def score_of(est, X, y, scoring_name):
            if scoring_name:
                from sklearn.metrics import get_scorer

                return float(get_scorer(scoring_name)(est, X, y))
            return float(est.score(X, y))

        train_ds = datasets["train"]
        valid_ds = datasets.get("valid")

        def loop(config):
            import pickle as _pickle

            import numpy as np

            from ray_tpu.train import session

            est = _pickle.loads(config["est_blob"])
            if config["params"]:
                est.set_params(**config["params"])
            train_rows = materialize(train_ds)
            X, y = rows_to_xy(train_rows, config["label"])
            est.fit(X, y)
            metrics = {}
            if y is not None:
                metrics["train_score"] = score_of(est, X, y,
                                                  config["scoring"])
            valid_rows = materialize(valid_ds)
            if valid_rows:
                Xv, yv = rows_to_xy(valid_rows, config["label"])
                if yv is not None:
                    metrics["valid_score"] = score_of(est, Xv, yv,
                                                      config["scoring"])
            # The checkpoint pytree store holds arrays, not raw bytes:
            # ship the pickle as uint8.
            blob = np.frombuffer(_pickle.dumps(est), dtype=np.uint8)
            session.report(metrics, checkpoint={"estimator": blob})

        super().__init__(
            loop,
            train_loop_config={"est_blob": est_blob,
                               "label": label_column,
                               "scoring": scoring,
                               "params": params or {}},
            scaling_config=ScalingConfig(num_workers=1, use_tpu=False),
            run_config=run_config, collective_backend=None)

    @staticmethod
    def get_model(checkpoint) -> Any:
        """Unpickle the fitted estimator from a fit() checkpoint."""
        import numpy as np

        data = checkpoint.to_dict() if hasattr(checkpoint, "to_dict") \
            else checkpoint
        blob = data["estimator"]
        if not isinstance(blob, (bytes, bytearray)):
            blob = np.asarray(blob, dtype=np.uint8).tobytes()
        return pickle.loads(blob)


__all__ = ["SklearnTrainer", "Result"]
