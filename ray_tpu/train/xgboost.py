"""XGBoostTrainer: data-parallel gradient-boosted trees as a Train run.

Parity: reference python/ray/train/xgboost/xgboost_trainer.py (over
xgboost_ray): actors each hold a shard of the dataset and run the
UNMODIFIED xgboost distributed algorithm — the framework provides
orchestration (actor gang, shard assignment, rabit tracker bring-up,
result/checkpoint collection), never reimplements boosting.

xgboost is a soft dependency (not in this image): the trainer imports
it lazily on the driver (for the tracker) and inside workers (for
training). tests/test_train_xgboost.py runs the whole orchestration
hermetically against a fake `xgboost` package shipped to workers via
runtime_env py_modules — the same pattern as the autoscaler's fake
gcloud/aws binaries.
"""

from __future__ import annotations

import pickle
from typing import Any

import ray_tpu
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import Result


def _xgb_worker(rank: int, world: int, rows: list, label: str,
                params: dict, num_boost_round: int, rabit_args: dict,
                eval_rows: dict):
    """Runs in a worker actor: join the xgboost collective and train on
    this shard. Returns (evals_result, pickled booster from rank 0)."""
    import numpy as np
    import xgboost as xgb

    feats = [k for k in sorted(rows[0]) if k != label]
    X = np.asarray([[r[k] for k in feats] for r in rows], np.float64)
    y = np.asarray([r[label] for r in rows], np.float64)
    dtrain = xgb.DMatrix(X, label=y)
    evals = [(dtrain, "train")]
    for name, erows in eval_rows.items():
        eX = np.asarray([[r[k] for k in feats] for r in erows], np.float64)
        ey = np.asarray([r[label] for r in erows], np.float64)
        evals.append((xgb.DMatrix(eX, label=ey), name))

    evals_result: dict = {}

    def train():
        booster = xgb.train(params, dtrain,
                            num_boost_round=num_boost_round,
                            evals=evals, evals_result=evals_result,
                            verbose_eval=False)
        return booster

    if world > 1:
        # xgboost's own collective (rabit) synchronizes gradients; the
        # framework only wires the tracker args through.
        with xgb.collective.CommunicatorContext(**rabit_args):
            booster = train()
    else:
        booster = train()
    blob = pickle.dumps(booster) if rank == 0 else None
    return evals_result, blob


@ray_tpu.remote
class _XGBWorker:
    def run(self, *args):
        return _xgb_worker(*args)


class XGBoostTrainer:
    """fit() shards datasets["train"] across scaling_config.num_workers
    actors and runs distributed xgboost; non-train datasets become eval
    sets, each reporting its own metric curve (reference semantics)."""

    def __init__(self, *, datasets: dict, label_column: str,
                 params: dict | None = None, num_boost_round: int = 10,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 runtime_env: dict | None = None):
        if "train" not in datasets:
            raise ValueError('datasets must include a "train" key')
        self.datasets = datasets
        self.label_column = label_column
        self.params = dict(params or {})
        self.num_boost_round = num_boost_round
        self.scaling_config = scaling_config or ScalingConfig(num_workers=1)
        self.run_config = run_config
        self.runtime_env = runtime_env

    def _tracker_args(self, world: int) -> dict:
        """Start a rabit tracker on the driver; returns the env args every
        worker passes to CommunicatorContext (reference: xgboost_ray's
        _start_rabit_tracker)."""
        if world <= 1:
            return {}
        from xgboost.tracker import RabitTracker

        tracker = RabitTracker(host_ip="127.0.0.1", n_workers=world)
        tracker.start(world)
        self._tracker = tracker
        args = tracker.worker_envs() if hasattr(tracker, "worker_envs") \
            else tracker.worker_args()
        return dict(args)

    def fit(self) -> Result:
        world = self.scaling_config.num_workers
        train_rows = self.datasets["train"].take_all()
        if not train_rows:
            raise ValueError("empty training dataset")
        eval_rows = {name: ds.take_all()
                     for name, ds in self.datasets.items()
                     if name != "train"}
        shards = [train_rows[i::world] for i in range(world)]
        rabit_args = self._tracker_args(world)
        opts = {}
        if self.runtime_env:
            opts["runtime_env"] = self.runtime_env
        workers = [_XGBWorker.options(**opts).remote() if opts
                   else _XGBWorker.remote() for _ in range(world)]
        try:
            outs = ray_tpu.get(
                [w.run.remote(rank, world, shards[rank], self.label_column,
                              self.params, self.num_boost_round,
                              rabit_args, eval_rows)
                 for rank, w in enumerate(workers)],
                timeout=600)
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            tracker = getattr(self, "_tracker", None)
            if tracker is not None and hasattr(tracker, "free"):
                try:
                    tracker.free()
                except Exception:
                    pass
        evals_result, booster_blob = outs[0]
        metrics = {}
        for split, curves in evals_result.items():
            for metric_name, values in curves.items():
                metrics[f"{split}-{metric_name}"] = values[-1]
        return Result(metrics=metrics,
                      checkpoint={"booster": booster_blob},
                      error=None)

    @staticmethod
    def get_model(checkpoint) -> Any:
        """Deserialize the trained booster from a fit() checkpoint."""
        blob = checkpoint["booster"] if isinstance(checkpoint, dict) \
            else checkpoint
        return pickle.loads(blob)
