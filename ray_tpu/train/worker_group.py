"""WorkerGroup + BackendExecutor: the Train actor topology.

Parity: reference python/ray/train/_internal/worker_group.py:102
(WorkerGroup over RayTrainWorker actors), backend_executor.py:68 (start:134
creates the placement group; :291-344 shares accelerator visibility incl.
TPU chips), session.py:132 (per-worker _TrainSession runs the user loop in
a thread and streams report()s).

TPU-native differences: backend setup is `jax.distributed.initialize`
rendezvous via env vars (not torch process groups), and workers are
gang-placed with STRICT_ICI when training spans a pod slice.
"""

from __future__ import annotations

import os
import queue
import threading
import traceback
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.config import ScalingConfig
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class TrainWorker:
    """One training worker process (reference: RayTrainWorker:19)."""

    def __init__(self, rank: int, world_size: int, env: dict | None = None):
        self.rank = rank
        self.world_size = world_size
        self._reports: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._done = False
        self._error: str | None = None
        self._result = None
        for k, v in (env or {}).items():
            os.environ[k] = str(v)
        os.environ["RAY_TPU_TRAIN_RANK"] = str(rank)
        os.environ["RAY_TPU_TRAIN_WORLD_SIZE"] = str(world_size)

    def setup_collective(self, group_name: str, backend: str) -> bool:
        from ray_tpu.util.collective import init_collective_group

        init_collective_group(self.world_size, self.rank, backend=backend,
                              group_name=group_name)
        return True

    def run(self, fn_blob: bytes, config: dict) -> bool:
        """Start the user train loop in a thread (session semantics)."""
        from ray_tpu._private import serialization
        from ray_tpu.train import session

        fn = serialization.loads_func(fn_blob)

        def target():
            session._set_session(session._Session(
                rank=self.rank, world_size=self.world_size,
                report_queue=self._reports,
                restore_checkpoint_path=config.get("_checkpoint_path"),
                storage_path=config.get("_storage_path")))
            try:
                self._result = fn(config) if _wants_arg(fn) else fn()
            except BaseException as e:  # noqa: BLE001
                self._error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
            finally:
                self._done = True
                session._set_session(None)

        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()
        return True

    def poll(self, max_items: int = 100) -> dict:
        """Drain buffered report()s; say whether the loop finished."""
        items = []
        while len(items) < max_items:
            try:
                items.append(self._reports.get_nowait())
            except queue.Empty:
                break
        return {"reports": items, "done": self._done, "error": self._error,
                "result": self._result if self._done and not self._error else None}

    def receive_weights(self, weights) -> dict:
        """Device-plane weight broadcast sink: `weights` arrives already
        resolved (the ref's descriptor pulled the tensors straight from
        the broadcaster's registry — no GCS/plasma round trip). Stored
        for the train loop (session.get_broadcast_weights)."""
        self._broadcast_weights = weights
        from ray_tpu._private.device_objects import tree_map

        leaves: list = []
        tree_map(weights, leaves.append, lambda v: hasattr(v, "shape"))
        # nbytes is metadata on jax.Array AND ndarray — no host gather
        # (np.asarray here would DMA the whole model back to host just
        # to report a size).
        return {"rank": self.rank, "leaves": len(leaves),
                "bytes": int(sum(getattr(x, "nbytes", 0) for x in leaves))}

    def node_id(self) -> str:
        return ray_tpu.get_runtime_context().node_id

    def shutdown(self) -> bool:
        return True


def _wants_arg(fn) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) > 0
    except (TypeError, ValueError):
        return False


class WorkerGroup:
    def __init__(self, scaling: ScalingConfig, env: dict | None = None):
        self.scaling = scaling
        self.pg = None
        n = scaling.num_workers
        if n > 1 or scaling.placement_strategy != "PACK":
            self.pg = placement_group(scaling.as_placement_group_bundles(),
                                      strategy=scaling.placement_strategy)
            if not self.pg.wait(timeout=120):
                from ray_tpu import exceptions as exc

                remove_placement_group(self.pg)
                raise exc.PlacementGroupSchedulingError(
                    f"train worker placement group "
                    f"({scaling.as_placement_group_bundles()}) not schedulable "
                    f"within 120s — not enough free cluster resources")
        self.workers = []
        res = scaling.worker_resources()
        for rank in range(n):
            opts = {"num_cpus": res.get("CPU", 1.0),
                    "resources": {k: v for k, v in res.items() if k != "CPU"}}
            if self.pg is not None:
                opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=self.pg, placement_group_bundle_index=rank)
            self.workers.append(
                TrainWorker.options(**opts).remote(rank, n, env or {}))

    def run_on_all(self, method: str, *args, **kwargs) -> list:
        return ray_tpu.get([getattr(w, method).remote(*args, **kwargs)
                            for w in self.workers], timeout=300)

    def broadcast_weights(self, params) -> list:
        """Broadcast initial weights to every worker through ONE device
        object (the train-side device-plane consumer): the driver pins
        the jax param tree in its own registry, the object path carries
        only the descriptor, and each worker pulls the tensors directly
        from the driver — collective route on a shared mesh, host path
        otherwise; never through the GCS or a pickle round trip. Trees
        with no jax.Array leaves degrade to a plain put transparently."""
        from ray_tpu._private import device_objects

        ref = device_objects.device_put(params)
        try:
            return ray_tpu.get(
                [w.receive_weights.remote(ref) for w in self.workers],
                timeout=300)
        finally:
            del ref  # drop the pin once every worker has its copy

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
