"""WorkerGroup + BackendExecutor: the Train actor topology.

Parity: reference python/ray/train/_internal/worker_group.py:102
(WorkerGroup over RayTrainWorker actors), backend_executor.py:68 (start:134
creates the placement group; :291-344 shares accelerator visibility incl.
TPU chips), session.py:132 (per-worker _TrainSession runs the user loop in
a thread and streams report()s).

TPU-native differences: backend setup is `jax.distributed.initialize`
rendezvous via env vars (not torch process groups), and workers are
gang-placed with STRICT_ICI when training spans a pod slice.
"""

from __future__ import annotations

import os
import queue
import threading
import traceback
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.config import ScalingConfig
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@ray_tpu.remote
class TrainWorker:
    """One training worker process (reference: RayTrainWorker:19)."""

    def __init__(self, rank: int, world_size: int, env: dict | None = None):
        import uuid

        self.rank = rank
        self.world_size = world_size
        self._reports: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._done = False
        self._paused = False
        self._error: str | None = None
        self._result = None
        # Elastic state: the user loop's preserved pytree (keep_state),
        # its device-registry pin prefix, and the stub tree the trainer
        # resolves it through. _uid (not rank) keys the pin prefix —
        # ranks are reassigned across resizes, registry keys must not be.
        self._ctl = None
        self._elastic_state = None
        self._elastic_stub = None
        self._state_step = -1
        self._elastic_prefix: str | None = None
        self._pin_seq = 0
        self._owner_wire = None
        self._peer_states: dict | None = None
        self._elastic_epoch = 0
        self._uid = uuid.uuid4().hex[:8]
        self._drain_listener = False
        for k, v in (env or {}).items():
            os.environ[k] = str(v)
        os.environ["RAY_TPU_TRAIN_RANK"] = str(rank)
        os.environ["RAY_TPU_TRAIN_WORLD_SIZE"] = str(world_size)

    def setup_collective(self, group_name: str, backend: str) -> bool:
        from ray_tpu.util.collective import init_collective_group

        init_collective_group(self.world_size, self.rank, backend=backend,
                              group_name=group_name)
        return True

    def run(self, fn_blob: bytes, config: dict) -> bool:
        """Start the user train loop in a thread (session semantics)."""
        from ray_tpu._private import serialization
        from ray_tpu.train import session

        prev = self._thread
        if prev is not None and prev.is_alive():
            prev.join(timeout=5.0)
        fn = serialization.loads_func(fn_blob)
        self._owner_wire = config.get("_elastic_owner") or self._owner_wire
        self._elastic_epoch = int(config.get("_elastic_epoch", 0))
        if config.get("_elastic") and not self._drain_listener:
            self._register_drain_listener()
        ctl = session._SessionControl()
        self._ctl = ctl
        self._paused = False
        self._done = False
        self._error = None

        def target():
            session._set_session(session._Session(
                rank=self.rank, world_size=self.world_size,
                report_queue=self._reports,
                restore_checkpoint_path=config.get("_checkpoint_path"),
                storage_path=config.get("_storage_path"),
                control=ctl,
                elastic_state=self._elastic_state,
                elastic_state_step=(self._state_step
                                    if self._state_step >= 0 else None),
                peer_states=self._peer_states,
                elastic_epoch=self._elastic_epoch,
                on_keep_state=self._keep_state))
            try:
                self._result = fn(config) if _wants_arg(fn) else fn()
            except session.ElasticPauseInterrupt:
                self._paused = True
            except session.SessionStopped:
                pass
            except BaseException as e:  # noqa: BLE001
                self._error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
            finally:
                if not self._paused:
                    self._done = True
                session._set_session(None)

        self._thread = threading.Thread(target=target, daemon=True)
        self._thread.start()
        return True

    def _register_drain_listener(self):
        """Worker-side pre-death signal (defense in depth next to the
        trainer's GCS NODE subscription): the raylet fans a DrainNotice
        to its workers at the top of _run_drain, and a draining gang
        member parks itself at the next step boundary even if the
        trainer's publish is still in flight."""
        try:
            from ray_tpu._private.api_internal import get_core_worker

            cw = get_core_worker()
            cw.add_drain_notice_listener(lambda payload: self._on_drain())
            self._drain_listener = True
        except Exception:
            pass  # non-fatal: the trainer-side signal still pauses us

    def _on_drain(self):
        ctl = self._ctl
        if ctl is not None:
            ctl.pause_requested.set()

    def _keep_state(self, state, step: int):
        """session.keep_state hook (runs on the user-loop thread): pin
        the tree's jax leaves with the TRAINER as ref owner so a node
        drain evacuates them to the trainer (device_objects.evacuate →
        DeviceObjectRepin), and keep a stub tree the trainer can resolve
        from either end."""
        self._elastic_state = state
        self._state_step = int(step)
        stub = state
        if self._owner_wire is not None:
            from ray_tpu._private import device_objects
            from ray_tpu._private.api_internal import get_core_worker

            self._pin_seq += 1
            prefix = f"elastic:{self._uid}:{self._pin_seq}"
            stubbed, _nbytes, n = device_objects.extract_arrays(
                state, prefix, get_core_worker())
            if n:
                reg = device_objects.registry()
                reg.note_ref_owner(prefix, self._owner_wire)
                old, self._elastic_prefix = self._elastic_prefix, prefix
                stub = stubbed
                if old:
                    reg.release_prefix(old, counted=False)
        self._elastic_stub = stub

    def poll(self, max_items: int = 100) -> dict:
        """Drain buffered report()s; say whether the loop finished."""
        items = []
        while len(items) < max_items:
            try:
                items.append(self._reports.get_nowait())
            except queue.Empty:
                break
        return {"reports": items, "done": self._done, "error": self._error,
                "paused": self._paused, "state_step": self._state_step,
                "result": self._result if self._done and not self._error else None}

    def request_pause(self) -> bool:
        """Ask the user loop to park at its next step boundary."""
        ctl = self._ctl
        if ctl is not None:
            ctl.pause_requested.set()
        return ctl is not None

    def stop(self, timeout: float = 5.0) -> dict:
        """Graceful session shutdown: request a stop at the next step
        boundary and JOIN the user-loop thread, so migration/teardown
        never kills the worker mid-report() and loses the final
        checkpoint pointer. Returns the final drained reports plus
        whether the join landed."""
        ctl = self._ctl
        if ctl is not None:
            ctl.stop_requested.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        out = self.poll(max_items=1_000_000)
        out["joined"] = t is None or not t.is_alive()
        return out

    def reconfigure(self, rank: int, world_size: int) -> bool:
        """Adopt a new gang shape (call only while paused/done)."""
        self.rank = rank
        self.world_size = world_size
        os.environ["RAY_TPU_TRAIN_RANK"] = str(rank)
        os.environ["RAY_TPU_TRAIN_WORLD_SIZE"] = str(world_size)
        return True

    def export_state(self) -> dict:
        """The preserved state as a stub tree (device plane carries the
        arrays; the trainer resolves — from this process while it lives,
        from the trainer's own registry after a drain evacuated the
        pins) plus the step it was kept at."""
        return {"stub": self._elastic_stub, "step": self._state_step}

    def receive_peer_states(self, states) -> bool:
        """Peer state trees for the next run(): either a device-object
        ref (shrink — resolved before the call lands) or a raw stub tree
        (grow — resolved HERE, pulling the arrays straight from the
        pinning survivor instead of bouncing through the trainer)."""
        from ray_tpu._private import device_objects
        from ray_tpu._private.api_internal import get_core_worker

        self._peer_states = {
            k: device_objects.resolve_value(v, get_core_worker())
            for k, v in (states or {}).items()}
        return True

    def receive_weights(self, weights) -> dict:
        """Device-plane weight broadcast sink: `weights` arrives already
        resolved (the ref's descriptor pulled the tensors straight from
        the broadcaster's registry — no GCS/plasma round trip). Stored
        for the train loop (session.get_broadcast_weights)."""
        self._broadcast_weights = weights
        from ray_tpu._private.device_objects import tree_map

        leaves: list = []
        tree_map(weights, leaves.append, lambda v: hasattr(v, "shape"))
        # nbytes is metadata on jax.Array AND ndarray — no host gather
        # (np.asarray here would DMA the whole model back to host just
        # to report a size).
        return {"rank": self.rank, "leaves": len(leaves),
                "bytes": int(sum(getattr(x, "nbytes", 0) for x in leaves))}

    def node_id(self) -> str:
        return ray_tpu.get_runtime_context().node_id

    def shutdown(self) -> bool:
        return True


def _wants_arg(fn) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) > 0
    except (TypeError, ValueError):
        return False


class WorkerGroup:
    def __init__(self, scaling: ScalingConfig, env: dict | None = None):
        self.scaling = scaling
        self.env = env or {}
        self.pg = None
        self.elastic = scaling.elastic is not None
        n = scaling.num_workers
        if self.elastic:
            # Elastic gangs change membership at runtime; placement
            # groups cannot resize, so elastic workers are scheduled by
            # plain resource demand (DRAINING nodes are already excluded
            # from placement). STRICT_* gang guarantees are therefore
            # incompatible with elastic.
            if scaling.placement_strategy.startswith("STRICT"):
                raise ValueError(
                    "elastic training cannot use a STRICT_* placement "
                    f"strategy (got {scaling.placement_strategy!r}): "
                    "membership changes at runtime")
        elif n > 1 or scaling.placement_strategy != "PACK":
            self.pg = placement_group(scaling.as_placement_group_bundles(),
                                      strategy=scaling.placement_strategy)
            if not self.pg.wait(timeout=120):
                from ray_tpu import exceptions as exc

                remove_placement_group(self.pg)
                raise exc.PlacementGroupSchedulingError(
                    f"train worker placement group "
                    f"({scaling.as_placement_group_bundles()}) not schedulable "
                    f"within 120s — not enough free cluster resources")
        self.workers = []
        for rank in range(n):
            self.workers.append(self._spawn(rank, n))

    def _spawn(self, rank: int, world_size: int):
        res = self.scaling.worker_resources()
        opts = {"num_cpus": res.get("CPU", 1.0),
                "resources": {k: v for k, v in res.items() if k != "CPU"}}
        if self.pg is not None:
            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=self.pg, placement_group_bundle_index=rank)
        if self.elastic:
            # poll/request_pause must land while a long stop() join (or
            # a slow run boundary) holds another call slot.
            opts["max_concurrency"] = 4
        return TrainWorker.options(**opts).remote(rank, world_size, self.env)

    def add_worker(self, rank: int, world_size: int):
        """Grow the gang by one (elastic grow-back)."""
        w = self._spawn(rank, world_size)
        self.workers.append(w)
        return w

    def remove_worker(self, w, *, stop_timeout_s: float = 2.0) -> None:
        """Drop one member (elastic shrink): graceful stop, then kill —
        frees the actor's lease so a draining node's bounded lease wait
        ends promptly."""
        try:
            ray_tpu.wait([w.stop.remote(stop_timeout_s)],
                         timeout=stop_timeout_s + 3)
        except Exception:
            pass
        try:
            ray_tpu.kill(w)
        except Exception:
            pass
        if w in self.workers:
            self.workers.remove(w)

    def run_on_all(self, method: str, *args, **kwargs) -> list:
        return ray_tpu.get([getattr(w, method).remote(*args, **kwargs)
                            for w in self.workers], timeout=300)

    def broadcast_weights(self, params) -> list:
        """Broadcast initial weights to every worker through ONE device
        object (the train-side device-plane consumer): the driver pins
        the jax param tree in its own registry, the object path carries
        only the descriptor, and each worker pulls the tensors directly
        from the driver — collective route on a shared mesh, host path
        otherwise; never through the GCS or a pickle round trip. Trees
        with no jax.Array leaves degrade to a plain put transparently."""
        from ray_tpu._private import device_objects

        ref = device_objects.device_put(params)
        try:
            return ray_tpu.get(
                [w.receive_weights.remote(ref) for w in self.workers],
                timeout=300)
        finally:
            del ref  # drop the pin once every worker has its copy

    def shutdown(self, graceful_timeout_s: float = 2.0):
        # Graceful first: stop() parks each user loop at a step boundary
        # and joins, so teardown never kills a worker mid-report().
        stops = []
        for w in self.workers:
            try:
                stops.append(w.stop.remote(graceful_timeout_s))
            except Exception:
                pass
        if stops:
            try:
                ray_tpu.wait(stops, num_returns=len(stops),
                             timeout=graceful_timeout_s + 3)
            except Exception:
                pass
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
