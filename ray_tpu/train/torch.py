"""TorchTrainer: torch train loops on the ray_tpu worker gang.

Parity: reference python/ray/train/torch/ — TorchTrainer wraps the same
DataParallelTrainer machinery; `_setup_torch_process_group`
(train/torch/config.py:63) becomes a gloo rendezvous wired from the
driver (MASTER_ADDR/PORT env, rank/world from the session), and
`prepare_model` (train/torch/train_loop_utils.py:74) wraps
DistributedDataParallel.  CPU/gloo here — the accelerator path in this
framework is JAX/TPU (JaxTrainer); TorchTrainer exists for torch-native
user code and host-side models, the same role the reference's gloo
backend plays off-GPU.
"""

from __future__ import annotations

import socket
from typing import Callable

from ray_tpu.train import session
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import JaxTrainer, Result


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def prepare_model(model):
    """Wrap for distributed training (reference: prepare_model
    train_loop_utils.py:74 → DDP). No-op for world_size 1."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Re-batch a DataLoader with a DistributedSampler shard (reference:
    prepare_data_loader train_loop_utils.py)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return loader
    sampler = DistributedSampler(loader.dataset,
                                 num_replicas=dist.get_world_size(),
                                 rank=dist.get_rank())
    return DataLoader(loader.dataset, batch_size=loader.batch_size,
                      sampler=sampler, num_workers=0,
                      collate_fn=loader.collate_fn,
                      drop_last=loader.drop_last)


def _torch_wrapped_loop(user_loop_blob: bytes, config: dict):
    """Runs inside each train worker: gloo process group up, then the
    user loop, then teardown (reference: _TorchBackend.on_start/on_shutdown
    train/torch/config.py).  Rendezvous: rank 0 binds a port on ITS host
    and publishes host:port through the GCS KV — the reference likewise
    has the backend pick the address on the rank-0 worker, not the driver
    (a driver-chosen 127.0.0.1 would break multi-node gangs)."""
    import os
    import time

    from ray_tpu._private import serialization
    from ray_tpu._private.api_internal import get_core_worker

    rank = session.get_world_rank()
    world = session.get_world_size()
    if world > 1:
        import torch.distributed as dist

        cw = get_core_worker()
        key = config.pop("_torch_rdzv_key")
        if rank == 0:
            addr, port = cw.address.host, _free_port()
            cw._run(cw.gcs.call("KVPut", {
                "ns": "torch_rdzv", "key": key, "value": f"{addr}:{port}"}))
        else:
            deadline = time.monotonic() + 120
            while True:
                val = cw._run(cw.gcs.call("KVGet", {
                    "ns": "torch_rdzv", "key": key}))["value"]
                if val:
                    addr, port_s = val.rsplit(":", 1)
                    port = int(port_s)
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError("torch rendezvous: rank 0 never "
                                       "published its address")
                time.sleep(0.05)
        os.environ["MASTER_ADDR"] = addr
        os.environ["MASTER_PORT"] = str(port)
        dist.init_process_group("gloo", rank=rank, world_size=world)
    user_loop = serialization.loads_func(user_loop_blob)
    try:
        user_loop(config)
    finally:
        if world > 1:
            import torch.distributed as dist

            if dist.is_initialized():
                dist.destroy_process_group()


class TorchTrainer(JaxTrainer):
    """Parity: ray.train.torch.TorchTrainer — same fit()/Result surface
    as JaxTrainer, with the torch process-group backend installed."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None):
        import uuid

        from ray_tpu._private import serialization

        user_blob = serialization.dumps_func(train_loop_per_worker)
        cfg = dict(train_loop_config or {})
        if (scaling_config or ScalingConfig()).num_workers > 1:
            cfg["_torch_rdzv_key"] = uuid.uuid4().hex

        def wrapped(config):
            _torch_wrapped_loop(user_blob, config)

        super().__init__(wrapped, train_loop_config=cfg,
                         scaling_config=scaling_config,
                         run_config=run_config,
                         collective_backend=None)


__all__ = ["TorchTrainer", "prepare_model", "prepare_data_loader", "Result"]
