"""AccelerateTrainer: HF accelerate train loops on the worker gang.

Parity: reference python/ray/train/huggingface/accelerate/
accelerate_trainer.py — AccelerateTrainer IS a TorchTrainer whose
backend additionally materializes the user's accelerate configuration
on every worker before the loop runs: the torch process group comes up
first (gloo rendezvous, torch.py), then the env contract `accelerate
launch` would export is set (in-process `Accelerator()` reads
ACCELERATE_* env vars, not config files — verified against accelerate
1.14), and the user loop instantiates `accelerate.Accelerator()`
unchanged. CPU/gloo here — the accelerator path in this framework is
JAX/TPU (JaxTrainer); this exists for HF-ecosystem user code, the same
role the reference's CPU/DeepSpeed-less path plays.
"""

from __future__ import annotations

from typing import Callable

from ray_tpu.train import session
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.torch import TorchTrainer
from ray_tpu.train.trainer import Result


def _run_with_accelerate_env(user_loop: Callable, config: dict):
    """Runs inside each train worker AFTER the torch process group is
    up: export the env contract `accelerate launch` provides (restored
    afterwards — worker processes are reused across fits and a stale
    ACCELERATE_* value would leak into the next job), then the user
    loop."""
    import os

    rank = session.get_world_rank()
    world = session.get_world_size()
    acc_cfg = config.pop("_accelerate_config", None) or {}
    env = {
        # PartialState reads these when deciding it is distributed;
        # MASTER_ADDR/PORT are already set by the torch backend's
        # rendezvous when world > 1. Set unconditionally: reused
        # workers must not keep a previous gang's values.
        "RANK": str(rank),
        "WORLD_SIZE": str(world),
        "LOCAL_RANK": str(session.get_context().get_local_rank()),
        "ACCELERATE_USE_CPU": "true",
    }
    for k, v in acc_cfg.items():
        # `accelerate launch` exports each config entry as
        # ACCELERATE_<KEY>; pass pre-namespaced keys through verbatim.
        name = k if k.startswith("ACCELERATE_") else \
            "ACCELERATE_" + k.upper()
        env[name] = str(v).lower() if isinstance(v, bool) else str(v)
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        user_loop(config)
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


class AccelerateTrainer(TorchTrainer):
    """Parity: ray.train.huggingface.AccelerateTrainer — same
    fit()/Result surface; `accelerate_config` (a dict of accelerate
    settings, e.g. {"mixed_precision": "bf16",
    "gradient_accumulation_steps": 4}, or None for defaults) reaches
    every worker as the ACCELERATE_* env vars `accelerate launch` would
    set. The user loop builds `Accelerator()` and uses
    prepare()/backward()/gather() unchanged."""

    def __init__(self, train_loop_per_worker: Callable,
                 *, accelerate_config: dict | None = None,
                 train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None):
        cfg = dict(train_loop_config or {})
        if accelerate_config is not None:
            cfg["_accelerate_config"] = dict(accelerate_config)

        def wrapped(config, _loop=train_loop_per_worker):
            _run_with_accelerate_env(_loop, config)

        super().__init__(wrapped, train_loop_config=cfg,
                         scaling_config=scaling_config,
                         run_config=run_config)


__all__ = ["AccelerateTrainer", "Result"]
