"""SPMD training-step construction: pjit over a named mesh.

This replaces the reference's torch DDP/FSDP inner loop (reference:
python/ray/train/torch/train_loop_utils.py:74 prepare_model — DDP wrapper;
:24,:91 FSDP) with one compiled program: shardings come from rules
(ZeRO/TP), XLA inserts the collectives, the optimizer update runs sharded.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.sharding import ShardingRules, TRANSFORMER_RULES


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, c: TrainState(*c))


def make_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation):
    """loss_fn(params, batch) -> scalar loss. Returns step(state, batch)."""

    def train_step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(new_params, new_opt, state.step + 1), {
            "loss": loss, "step": state.step + 1}

    return train_step


def shard_train_step(train_step: Callable, mesh: Mesh, state_specs,
                     batch_spec) -> Callable:
    """jit the step with input/output shardings pinned to the mesh."""
    state_shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), state_specs,
        is_leaf=lambda x: isinstance(x, P))
    batch_shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), batch_spec,
        is_leaf=lambda x: isinstance(x, P))
    return jax.jit(
        train_step,
        in_shardings=(state_shardings, batch_shardings),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,))


def state_specs_from_rules(state: TrainState, rules: ShardingRules):
    """PartitionSpecs for TrainState: params by rules; optimizer state
    inherits each param's spec (ZeRO — optimizer shards like its param);
    scalars replicated."""
    param_specs = rules.tree_specs(state.params)

    param_spec_map = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]:
        param_spec_map[_shape_key(leaf)] = rules.spec_for(path, leaf)

    def opt_spec(path, leaf):
        if hasattr(leaf, "shape") and leaf.ndim > 0:
            return param_spec_map.get(_shape_key(leaf), P())
        return P()

    opt_specs = jax.tree_util.tree_map_with_path(opt_spec, state.opt_state)
    return TrainState(param_specs, opt_specs, P())


def _shape_key(leaf):
    return tuple(leaf.shape) if hasattr(leaf, "shape") else ()


def reshard_to_mesh(state, specs, mesh: Mesh):
    """Re-lay a state pytree out onto a (smaller or larger) mesh — the
    elastic-resize hop after a gang member left or joined: the same
    PartitionSpecs applied to the new mesh's device set. One device_put
    per leaf; XLA moves only the shards that change owner."""
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P))
    return jax.tree_util.tree_map(
        lambda leaf, sh: jax.device_put(leaf, sh), state, shardings)


def init_sharded_state(mesh: Mesh, init_fn: Callable, rules: ShardingRules,
                       optimizer: optax.GradientTransformation,
                       *init_args) -> tuple[TrainState, Any]:
    """Initialize params/opt-state directly with sharded layouts (params are
    created on-device already partitioned — no host round-trip)."""

    def build():
        params = init_fn(*init_args)
        opt_state = optimizer.init(params)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    abstract = jax.eval_shape(build)
    specs = state_specs_from_rules(abstract, rules)
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P))
    state = jax.jit(build, out_shardings=shardings)()
    return state, specs
