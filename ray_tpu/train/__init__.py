from ray_tpu.train import session
from ray_tpu.train.session import get_context, report
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    ElasticConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.spmd import (
    TrainState,
    init_sharded_state,
    make_train_step,
    reshard_to_mesh,
    shard_train_step,
    state_specs_from_rules,
)
from ray_tpu.train.trainer import JaxTrainer, Result

__all__ = [
    "JaxTrainer", "Result", "ScalingConfig", "RunConfig", "CheckpointConfig",
    "ElasticConfig", "FailureConfig", "Checkpoint", "CheckpointManager",
    "session", "TrainState", "make_train_step", "shard_train_step",
    "init_sharded_state", "state_specs_from_rules", "reshard_to_mesh",
]

# TorchTrainer / AccelerateTrainer / HF callbacks import torch lazily —
# reach them via their submodules (ray_tpu.train.torch,
# ray_tpu.train.accelerate, ray_tpu.train.huggingface) so `import
# ray_tpu.train` stays torch-free for pure-JAX users.

from ray_tpu._private.usage_stats import record_library_usage as _rlu
_rlu('train')
del _rlu
