"""Checkpoint abstraction over orbax.

Parity: reference python/ray/air/checkpoint.py (dir/dict Checkpoint) +
train/_internal/storage.py (persistent storage). TPU-native: pytrees are
written with orbax (async-capable, sharding-aware restore for SPMD states).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

import jax


class Checkpoint:
    """A directory-backed checkpoint with optional pytree payload."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_pytree(cls, tree: Any, path: str | None = None,
                    metrics: dict | None = None) -> "Checkpoint":
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        save_pytree(tree, os.path.join(path, "state"))
        if metrics is not None:
            with open(os.path.join(path, "metrics.json"), "w") as f:
                json.dump(metrics, f)
        return cls(path)

    @classmethod
    def from_dict(cls, data: dict, path: str | None = None) -> "Checkpoint":
        """Dict-backed checkpoint (reference: air/checkpoint.py
        Checkpoint.from_dict) — stored as a pytree directory."""
        return cls.from_pytree(dict(data), path)

    def to_dict(self) -> dict:
        return dict(self.to_pytree())

    def to_pytree(self, template: Any | None = None) -> Any:
        return restore_pytree(os.path.join(self.path, "state"), template)

    def metrics(self) -> dict:
        p = os.path.join(self.path, "metrics.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def as_directory(self) -> str:
        return self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"


_checkpointer = None


def _get_checkpointer():
    """One process-wide StandardCheckpointer (it owns a background thread;
    constructing one per call leaks threads over a long training run)."""
    global _checkpointer
    if _checkpointer is None:
        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


def save_pytree(tree: Any, path: str) -> None:
    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    if _HAS_ORBAX:
        ckptr = _get_checkpointer()
        ckptr.save(path, tree)
        ckptr.wait_until_finished()
    else:  # pragma: no cover
        import pickle

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "tree.pkl"), "wb") as f:
            pickle.dump(jax.device_get(tree), f)


def restore_pytree(path: str, template: Any | None = None) -> Any:
    path = os.path.abspath(path)
    if _HAS_ORBAX:
        ckptr = _get_checkpointer()
        if template is not None:
            # Sharded SPMD restore: orbax loads each shard directly onto
            # the template's sharding (no full-host materialization).
            try:
                return ckptr.restore(
                    path, args=ocp.args.StandardRestore(template))
            except Exception:
                # Template/checkpoint mismatch (e.g. plain numpy template):
                # fall through to the unsharded path below.
                pass
        tree = ckptr.restore(path)
        if template is not None:
            tree = jax.tree_util.tree_map(
                lambda t, v: jax.device_put(v, t.sharding)
                if hasattr(t, "sharding") else v, template, tree)
        return tree
    else:  # pragma: no cover
        import pickle

        with open(os.path.join(path, "tree.pkl"), "rb") as f:
            return pickle.load(f)


class CheckpointManager:
    """Keeps the latest-k checkpoints under a run directory
    (parity: train checkpoint manager + Tune trial checkpointing)."""

    def __init__(self, root: str, num_to_keep: int | None = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.num_to_keep = num_to_keep
        self._index = 0

    def save(self, tree: Any, metrics: dict | None = None) -> Checkpoint:
        self._index += 1
        path = os.path.join(self.root, f"checkpoint_{self._index:06d}")
        ckpt = Checkpoint.from_pytree(tree, path, metrics)
        self._gc()
        return ckpt

    def latest(self) -> Checkpoint | None:
        cs = self.list()
        return cs[-1] if cs else None

    def list(self) -> list[Checkpoint]:
        names = sorted(n for n in os.listdir(self.root)
                       if n.startswith("checkpoint_"))
        return [Checkpoint(os.path.join(self.root, n)) for n in names]

    def _gc(self) -> None:
        if self.num_to_keep is None:
            return
        cs = self.list()
        while len(cs) > self.num_to_keep:
            shutil.rmtree(cs.pop(0).path, ignore_errors=True)
