"""ray_tpu: a TPU-native distributed computing framework.

The public API mirrors the reference's `ray` package surface
(reference: python/ray/_private/worker.py — init:1139, get:2475, put:2590,
wait:2653, kill:2819, cancel:2850, @ray.remote overloads :3027+) over a
runtime whose accelerator plane is JAX/XLA on TPU.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable, Sequence

from ray_tpu import exceptions
from ray_tpu._private import api_internal
from ray_tpu._private.api_internal import (ActorClass, ActorHandle,
                                           DeviceObjectRef, ObjectRef,
                                           ObjectRefGenerator)
from ray_tpu._private.common import Address
from ray_tpu._private.config import Config

__version__ = "0.1.0"

_init_lock = threading.RLock()
_runtime_node = None  # RuntimeNode when this process started the cluster
_driver_core_worker = None
_client_ctx = None  # ClientContext when attached via address="client://..."


def init(address: str | None = None, *, resources: dict | None = None,
         labels: dict | None = None, num_cpus: float | None = None,
         object_store_memory: int | None = None, namespace: str | None = None,
         config: Config | None = None, ignore_reinit_error: bool = False,
         log_to_driver: bool | None = None, runtime_env: dict | None = None,
         _head_raylet: tuple[str, int] | None = None,
         _store_path: str | None = None, _node_id: str | None = None):
    """Start (or connect to) a cluster and attach this process as a driver.

    address=None starts a local head (GCS + raylet) like the reference's
    `ray.init()`; address="host:port" connects to an existing GCS
    (the reference's ray.init(address=...)); address="client://host:port"
    attaches as a remote client through a proxy (the reference's `ray://`).
    """
    global _runtime_node, _driver_core_worker, _client_ctx
    from ray_tpu._private.node import RuntimeNode
    from ray_tpu._private.worker import CoreWorker

    if address is not None and address.startswith("client://"):
        from ray_tpu.util.client.worker import ClientContext

        unsupported = {
            "resources": resources, "labels": labels, "num_cpus": num_cpus,
            "object_store_memory": object_store_memory,
            "namespace": namespace, "runtime_env": runtime_env,
        }
        bad = [k for k, v in unsupported.items() if v is not None]
        if bad:
            raise ValueError(
                f"init(address='client://...') does not support {bad}; these "
                "are driver/cluster options — set them on the server side")
        with _init_lock:
            if _client_ctx is not None or _driver_core_worker is not None:
                if ignore_reinit_error:
                    return
                raise exceptions.RayTpuError("ray_tpu.init() called twice")
            target = address[len("client://"):]
            host, sep, port_s = target.rpartition(":")
            if not sep or not port_s.isdigit():
                raise ValueError(
                    f"client address must be client://host:port, got {address!r}")
            _client_ctx = ClientContext(host, int(port_s))
            return

    with _init_lock:
        if _driver_core_worker is not None or _client_ctx is not None:
            if ignore_reinit_error:
                return
            raise exceptions.RayTpuError("ray_tpu.init() called twice")
        cfg = config or Config()
        if object_store_memory:
            cfg.object_store_memory = int(object_store_memory)
        if log_to_driver is not None:  # explicit kwarg wins over Config
            cfg.log_to_driver = bool(log_to_driver)
        if address is None:
            node = RuntimeNode(cfg)
            gcs_host, gcs_port = node.start_gcs()
            head_res = dict(resources or {})
            if num_cpus is not None:
                head_res.setdefault("CPU", num_cpus)
            handle = node.start_raylet(resources=head_res or None, labels=labels,
                                       is_head=True)
            _runtime_node = node
            raylet_host, raylet_port = handle.host, handle.port
            store_path = handle.store_path
            node_id = handle.node_id
        else:
            gcs_host, gcs_port_s = address.rsplit(":", 1)
            gcs_port = int(gcs_port_s)
            if _head_raylet is not None:
                raylet_host, raylet_port = _head_raylet
                store_path = _store_path
                node_id = _node_id
            else:
                # Resolve a raylet to attach to from the GCS node table
                # (reference: ray.init(address=...) bootstraps from the GCS):
                # prefer this host's raylet (shared-memory store is local),
                # else the head node's.
                raylet_host = raylet_port = store_path = node_id = None
                import socket

                local_names = {"127.0.0.1", "localhost", socket.gethostname()}
                try:
                    local_names.add(socket.gethostbyname(socket.gethostname()))
                except OSError:
                    pass
                nodes = _query_nodes(gcs_host, gcs_port, cfg)
                alive = [n for n in nodes if n.get("alive")]
                alive.sort(key=lambda n: (n["host"] not in local_names,
                                          not n.get("is_head")))
                if not alive:
                    raise exceptions.RayTpuError(
                        f"no alive nodes in cluster at {address}")
                chosen = alive[0]
                raylet_host = chosen["host"]
                raylet_port = chosen["raylet_port"]
                store_path = chosen["store_path"]
                node_id = chosen["node_id"]
        cw = CoreWorker(
            gcs_host=gcs_host, gcs_port=gcs_port,
            raylet_host=raylet_host, raylet_port=raylet_port,
            store_path=store_path, node_id=node_id,
            is_driver=True, config=cfg, owns_cluster=address is None)
        _driver_core_worker = cw
        api_internal.set_core_worker(cw)
        if _runtime_node is not None:
            from ray_tpu._private.usage_stats import UsageStatsReporter

            cw._usage_reporter = UsageStatsReporter(_runtime_node.session_dir)
            cw._usage_reporter.start()
        if runtime_env is not None:
            from ray_tpu.runtime_env import set_job_runtime_env

            set_job_runtime_env(runtime_env)


def _query_nodes(gcs_host: str, gcs_port: int, cfg: Config) -> list[dict]:
    """One-shot GCS query usable before a CoreWorker exists."""
    import asyncio

    from ray_tpu._private import rpc

    async def go():
        conn = await rpc.dial(
            gcs_host, gcs_port, name="init-bootstrap",
            timeout=cfg.rpc_connect_timeout_s)
        try:
            resp = await conn.call("GetAllNodes", {},
                                   timeout=cfg.rpc_call_timeout_s)
            return resp["nodes"]
        finally:
            await conn.close()

    # A dedicated thread, not asyncio.run(): init() may be called from
    # inside a running event loop (notebook cell, async web handler).
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        return pool.submit(asyncio.run, go()).result()


def is_initialized() -> bool:
    return (api_internal.core_worker_or_none() is not None
            or _client_ctx is not None)


def shutdown():
    global _runtime_node, _driver_core_worker, _client_ctx
    with _init_lock:
        if _client_ctx is not None:
            _client_ctx.close()
            _client_ctx = None
            return
        cw = api_internal.core_worker_or_none()
        if cw is not None:
            cw.shutdown()
        api_internal.set_core_worker(None)
        _driver_core_worker = None
        from ray_tpu.runtime_env import set_job_runtime_env

        set_job_runtime_env(None)
        if _runtime_node is not None:
            _runtime_node.shutdown()
            _runtime_node = None


def _client_mode():
    """The active ClientContext, or None when a local CoreWorker exists.

    Mirrors the reference's client_mode_hook dispatch
    (reference: python/ray/_private/client_mode_hook.py): a worker-side
    CoreWorker always wins so library code running *on* the cluster is
    unaffected by a client connection in the same process.
    """
    if api_internal.core_worker_or_none() is not None:
        return None
    return _client_ctx


def remote(*args, **kwargs):
    """@ray_tpu.remote decorator for functions and classes."""
    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        ctx = _client_mode()
        if ctx is not None:
            return ctx.remote(args[0], {})
        return api_internal.make_remote(args[0], {})
    if args:
        raise TypeError("@ray_tpu.remote takes keyword options only")

    def wrap(obj):
        ctx = _client_mode()
        if ctx is not None:
            return ctx.remote(obj, kwargs)
        return api_internal.make_remote(obj, kwargs)

    return wrap


def put(value: Any) -> ObjectRef:
    ctx = _client_mode()
    if ctx is not None:
        return ctx.put(value)
    cw = api_internal.get_core_worker()
    if isinstance(value, ObjectRef):
        raise TypeError("ray_tpu.put() of an ObjectRef is not allowed")
    oid, owner = cw.put(value)
    return ObjectRef(oid, owner)


def get(refs, timeout: float | None = None):
    ctx = _client_mode()
    if ctx is not None:
        return ctx.get(refs, timeout=timeout)
    cw = api_internal.get_core_worker()
    single = isinstance(refs, ObjectRef)
    if single:
        refs = [refs]
    refs = list(refs)
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get() takes ObjectRefs, got {type(r)}")
    values = cw.get([(r.id, r.owner) for r in refs], timeout=timeout)
    return values[0] if single else values


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: float | None = None):
    ctx = _client_mode()
    if ctx is not None:
        return ctx.wait(refs, num_returns=num_returns, timeout=timeout)
    cw = api_internal.get_core_worker()
    refs = list(refs)
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    ready_idx, not_ready_idx = cw.wait(
        [(r.id, r.owner) for r in refs], num_returns=num_returns, timeout=timeout)
    return [refs[i] for i in ready_idx], [refs[i] for i in not_ready_idx]


def kill(actor, *, no_restart: bool = True):
    ctx = _client_mode()
    if ctx is not None:
        return ctx.kill(actor, no_restart=no_restart)
    cw = api_internal.get_core_worker()
    if not isinstance(actor, ActorHandle):
        raise TypeError("ray_tpu.kill() takes an ActorHandle")
    cw.kill_actor(actor._id_hex, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    """Best-effort cancellation of a pending task (running-task interrupt
    lands with the richer cancel path; reference: worker.py:2850)."""
    ctx = _client_mode()
    if ctx is not None:
        return ctx.cancel(ref, force=force)
    cw = api_internal.get_core_worker()
    task_id = ref.id.task_id().hex()

    def _cancel_on_loop():
        # Queue/pending-task state is owned by the IO loop thread.
        pt = cw.pending_tasks.get(task_id)
        if pt is None or pt.pushed_to is not None:
            return
        from ray_tpu._private import serialization

        err = serialization.serialize_exception(
            exceptions.TaskCancelledError(f"task {task_id[:12]} cancelled"))
        for q in cw._queues.values():
            if task_id in q:
                q.remove(task_id)
        cw._complete_task_error(pt, err)

    cw.loop.call_soon_threadsafe(_cancel_on_loop)


def get_actor(name: str, namespace: str | None = None) -> ActorHandle:
    ctx = _client_mode()
    if ctx is not None:
        return ctx.get_actor(name, namespace=namespace)
    cw = api_internal.get_core_worker()
    resp = cw._run(cw.gcs.call("GetNamedActor", {
        "name": name, "namespace": namespace or "default"}))
    if not resp.get("found"):
        raise ValueError(f"named actor {name!r} not found")
    from ray_tpu._private.ids import ActorID

    return ActorHandle(ActorID.from_hex(resp["actor_id"]), name)


def nodes() -> list[dict]:
    ctx = _client_mode()
    if ctx is not None:
        return ctx.nodes()
    cw = api_internal.get_core_worker()
    return cw._run(cw.gcs.call("GetAllNodes", {}))["nodes"]


def cluster_resources() -> dict:
    ctx = _client_mode()
    if ctx is not None:
        return ctx.cluster_resources()
    total: dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for k, v in n["total_resources"].items():
                total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> dict:
    ctx = _client_mode()
    if ctx is not None:
        return ctx.available_resources()
    total: dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for k, v in n["available_resources"].items():
                total[k] = total.get(k, 0.0) + v
    return total


class _RuntimeContext:
    def __init__(self, cw):
        self._cw = cw

    @property
    def job_id(self) -> str:
        return self._cw.job_id

    @property
    def node_id(self) -> str:
        return self._cw.node_id

    @property
    def worker_id(self) -> str:
        return self._cw.worker_id

    @property
    def task_id(self) -> str:
        return self._cw._current_task_id.hex()

    @property
    def actor_id(self) -> str | None:
        return self._cw._actor_id

    def get_node_id(self) -> str:
        return self._cw.node_id


def get_runtime_context() -> _RuntimeContext:
    return _RuntimeContext(api_internal.get_core_worker())


def method(num_returns: int = 1):
    """@ray_tpu.method decorator for actor methods (parity: ray.method)."""

    def wrap(fn):
        fn._ray_tpu_num_returns = num_returns
        return fn

    return wrap


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "put", "get", "wait",
    "ObjectRefGenerator",
    "kill", "cancel", "get_actor", "nodes", "cluster_resources",
    "available_resources", "get_runtime_context", "method",
    "ObjectRef", "DeviceObjectRef", "ActorHandle", "ActorClass", "Config",
    "exceptions",
]
