"""Core-runtime microbenchmarks.

Parity: reference python/ray/_private/ray_perf.py:93-200 (`ray
microbenchmark` CLI): single-client task throughput, actor call
throughput/latency, put/get bandwidth. Run: `python -m
ray_tpu.microbenchmark` (or `ray_tpu microbenchmark`).
"""

from __future__ import annotations

import json
import time

import numpy as np

import ray_tpu


def _rate(n, dt):
    return round(n / dt, 1)


def bench_tasks(n: int = 4000) -> dict:
    @ray_tpu.remote
    def noop():
        return None

    # Warm the worker pool AND the lease ramp: steady-state throughput is
    # what the reference's ray_perf.py:93 measures (it runs multi-second
    # timed windows), so the ramp must not dominate the timed burst.
    ray_tpu.get([noop.remote() for _ in range(200)])
    t0 = time.perf_counter()
    ray_tpu.get([noop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    return {"tasks_per_s": _rate(n, dt)}


def bench_actor_calls(n: int = 500) -> dict:
    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(50):
        ray_tpu.get(a.m.remote())
    sync_dt = time.perf_counter() - t0
    return {"actor_calls_per_s": _rate(n, dt),
            "actor_call_roundtrip_ms": round(sync_dt / 50 * 1000, 3)}


def bench_put_get(mb: int = 64, rounds: int = 4) -> dict:
    arr = np.ones(mb * 1024 * 1024 // 8)
    # Warmup put faults in fresh tmpfs pages (one-time arena cost);
    # steady-state bandwidth is what matters.
    ray_tpu.get(ray_tpu.put(arr))
    put_dt = get_dt = 0.0
    for _ in range(rounds):
        t0 = time.perf_counter()
        ref = ray_tpu.put(arr)
        put_dt += time.perf_counter() - t0
        t0 = time.perf_counter()
        out = ray_tpu.get(ref)
        get_dt += time.perf_counter() - t0
        assert out.shape == arr.shape
    return {"put_gb_per_s": round(mb * rounds / 1024 / put_dt, 3),
            "get_gb_per_s": round(mb * rounds / 1024 / get_dt, 3)}


def bench_task_args_throughput(n_args: int = 100) -> dict:
    @ray_tpu.remote
    def consume(*args):
        return len(args)

    refs = [ray_tpu.put(i) for i in range(n_args)]
    t0 = time.perf_counter()
    assert ray_tpu.get(consume.remote(*refs)) == n_args
    dt = time.perf_counter() - t0
    return {"args_per_task": n_args, "many_args_call_s": round(dt, 3)}


def main(as_json: bool = True):
    owns_cluster = not ray_tpu.is_initialized()
    if owns_cluster:
        ray_tpu.init(num_cpus=4)
    try:
        results = {}
        for fn in (bench_tasks, bench_actor_calls, bench_put_get,
                   bench_task_args_throughput):
            results.update(fn())
        print(json.dumps(results) if as_json else results)
        return results
    finally:
        if owns_cluster:
            ray_tpu.shutdown()


if __name__ == "__main__":
    main()
