"""ray_tpu CLI.

Parity: reference python/ray/scripts/scripts.py (`ray start/stop/status`,
`ray list ...` at :2441-2492, `ray microbenchmark`). Run as
`python -m ray_tpu.scripts <cmd>`.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def cmd_start(args):
    from ray_tpu._private.accelerator import node_resources_and_labels
    from ray_tpu._private.config import Config
    from ray_tpu._private.node import RuntimeNode

    cfg = Config()
    node = RuntimeNode(cfg)
    resources, labels = node_resources_and_labels()
    if args.resources:
        resources.update(json.loads(args.resources))
    if args.num_cpus is not None:
        resources["CPU"] = args.num_cpus
    if args.head:
        host, port = node.start_gcs()
        handle = node.start_raylet(resources=resources or None, labels=labels,
                                   is_head=True)
        info = {"gcs_address": f"{host}:{port}",
                "raylet": f"{handle.host}:{handle.port}",
                "node_id": handle.node_id,
                "store_path": handle.store_path,
                "session_dir": node.session_dir}
        if getattr(args, "client_server_port", None) is not None:
            # Host a client proxy in the head supervisor (reference:
            # `ray start --head --ray-client-server-port`).
            import ray_tpu
            from ray_tpu.util.client.server import serve as client_serve

            ray_tpu.init(address=f"{host}:{port}",
                         _head_raylet=(handle.host, handle.port),
                         _store_path=handle.store_path,
                         _node_id=handle.node_id)
            cs = client_serve(port=args.client_server_port)
            info["client_server"] = f"{cs.host}:{cs.port}"
        with open(args.state_file, "w") as f:
            json.dump(info, f)
        print(json.dumps(info))
        print(f"\nhead started; connect with:\n  ray_tpu.init("
              f"address='{host}:{port}', ...)\nstate written to "
              f"{args.state_file}; `ray_tpu stop` to shut down")
    else:
        if not args.address:
            print("worker nodes need --address=<gcs host:port>", file=sys.stderr)
            return 1
        host, port = args.address.rsplit(":", 1)
        node.attach_gcs(host, int(port))
        handle = node.start_raylet(resources=resources or None, labels=labels)
        print(json.dumps({"node_id": handle.node_id,
                          "raylet": f"{handle.host}:{handle.port}"}))
    # Keep the daemon processes alive under this supervisor.
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    finally:
        node.shutdown()
    return 0


def cmd_stop(args):
    if os.path.exists(args.state_file):
        os.unlink(args.state_file)
    os.system("pkill -f 'ray_tpu._private.(gcs|raylet|worker)' 2>/dev/null")
    print("stopped ray_tpu daemons")
    return 0


def _connect_from_state(args):
    import ray_tpu

    if ray_tpu.is_initialized():
        # In-process use (tests, embedding): the session is the
        # CALLER's; _shutdown_if_owned leaves it alone.
        ray_tpu._cli_owns_session = False
        return ray_tpu
    with open(args.state_file) as f:
        info = json.load(f)
    host, port = info["raylet"].rsplit(":", 1)
    ray_tpu.init(address=info["gcs_address"],
                 _head_raylet=(host, int(port)),
                 _store_path=info["store_path"],
                 _node_id=info["node_id"])
    ray_tpu._cli_owns_session = True
    return ray_tpu


def _shutdown_if_owned(ray_tpu):
    """Tear down only sessions THIS command created — never a live
    session an embedding caller handed us via an early-initialized
    runtime."""
    if getattr(ray_tpu, "_cli_owns_session", True):
        ray_tpu.shutdown()


def cmd_status(args):
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util import state

    st = state.cluster_status()
    print(json.dumps(st, indent=2, default=str))
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_serve(args):
    """Declarative serve management (reference: `serve deploy/status`)."""
    ray_tpu = _connect_from_state(args)
    from ray_tpu import serve

    if args.serve_cmd == "deploy":
        from ray_tpu.serve.config_deploy import deploy_config

        handles = deploy_config(args.config)
        print(json.dumps({"deployed": sorted(handles)}))
    elif args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")
    elif args.serve_cmd == "run":
        # `serve run module:attr` (reference: the serve CLI's main dev
        # entry) — import the deployment (or bound app), deploy, block.
        import importlib

        mod_name, _, attr = args.target.partition(":")
        if not attr:
            print("target must be module:deployment", file=sys.stderr)
            return 1
        sys.path.insert(0, os.getcwd())
        target = getattr(importlib.import_module(mod_name), attr)
        handle = serve.run(target)
        st = serve.status()
        print(json.dumps({"running": sorted(st.get("deployments", st))},
                         default=str), flush=True)
        if not getattr(args, "non_blocking", False):
            try:
                signal.pause()
            except KeyboardInterrupt:
                pass
            serve.shutdown()
        del handle
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_stack(args):
    """Dump every worker's thread stacks (reference: `ray stack`)."""
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util import state

    for node in state.dump_stacks():
        print(f"=== node {node.get('node_id', '?')[:12]} ===")
        if "error" in node:
            print(f"  unreachable: {node['error']}")
            continue
        for w in node.get("workers", []):
            hdr = (f"-- worker {w.get('worker_id', '?')[:12]} "
                   f"pid={w.get('pid')} actor={w.get('actor_id')}")
            print(hdr)
            for t in w.get("threads", []):
                print(f"  [{t['thread']}{' daemon' if t['daemon'] else ''}]")
                for line in t["stack"].rstrip().splitlines():
                    print(f"    {line}")
            if "error" in w:
                print(f"  error: {w['error']}")
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_list(args):
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util import state

    fn = {"nodes": state.list_nodes, "actors": state.list_actors,
          "jobs": state.list_jobs, "tasks": state.list_tasks,
          "placement-groups": state.list_placement_groups,
          "objects": state.list_objects}[args.entity]
    print(json.dumps(fn(), indent=2, default=str))
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_events(args):
    """`ray_tpu events` — merged structured cluster events (parity:
    reference src/ray/util/event.h + dashboard event module)."""
    import glob
    import os

    from ray_tpu.util.events import list_events

    base = "/tmp/ray_tpu_sessions"
    sessions = sorted(glob.glob(os.path.join(base, "session-*")),
                      key=os.path.getmtime)
    if not sessions:
        print("no sessions found")
        return 1
    for e in list_events(sessions[-1], min_severity=args.severity):
        fields = e.get("fields") or {}
        extra = " ".join(f"{k}={v}" for k, v in fields.items())
        print(f'{e["ts"]:.3f} {e["severity"]:7} {e["source"]:8} '
              f'{e["message"]} {extra}'.rstrip())
    return 0


def cmd_summary(args):
    """`ray_tpu summary tasks|actors|objects` (parity: reference
    `ray summary` — experimental/state/state_cli.py summary commands)."""
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util import state

    fn = {"tasks": state.summarize_tasks, "actors": state.summarize_actors,
          "objects": state.summarize_objects}[args.entity]
    print(json.dumps(fn(), indent=2, default=str))
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_task_latency(args):
    """`ray_tpu task-latency` — per-stage lifecycle latency percentiles
    (SUBMITTED → LEASE_REQUESTED → LEASE_GRANTED → DISPATCHED →
    ARGS_FETCHED → RUNNING → FINISHED/FAILED) from the GCS task-event
    table, rendered as one row per stage."""
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util import state

    out = state.summarize_task_latency(limit=args.limit)
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"{out['tasks']} tasks with recorded events")
        print(f"{'STAGE':<24}{'COUNT':>8}{'P50':>10}{'P95':>10}"
              f"{'P99':>10}{'MEAN':>10}{'MAX':>10}  (ms)")
        for name, _, _ in state.LATENCY_STAGES:
            s = out["stages"].get(name)
            if s is None:
                continue
            print(f"{name:<24}{s['count']:>8}{s['p50_ms']:>10.2f}"
                  f"{s['p95_ms']:>10.2f}{s['p99_ms']:>10.2f}"
                  f"{s['mean_ms']:>10.2f}{s['max_ms']:>10.2f}")
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_pump_stats(args):
    """`ray_tpu pump-stats` — daemon event-loop stats: per-handler call
    counts and latencies for the GCS and every raylet pump (analogue of
    the reference's event_stats.h debug dump)."""
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util import state

    print(json.dumps(state.pump_stats(), indent=2, default=str))
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_drain(args):
    """`ray_tpu drain <node_id> [--reason r] [--deadline s] [--no-wait]`
    — graceful evacuation (parity: reference `ray drain-node` /
    autoscaler.proto DrainNode): the raylet re-spills queued leases,
    waits for running work up to the deadline, pushes primary object
    copies and pinned device objects to peers, while the GCS migrates
    restartable actors. By default waits until the node reports
    DRAINED (then it is safe to terminate)."""
    ray_tpu = _connect_from_state(args)
    from ray_tpu._private.api_internal import get_core_worker

    cw = get_core_worker()
    resp = cw._run(cw.gcs.call("DrainNode", {
        "node_id": args.node_id, "reason": args.reason,
        "deadline_s": args.deadline}, timeout=60))
    if not isinstance(resp, dict):
        resp = {"ok": resp}
    if not resp.get("ok"):
        print(json.dumps(resp))
        _shutdown_if_owned(ray_tpu)
        return 1
    rc = 0
    if not args.no_wait:
        from ray_tpu._private.common import wait_for_drained

        outcome, me = wait_for_drained(
            lambda: cw._run(cw.gcs.call("GetAllNodes", {}))["nodes"],
            args.node_id, args.deadline, slack_s=15.0)
        resp["state"] = "DRAINED" if outcome == "DRAINED" \
            else (me.get("state", outcome) if me else outcome)
        if me is not None:
            resp["drain_stats"] = me.get("drain_stats") or {}
        if outcome != "DRAINED":
            rc = 1
    print(json.dumps(resp))
    _shutdown_if_owned(ray_tpu)
    return rc


def cmd_memory(args):
    """`ray_tpu memory` — cluster object-memory report (parity:
    reference `ray memory` / memory_utils.py: per-node store usage +
    this driver's owned references with pinned sizes and totals)."""
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util import state

    nodes = state.node_stats()
    print(f"{'NODE':<10}{'IN USE':>12}{'HEAP':>12}{'OBJECTS':>9}"
          f"{'EVICTED':>9}{'SPILLED':>12}")
    tot_use = tot_heap = 0
    for n in nodes:
        st = n.get("store", {})
        tot_use += st.get("bytes_in_use", 0)
        tot_heap += st.get("heap_size", 0)
        print(f"{n.get('node_id', '?')[:8]:<10}"
              f"{st.get('bytes_in_use', 0) / 2**20:>10.1f}MB"
              f"{st.get('heap_size', 0) / 2**20:>10.1f}MB"
              f"{st.get('num_objects', 0):>9}"
              f"{st.get('num_evictions', 0):>9}"
              f"{n.get('spilled_bytes', 0) / 2**20:>10.1f}MB")
    print(f"{'TOTAL':<10}{tot_use / 2**20:>10.1f}MB"
          f"{tot_heap / 2**20:>10.1f}MB\n")
    objs = state.list_objects()
    objs.sort(key=lambda o: -(o.get("size") or 0))
    print(f"owned by this driver: {len(objs)} refs, "
          f"{sum(o.get('size') or 0 for o in objs) / 2**20:.1f}MB")
    print(f"{'OBJECT':<14}{'STATE':<9}{'SIZE':>10}{'LREF':>6}{'SREF':>6}"
          f"  LOCATIONS")
    for o in objs[:args.limit]:
        print(f"{o['object_id'][:12]:<14}{o['state']:<9}"
              f"{(o.get('size') or 0) / 2**10:>8.1f}KB"
              f"{o['local_refs']:>6}{o['submitted_refs']:>6}"
              f"  {','.join(n[:8] for n in o.get('locations', [])) or '-'}")
    if len(objs) > args.limit:
        print(f"... {len(objs) - args.limit} more (use --limit)")
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_device_objects(args):
    """`ray_tpu device-objects` — device object plane report: pinned-HBM
    bytes/objects per worker (raylet fan-out), transfer/fallback route
    counters, and this driver's owned device-object descriptors."""
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util import state

    out = state.list_device_objects(entries=not args.no_entries)
    if args.json:
        print(json.dumps(out, indent=2, default=str))
        _shutdown_if_owned(ray_tpu)
        return 0
    c = out["local"]["counters"]
    print(f"routes: in_process={c['in_process']} "
          f"collective={c['collective']} "
          f"host_fallback={c['host_fallback']} lost={c['lost']} "
          f"released={c['released']}")
    print(f"{'NODE':<10}{'WORKER':<10}{'PINNED':>8}{'BYTES':>12}"
          f"{'IN-PROC':>9}{'COLL':>6}{'HOST':>6}")
    for node in out["nodes"]:
        nid = str(node.get("node_id", "?"))[:8]
        if "error" in node:
            print(f"{nid:<10}unreachable: {node['error']}")
            continue
        for w in node.get("workers", []):
            wc = w.get("counters", {})
            print(f"{nid:<10}{str(w.get('worker_id', '?'))[:8]:<10}"
                  f"{w.get('pinned_objects', 0):>8}"
                  f"{w.get('pinned_bytes', 0) / 2**20:>10.2f}MB"
                  f"{wc.get('in_process', 0):>9}"
                  f"{wc.get('collective', 0):>6}"
                  f"{wc.get('host_fallback', 0):>6}")
    if out["owned"]:
        print(f"\nowned device objects: {len(out['owned'])}")
        print(f"{'OBJECT':<14}{'STATE':<8}{'LEAVES':>7}{'BYTES':>12}"
              f"  PIN WORKER")
        for o in out["owned"]:
            print(f"{o['object_id'][:12]:<14}{o['state']:<8}"
                  f"{o['leaves']:>7}{o['pinned_bytes'] / 2**10:>10.1f}KB"
                  f"  {o['pin_worker']}")
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_microbenchmark(args):
    from ray_tpu import microbenchmark

    microbenchmark.main()
    return 0


def cmd_dashboard(args):
    ray_tpu = _connect_from_state(args)
    from ray_tpu import dashboard

    port = dashboard.start(port=args.port)
    print(f"dashboard at http://127.0.0.1:{port}/")
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    dashboard.stop()
    _shutdown_if_owned(ray_tpu)
    return 0


def cmd_job(args):
    ray_tpu = _connect_from_state(args)
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    try:
        if args.job_cmd == "submit":
            sid = client.submit_job(entrypoint=" ".join(args.entrypoint))
            print(sid)
            if args.wait:
                status = client.wait_until_finished(sid, timeout=args.timeout)
                print(status)
                print(client.get_job_logs(sid), end="")
                return 0 if status == "SUCCEEDED" else 1
        elif args.job_cmd == "status":
            print(client.get_job_status(args.id))
        elif args.job_cmd == "logs":
            print(client.get_job_logs(args.id), end="")
        elif args.job_cmd == "list":
            for j in client.list_jobs():
                print(json.dumps(j.__dict__, default=str))
        elif args.job_cmd == "stop":
            print("stopped" if client.stop_job(args.id) else "not running")
    finally:
        _shutdown_if_owned(ray_tpu)
    return 0


def cmd_timeline(args):
    ray_tpu = _connect_from_state(args)
    from ray_tpu.util.timeline import dump_timeline

    path = dump_timeline(args.output)
    print(f"chrome trace written to {path} (open in chrome://tracing "
          "or https://ui.perfetto.dev)")
    _shutdown_if_owned(ray_tpu)
    return 0


def main():
    parser = argparse.ArgumentParser(prog="ray_tpu")
    parser.add_argument("--state-file", default="/tmp/ray_tpu_head.json")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default="")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--resources", default="")
    p.add_argument("--client-server-port", type=int, default=None,
                   help="serve remote client:// drivers on this port "
                        "(reference: --ray-client-server-port)")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop local daemons")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("stack", help="dump all workers' thread stacks")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("status", help="cluster status")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list", help="list cluster entities")
    p.add_argument("entity", choices=["nodes", "actors", "jobs", "tasks",
                                      "placement-groups", "objects"])
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("events", help="structured cluster events "
                                      "(node/actor deaths, OOM, spills)")
    p.add_argument("--severity", default="INFO",
                   choices=["DEBUG", "INFO", "WARNING", "ERROR", "FATAL"])
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("summary", help="aggregate counts per entity "
                                       "(parity: `ray summary`)")
    p.add_argument("entity", choices=["tasks", "actors", "objects"])
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("task-latency", help="per-stage task lifecycle "
                                            "latency percentiles")
    p.add_argument("--limit", type=int, default=200000)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_task_latency)

    p = sub.add_parser("pump-stats", help="daemon event-loop stats "
                                          "(per-handler counts/latencies)")
    p.set_defaults(fn=cmd_pump_stats)

    p = sub.add_parser("drain", help="gracefully drain a node: evacuate "
                                     "leases, actors, objects, and pinned "
                                     "HBM, then wait for DRAINED (parity: "
                                     "`ray drain-node`)")
    p.add_argument("node_id")
    p.add_argument("--reason", default="manual",
                   choices=["preemption", "idle", "manual"])
    p.add_argument("--deadline", type=float, default=30.0,
                   help="seconds the raylet may spend evacuating")
    p.add_argument("--no-wait", action="store_true",
                   help="return after initiating the drain instead of "
                        "waiting for DRAINED")
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser("memory", help="cluster object-memory report "
                                      "(parity: `ray memory`)")
    p.add_argument("--limit", type=int, default=20)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("device-objects",
                       help="device object plane report (pinned-HBM "
                            "bytes, transfer routes, descriptors)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--no-entries", action="store_true",
                   help="skip per-array registry entries")
    p.set_defaults(fn=cmd_device_objects)

    p = sub.add_parser("microbenchmark", help="core-runtime throughput suite")
    p.set_defaults(fn=cmd_microbenchmark)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("serve", help="declarative serve deploy/status")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    ps = ssub.add_parser("deploy")
    ps.add_argument("config", help="JSON config file (ServeDeploy schema)")
    ssub.add_parser("status")
    ssub.add_parser("shutdown")
    pr = ssub.add_parser("run", help="import module:deployment, deploy, "
                                     "block (reference: `serve run`)")
    pr.add_argument("target")
    pr.add_argument("--non-blocking", action="store_true",
                    dest="non_blocking")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("job", help="submit and manage jobs")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    pj = jsub.add_parser("submit")
    pj.add_argument("entrypoint", nargs="+")
    pj.add_argument("--wait", action="store_true")
    pj.add_argument("--timeout", type=float, default=300.0)
    for name in ("status", "logs", "stop"):
        pj = jsub.add_parser(name)
        pj.add_argument("id")
    jsub.add_parser("list")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("timeline", help="dump chrome-trace of task events")
    p.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    p.set_defaults(fn=cmd_timeline)

    args = parser.parse_args()
    sys.exit(args.fn(args) or 0)


if __name__ == "__main__":
    main()
