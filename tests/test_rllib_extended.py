"""SAC and IMPALA tests (parity: reference rllib/algorithms/{sac,impala}
tests — contract + learning-regression style)."""

import numpy as np
import pytest

from ray_tpu.rllib.env import Pendulum
from ray_tpu.rllib.sac import init_sac_params, numpy_policy


def test_pendulum_env_contract():
    env = Pendulum()
    obs = env.reset(seed=0)
    assert obs.shape == (3,)
    assert env.action_size == 1
    total, done, steps = 0.0, False, 0
    while not done:
        obs, r, done, _ = env.step(np.array([0.5]))
        assert r <= 0.0  # cost-based reward
        total += r
        steps += 1
    assert steps == env.max_episode_steps


def test_sac_policy_shapes():
    params = init_sac_params(3, 1)
    mu, log_std = numpy_policy(params, np.zeros((5, 3), np.float32))
    assert mu.shape == (5, 1)
    assert log_std.shape == (5, 1)
    assert (log_std >= -20).all() and (log_std <= 2).all()


def test_sac_rejects_discrete_env():
    from ray_tpu.rllib import SACConfig

    with pytest.raises(ValueError, match="continuous"):
        SACConfig().environment("CartPole-v1").build()


def test_sac_learns_pendulum(ray_start_regular):
    from ray_tpu.rllib import SACConfig

    algo = (SACConfig()
            .environment("Pendulum-v1")
            .rollouts(num_rollout_workers=2)
            .training(rollout_fragment_length=200, learning_starts=400,
                      num_updates_per_iter=128, train_batch_size=128,
                      lr=1e-3)
            .build())
    try:
        results = [algo.train() for _ in range(12)]
        last = results[-1]
        assert last["training_iteration"] == 12
        assert last["timesteps_total"] >= 12 * 2 * 200
        assert last["alpha"] > 0
        # Learning signals: the critic converges (loss shrinks an order of
        # magnitude from the first learning iteration) and swing-up cost
        # improves late vs early (pendulum returns are noisy — wide windows).
        assert last["critic_loss"] < results[0]["critic_loss"] / 3
        early = np.nanmean([r["episode_reward_mean"] for r in results[:3]])
        late = np.nanmean([r["episode_reward_mean"] for r in results[-3:]])
        assert late > early
    finally:
        algo.stop()


def test_impala_learns_cartpole(ray_start_regular):
    from ray_tpu.rllib import ImpalaConfig

    algo = (ImpalaConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(rollout_fragment_length=256,
                      num_fragments_per_iter=4, lr=1e-3)
            .build())
    try:
        first = algo.train()
        last = first
        for _ in range(7):
            last = algo.train()
        assert last["training_iteration"] == 8
        assert last["timesteps_total"] == 8 * 4 * 256
        # V-trace importance ratios hover near 1 (small async staleness).
        assert 0.2 < last["mean_rho"] < 5.0
        assert last["episode_reward_mean"] > first["episode_reward_mean"]
    finally:
        algo.stop()


def test_impala_vtrace_on_policy_matches_returns():
    """With rho=c=1 (on-policy) and no bootstrapping, vs ≈ discounted
    returns — the V-trace recursion must reduce to TD(1)."""
    import jax
    import jax.numpy as jnp

    gamma = 0.9
    T = 5
    rewards = jnp.asarray(np.ones(T, np.float32))
    values = jnp.zeros(T)
    dones = jnp.zeros(T).at[-1].set(1.0)
    rhos = jnp.ones(T)

    # Re-implement the scan exactly as the learner does.
    next_values = jnp.concatenate([values[1:], jnp.zeros(1)]) * (1 - dones)
    deltas = rhos * (rewards + gamma * next_values - values)

    def body(acc, xs):
        delta, c, done = xs
        acc = delta + gamma * (1 - done) * c * acc
        return acc, acc

    _, advs = jax.lax.scan(body, jnp.zeros(()), (deltas, rhos, dones),
                           reverse=True)
    vs = values + advs
    expected = np.array([sum(gamma ** k for k in range(T - t))
                         for t in range(T)], np.float32)
    np.testing.assert_allclose(np.asarray(vs), expected, rtol=1e-5)


def test_ppo_cnn_visual_env(ray_start_regular):
    """Atari-style pipeline: pixel observations -> catalog CNN under jit
    on the learner, jax-CPU forward in rollout workers (reference:
    rllib conv-net defaults for image spaces)."""
    from ray_tpu.rllib.ppo import PPOConfig

    algo = (PPOConfig()
            .environment("VisualCatch-v0")
            .rollouts(num_rollout_workers=1)
            .training(model="atari_cnn", rollout_fragment_length=128,
                      train_batch_size=128, num_sgd_iter=2,
                      sgd_minibatch_size=64)
            .build())
    try:
        r1 = algo.train()
        assert r1["timesteps_this_iter"] >= 128
        assert "pi_loss" in r1
        # Policy action path works on a raw frame.
        from ray_tpu.rllib.env import make_env

        env = make_env("VisualCatch-v0")
        a = algo.compute_single_action(env.reset(0))
        assert a in (0, 1, 2)
    finally:
        algo.stop()


def test_ppo_multi_agent(ray_start_regular):
    """Two agents, two policies, one env (reference: MultiAgentEnv +
    .multi_agent(policies=..., policy_mapping_fn=...))."""
    from ray_tpu.rllib.ppo import PPOConfig

    algo = (PPOConfig()
            .environment("DualCartPole-v0")
            .rollouts(num_rollout_workers=2)
            .training(rollout_fragment_length=128, train_batch_size=256,
                      num_sgd_iter=2, sgd_minibatch_size=64)
            .multi_agent(
                policies={"pol_a": None, "pol_b": None},
                policy_mapping_fn=lambda aid: "pol_a"
                if aid == "agent_0" else "pol_b")
            .build())
    try:
        r1 = algo.train()
        assert r1["timesteps_this_iter"] > 0
        r2 = algo.train()
        assert r2["training_iteration"] == 2
        assert set(algo.policy_params) == {"pol_a", "pol_b"}
        # Policies evolved independently (different data streams).
        import numpy as np

        pa = algo.policy_params["pol_a"]
        pb = algo.policy_params["pol_b"]
        diff = float(np.abs(np.asarray(pa["h1"]["w"])
                            - np.asarray(pb["h1"]["w"])).max())
        assert diff > 0
    finally:
        algo.stop()


def test_visual_catch_training_smoke(ray_start_regular):
    """Smoke: several CNN-PPO iterations on the pixel env stay finite and
    keep rewards in the env's range (full learning curves belong to the
    release suite, not a 1-CPU CI box)."""
    from ray_tpu.rllib.ppo import PPOConfig

    algo = (PPOConfig()
            .environment("VisualCatch-v0")
            .rollouts(num_rollout_workers=1)
            .training(model="atari_cnn", rollout_fragment_length=256,
                      train_batch_size=256, num_sgd_iter=3,
                      sgd_minibatch_size=128, lr=1e-3)
            .build())
    try:
        import math

        for _ in range(3):
            r = algo.train()
            assert math.isfinite(r["pi_loss"]) and math.isfinite(r["vf_loss"])
            assert -1.0 <= r["episode_reward_mean"] <= 1.0
    finally:
        algo.stop()
