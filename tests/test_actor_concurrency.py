"""Concurrent actors: max_concurrency (threaded) + async-def methods.

Parity: reference concurrency groups / threaded actors
(core_worker concurrency_group_manager) and asyncio actors (fiber.h) —
calls are delivered in order, then may overlap up to max_concurrency.
"""

import time

import ray_tpu


def test_threaded_actor_overlaps(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Conc:
        def __init__(self):
            self.active = 0
            self.peak = 0

        def slow(self):
            self.active += 1
            self.peak = max(self.peak, self.active)
            time.sleep(0.3)
            self.active -= 1
            return None

        def peak_seen(self):
            return self.peak

    a = Conc.remote()
    ray_tpu.get(a.peak_seen.remote(), timeout=60)  # absorb cold start
    t0 = time.perf_counter()
    ray_tpu.get([a.slow.remote() for _ in range(4)], timeout=60)
    dt = time.perf_counter() - t0
    assert ray_tpu.get(a.peak_seen.remote(), timeout=60) >= 2
    assert dt < 4 * 0.3, f"calls fully serialized: {dt:.2f}s"


def test_default_actor_still_serial(ray_start_regular):
    @ray_tpu.remote
    class Serial:
        def __init__(self):
            self.active = 0
            self.overlapped = False

        def slow(self):
            self.active += 1
            if self.active > 1:
                self.overlapped = True
            time.sleep(0.05)
            self.active -= 1

        def check(self):
            return self.overlapped

    a = Serial.remote()
    ray_tpu.get([a.slow.remote() for _ in range(6)], timeout=60)
    assert ray_tpu.get(a.check.remote(), timeout=60) is False


def test_async_actor_methods(ray_start_regular):
    @ray_tpu.remote(max_concurrency=8)
    class Async:
        async def wait_and(self, x):
            import asyncio

            await asyncio.sleep(0.2)
            return x * 2

        def ready(self):
            return True

    b = Async.remote()
    ray_tpu.get(b.ready.remote(), timeout=60)  # absorb cold start
    t0 = time.perf_counter()
    out = ray_tpu.get([b.wait_and.remote(i) for i in range(8)], timeout=60)
    dt = time.perf_counter() - t0
    assert out == [i * 2 for i in range(8)]
    # 8 x 200ms sleeps overlap on the actor's event loop.
    assert dt < 1.2, f"async calls serialized: {dt:.2f}s"


def test_async_actor_exception(ray_start_regular):
    import pytest

    @ray_tpu.remote(max_concurrency=2)
    class Async:
        async def boom(self):
            raise ValueError("async-kaboom")

    b = Async.remote()
    from ray_tpu.exceptions import TaskError

    with pytest.raises(TaskError, match="async-kaboom"):
        ray_tpu.get(b.boom.remote(), timeout=60)


def test_concurrent_actor_puts_are_isolated(ray_start_regular):
    """Concurrent tasks on one actor each put objects — ids must not
    collide (thread-local task ids + global put counter)."""
    import numpy as np

    @ray_tpu.remote(max_concurrency=4)
    class Putter:
        def make(self, i):
            return ray_tpu.put(np.full(130_000, i, np.uint8))

    a = Putter.remote()
    inner = ray_tpu.get([a.make.remote(i) for i in range(8)], timeout=60)
    vals = ray_tpu.get(inner, timeout=60)
    for i, v in enumerate(vals):
        assert v[0] == i and len(v) == 130_000
