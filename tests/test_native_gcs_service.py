"""Native in-pump GCS service (src/gcs_service.cc) e2e tests.

The service executes the GCS's KV and pubsub protocol entirely on the
fastpath pump's C++ loop thread; these tests drive it through REAL
rpc.Connection clients against a REAL GcsServer, asserting (a) the
semantics match the Python handlers exactly, (b) the frames were in
fact handled natively (service counters), and (c) rows persist across
restarts in both directions — native-written state restores under the
Python fallback and vice versa (the row format is byte-compatible by
construction: hex(msgpack([ns, key])) -> msgpack(value)).

Reference analog: gcs_kv_manager.cc HandleInternalKVPut and
pubsub_handler.cc dispatched on the gcs_server C++ event loop
(gcs_server.h:79).
"""

import asyncio

import pytest

from ray_tpu._private import rpc
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.native_fastpath import available as pump_available
from ray_tpu._private.native_gcs_service import available as svc_available

pytestmark = pytest.mark.skipif(
    not (pump_available() and svc_available()),
    reason="native pump/service unavailable")


def run(coro):
    return asyncio.run(coro)


async def _start_gcs(tmp_path=None):
    gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state")
                    if tmp_path else None)
    host, port = await gcs.start()
    return gcs, host, port


def test_kv_semantics_native(tmp_path):
    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            assert gcs._native_svc is not None, \
                "native service should be active under the pump server"
            conn = await rpc.connect(host, port)

            r = await conn.call("KVPut", {"ns": "fn", "key": b"k1",
                                          "value": b"v1"})
            assert r == {"added": True}
            r = await conn.call("KVPut", {"ns": "fn", "key": b"k1",
                                          "value": b"zz",
                                          "overwrite": False})
            assert r == {"added": False}
            r = await conn.call("KVGet", {"ns": "fn", "key": b"k1"})
            assert r == {"value": b"v1"}
            r = await conn.call("KVGet", {"ns": "fn", "key": b"nope"})
            assert r == {"value": None}
            r = await conn.call("KVExists", {"ns": "fn", "key": b"k1"})
            assert r == {"exists": True}
            await conn.call("KVPut", {"ns": "fn", "key": b"k2",
                                      "value": b"v2"})
            await conn.call("KVPut", {"ns": "other", "key": b"k3",
                                      "value": b"v3"})
            r = await conn.call("KVKeys", {"ns": "fn", "prefix": b"k"})
            assert sorted(r["keys"]) == [b"k1", b"k2"]
            r = await conn.call("KVKeys", {"ns": "fn", "prefix": b"zzz"})
            assert r["keys"] == []
            r = await conn.call("KVDel", {"ns": "fn", "key": b"k1"})
            assert r == {"deleted": True}
            r = await conn.call("KVDel", {"ns": "fn", "key": b"k1"})
            assert r == {"deleted": False}
            r = await conn.call("KVGet", {"ns": "fn", "key": b"k1"})
            assert r == {"value": None}

            # All of the above were handled in C++ — Python never saw
            # the frames, and self.kv stayed empty.
            handled, appends, fails = gcs._native_svc.counters()
            assert handled >= 10
            assert appends >= 4   # 3 puts + 1 effective delete
            assert fails == 0
            assert gcs.kv == {}
            n_ns, n_rows = gcs._native_svc.kv_stats()
            assert (n_ns, n_rows) == (2, 2)   # fn:k2, other:k3
            await conn.close()
        finally:
            await gcs.stop()

    run(main())


def test_pubsub_native_fanout(tmp_path):
    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            sub1 = await rpc.connect(host, port)
            sub2 = await rpc.connect(host, port)
            pub = await rpc.connect(host, port)
            got1, got2 = [], []
            ev1, ev2 = asyncio.Event(), asyncio.Event()

            def on_pub(sink, ev):
                def h(conn, payload):
                    sink.append(payload)
                    ev.set()
                return h

            sub1.handlers["Publish"] = on_pub(got1, ev1)
            sub2.handlers["Publish"] = on_pub(got2, ev2)
            assert (await sub1.call("Subscribe",
                                    {"channels": ["X"]}))["ok"]
            assert (await sub2.call("Subscribe",
                                    {"channels": ["X", "Y"]}))["ok"]
            assert gcs._native_svc.sub_count("X") == 2
            assert gcs._native_svc.sub_count("Y") == 1

            # External publish RPC: native fanout to both.
            r = await pub.call("Publish", {"channel": "X",
                                           "message": {"n": 1}})
            assert r == {"ok": True}
            await asyncio.wait_for(ev1.wait(), 5)
            await asyncio.wait_for(ev2.wait(), 5)
            assert got1 == [{"channel": "X", "message": {"n": 1}}]
            assert got2 == [{"channel": "X", "message": {"n": 1}}]

            # Internal publish (the path actor/node/PG state changes
            # use): routed through the native fanout too.
            ev2.clear()
            await gcs.publish("Y", {"n": 2})
            await asyncio.wait_for(ev2.wait(), 5)
            assert got2[-1] == {"channel": "Y", "message": {"n": 2}}
            # Python-side subscriber table stayed empty: the
            # subscriptions live in the native service.
            assert not any(gcs.subscribers.values())

            # Disconnect cleans native subscriber state.
            await sub2.close()
            for _ in range(100):
                if gcs._native_svc.sub_count("X") == 1:
                    break
                await asyncio.sleep(0.02)
            assert gcs._native_svc.sub_count("X") == 1
            assert gcs._native_svc.sub_count("Y") == 0
            await sub1.close()
            await pub.close()
        finally:
            await gcs.stop()

    run(main())


def test_restart_restores_native_rows(tmp_path):
    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        conn = await rpc.connect(host, port)
        await conn.call("KVPut", {"ns": "fn", "key": b"pk",
                                  "value": b"pv"})
        await conn.call("KVPut", {"ns": "", "key": b"root",
                                  "value": b"rv"})
        await conn.close()
        await gcs.stop()

        gcs2, host2, port2 = await _start_gcs(tmp_path)
        try:
            assert gcs2._native_svc is not None
            assert gcs2._native_svc.kv_stats()[1] == 2
            conn2 = await rpc.connect(host2, port2)
            assert (await conn2.call("KVGet", {"ns": "fn",
                                               "key": b"pk"}))["value"] \
                == b"pv"
            assert (await conn2.call("KVGet",
                                     {"key": b"root"}))["value"] == b"rv"
            await conn2.close()
        finally:
            await gcs2.stop()

    run(main())


def test_cross_compat_python_and_native_rows(tmp_path, monkeypatch):
    """Rows written by the Python fallback restore under the native
    service and vice versa — the store format is shared."""
    async def write_python_side():
        monkeypatch.setenv("RAY_TPU_NATIVE_GCS_SERVICE", "0")
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            assert gcs._native_svc is None
            conn = await rpc.connect(host, port)
            await conn.call("KVPut", {"ns": "compat", "key": b"from-py",
                                      "value": b"py-val"})
            await conn.close()
        finally:
            await gcs.stop()
        monkeypatch.delenv("RAY_TPU_NATIVE_GCS_SERVICE")

    async def native_reads_then_writes():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            assert gcs._native_svc is not None
            conn = await rpc.connect(host, port)
            assert (await conn.call(
                "KVGet", {"ns": "compat",
                          "key": b"from-py"}))["value"] == b"py-val"
            await conn.call("KVPut", {"ns": "compat", "key": b"from-c",
                                      "value": b"c-val"})
            await conn.close()
        finally:
            await gcs.stop()

    async def python_reads_native_row():
        monkeypatch.setenv("RAY_TPU_NATIVE_GCS_SERVICE", "0")
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            assert (await conn.call(
                "KVGet", {"ns": "compat",
                          "key": b"from-c"}))["value"] == b"c-val"
            assert (await conn.call(
                "KVGet", {"ns": "compat",
                          "key": b"from-py"}))["value"] == b"py-val"
            await conn.close()
        finally:
            await gcs.stop()
        monkeypatch.delenv("RAY_TPU_NATIVE_GCS_SERVICE")

    run(write_python_side())
    run(native_reads_then_writes())
    run(python_reads_native_row())


def test_malformed_known_method_errors_not_passthrough(tmp_path):
    """A malformed payload for a method the native service owns must
    come back as an RpcError — passing it to Python would answer from
    the (empty) Python tables and silently diverge."""
    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            await conn.call("KVPut", {"ns": "x", "key": b"k",
                                      "value": b"v"})
            with pytest.raises(rpc.RpcError, match="malformed"):
                await conn.call("KVGet", {"ns": "x"})   # no "key"
            assert gcs._native_svc.proto_errors() == 1
            # The well-formed path still works afterwards.
            assert (await conn.call(
                "KVGet", {"ns": "x", "key": b"k"}))["value"] == b"v"
            await conn.close()
        finally:
            await gcs.stop()

    run(main())


def test_idempotent_reput_skips_wal(tmp_path):
    """Re-putting an identical value must not append to the WAL
    (parity with the Python write-through's hash-diff dedup)."""
    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            await conn.call("KVPut", {"ns": "x", "key": b"k",
                                      "value": b"v"})
            appends_before = gcs._native_svc.counters()[1]
            for _ in range(5):
                r = await conn.call("KVPut", {"ns": "x", "key": b"k",
                                              "value": b"v"})
                assert r == {"added": True}
            assert gcs._native_svc.counters()[1] == appends_before
            # A changed value DOES append.
            await conn.call("KVPut", {"ns": "x", "key": b"k",
                                      "value": b"v2"})
            assert gcs._native_svc.counters()[1] == appends_before + 1
            await conn.close()
        finally:
            await gcs.stop()

    run(main())


def test_str_and_bytes_keys_are_distinct(tmp_path):
    """Key identity is the raw msgpack encoding: "a" (str) and b"a"
    (bin) are different keys, matching the Python dict fallback."""
    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            await conn.call("KVPut", {"ns": "t", "key": "a",
                                      "value": b"str-key"})
            await conn.call("KVPut", {"ns": "t", "key": b"a",
                                      "value": b"bin-key"})
            assert (await conn.call(
                "KVGet", {"ns": "t", "key": "a"}))["value"] == b"str-key"
            assert (await conn.call(
                "KVGet", {"ns": "t", "key": b"a"}))["value"] == b"bin-key"
            await conn.close()
        finally:
            await gcs.stop()

    run(main())


def test_publish_missing_channel_malformed(tmp_path):
    """ADVICE r5: a Publish without "channel" (or without "message")
    must be rejected as malformed — the Python handler KeyErrors — not
    fanned out to channel "" with ok:true."""
    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            with pytest.raises(rpc.RpcError, match="malformed"):
                await conn.call("Publish", {"message": {"x": 1}})
            with pytest.raises(rpc.RpcError, match="malformed"):
                await conn.call("Publish", {"channel": "X"})
            # Well-formed publish still works.
            assert (await conn.call(
                "Publish", {"channel": "X", "message": {"x": 1}}))["ok"]
            assert gcs._native_svc.proto_errors() == 2
            await conn.close()
        finally:
            await gcs.stop()

    run(main())


def test_subscribe_missing_channels_malformed(tmp_path):
    """ADVICE r5: Subscribe without a "channels" list must error like
    the Python handler (KeyError), not return ok:true."""
    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            with pytest.raises(rpc.RpcError, match="malformed"):
                await conn.call("Subscribe", {})
            # An EMPTY channels list is well-formed (subscribes to
            # nothing), matching the Python for-loop semantics.
            assert (await conn.call("Subscribe", {"channels": []}))["ok"]
            await conn.close()
        finally:
            await gcs.stop()

    run(main())


def _raw_kvput_frame(seq: int) -> bytes:
    """A KVPut request whose key uses a VALID but NON-CANONICAL msgpack
    encoding (bin16 for a 1-byte key — msgpack-python would emit bin8).
    Hand-built: pack() always produces canonical forms."""
    body = bytes([0x94])                    # [msg_type, seq, method, payload]
    body += bytes([0x00])                   # MSG_REQUEST
    body += bytes([seq])                    # seq (fixint)
    body += bytes([0xa5]) + b"KVPut"
    body += bytes([0x83])                   # map3
    body += bytes([0xa2]) + b"ns" + bytes([0xa1]) + b"t"
    body += bytes([0xa3]) + b"key" + bytes([0xc5, 0x00, 0x01]) + b"a"
    body += bytes([0xa5]) + b"value" + bytes([0xc4, 0x01]) + b"v"
    return len(body).to_bytes(4, "big") + body


def test_noncanonical_key_encoding_canonicalizes(tmp_path, monkeypatch):
    """ADVICE r5: RowKeyHex must canonicalize the key encoding so native
    and Python compute identical store row keys for any accepted wire
    encoding. A bin16-encoded b"a" written natively must (a) be the same
    logical row as canonical b"a", and (b) stay deleted after a
    Python-fallback delete + restart (no resurrecting rows)."""
    import asyncio as aio

    async def native_write_noncanonical():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            reader, writer = await aio.open_connection(host, port)
            writer.write(_raw_kvput_frame(1))
            await writer.drain()
            header = await reader.readexactly(4)
            resp = rpc.unpack(
                await reader.readexactly(int.from_bytes(header, "big")))
            assert resp[0] == rpc.MSG_RESPONSE and resp[3] == {"added": True}
            writer.close()
            # Canonical-key reads find the row (identity canonicalized).
            conn = await rpc.connect(host, port)
            assert (await conn.call(
                "KVGet", {"ns": "t", "key": b"a"}))["value"] == b"v"
            await conn.close()
        finally:
            await gcs.stop()

    async def python_deletes():
        monkeypatch.setenv("RAY_TPU_NATIVE_GCS_SERVICE", "0")
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            assert (await conn.call(
                "KVGet", {"ns": "t", "key": b"a"}))["value"] == b"v"
            assert (await conn.call(
                "KVDel", {"ns": "t", "key": b"a"}))["deleted"]
            await conn.close()
        finally:
            await gcs.stop()
        monkeypatch.delenv("RAY_TPU_NATIVE_GCS_SERVICE")

    async def stays_deleted():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            assert (await conn.call(
                "KVGet", {"ns": "t", "key": b"a"}))["value"] is None
            assert gcs._native_svc.kv_stats()[1] == 0
            await conn.close()
        finally:
            await gcs.stop()

    run(native_write_noncanonical())
    run(python_deletes())
    run(stays_deleted())


def test_str_key_restores_under_python_fallback(tmp_path, monkeypatch):
    """ADVICE r5: _restore_kv_row must preserve the decoded key TYPE —
    a str-keyed row written natively must answer a str-keyed KVGet
    after a fallback restart (the old .encode() coercion broke it)."""
    async def native_writes_str_key():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            conn = await rpc.connect(host, port)
            await conn.call("KVPut", {"ns": "t", "key": "skey",
                                      "value": b"sval"})
            await conn.close()
        finally:
            await gcs.stop()

    async def python_restores_str_key():
        monkeypatch.setenv("RAY_TPU_NATIVE_GCS_SERVICE", "0")
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            assert gcs._native_svc is None
            conn = await rpc.connect(host, port)
            assert (await conn.call(
                "KVGet", {"ns": "t", "key": "skey"}))["value"] == b"sval"
            await conn.close()
        finally:
            await gcs.stop()
        monkeypatch.delenv("RAY_TPU_NATIVE_GCS_SERVICE")

    run(native_writes_str_key())
    run(python_restores_str_key())


def test_native_factory_failure_closes_handle(tmp_path, monkeypatch):
    """ADVICE r5: if install fails after gsvc_create, the partially
    constructed native handle must be closed on the Python-fallback
    path, not leaked."""
    from ray_tpu._private import native_gcs_service

    closed = []
    orig_close = native_gcs_service.GcsNativeService.close

    def tracking_close(self):
        closed.append(True)
        orig_close(self)

    def broken_install(self):
        raise RuntimeError("injected install failure")

    monkeypatch.setattr(native_gcs_service.GcsNativeService, "close",
                        tracking_close)
    monkeypatch.setattr(native_gcs_service.GcsNativeService, "install",
                        broken_install)

    async def main():
        gcs, host, port = await _start_gcs(tmp_path)
        try:
            assert gcs._native_svc is None  # fell back to Python
            assert closed, "leaked native service handle on fallback"
            # The Python handlers serve KV after the fallback.
            conn = await rpc.connect(host, port)
            await conn.call("KVPut", {"ns": "x", "key": b"k",
                                      "value": b"v"})
            assert (await conn.call(
                "KVGet", {"ns": "x", "key": b"k"}))["value"] == b"v"
            await conn.close()
        finally:
            await gcs.stop()

    run(main())
