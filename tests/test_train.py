"""JaxTrainer tests (parity: reference python/ray/train/tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    CheckpointManager,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    session,
)


def test_single_worker_train(ray_start_regular):
    def loop(config):
        for step in range(3):
            session.report({"step": step, "loss": 1.0 / (step + 1)})

    result = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_two_worker_allreduce(ray_start_regular):
    def loop(config):
        from ray_tpu.util.collective import allreduce

        rank = session.get_world_rank()
        grad = np.full((8,), float(rank + 1))
        total = allreduce(grad, group_name=config["_collective_group"])
        session.report({"total": float(total[0]),
                        "world": session.get_world_size()})

    result = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["total"] == 3.0  # 1 + 2
    assert result.metrics["world"] == 2


def test_train_failure_surfaces(ray_start_regular):
    def loop(config):
        raise RuntimeError("train loop exploded")

    with pytest.raises(ray_tpu.exceptions.RayTpuError, match="exploded"):
        JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1)).fit()


def test_checkpoint_reported(ray_start_regular, tmp_path):
    ckpt_dir = str(tmp_path / "ck")

    def loop(config):
        import jax.numpy as jnp

        from ray_tpu.train.checkpoint import Checkpoint

        if session.get_world_rank() == 0:
            ck = Checkpoint.from_pytree(
                {"w": jnp.arange(4.0)}, config["dir"], metrics={"loss": 0.5})
            session.report({"done": 1}, checkpoint=ck)
        else:
            session.report({"done": 1})

    result = JaxTrainer(
        loop, train_loop_config={"dir": ckpt_dir},
        scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.checkpoint is not None
    tree = result.checkpoint.to_pytree()
    np.testing.assert_array_equal(np.asarray(tree["w"]), [0, 1, 2, 3])
    assert result.checkpoint.metrics() == {"loss": 0.5}


def test_checkpoint_manager_gc(tmp_path):
    mgr = CheckpointManager(str(tmp_path), num_to_keep=2)
    for i in range(4):
        mgr.save({"v": np.array([i])}, metrics={"i": i})
    cs = mgr.list()
    assert len(cs) == 2
    latest = mgr.latest().to_pytree()
    assert int(np.asarray(latest["v"])[0]) == 3


def test_torch_trainer_ddp(ray_start_regular):
    """TorchTrainer parity: gloo process group + DDP gradient averaging
    (reference: train/torch/config.py:63 + train_loop_utils.py:74)."""
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
        import torch
        import torch.distributed as dist
        from ray_tpu import train as rt

        from ray_tpu.train.torch import prepare_model

        torch.manual_seed(rt.session.get_world_rank())
        model = torch.nn.Linear(4, 1)
        # Identical init across ranks is DDP's job: broadcast at wrap.
        model = prepare_model(model)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        x = torch.ones(8, 4) * (rt.session.get_world_rank() + 1)
        y = torch.zeros(8, 1)
        for _ in range(3):
            opt.zero_grad()
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
        # All ranks end with identical (averaged) params.
        w = [p.detach().clone() for p in model.parameters()]
        flat = torch.cat([t.flatten() for t in w])
        if dist.is_initialized():
            gathered = [torch.zeros_like(flat)
                        for _ in range(dist.get_world_size())]
            dist.all_gather(gathered, flat)
            same = all(torch.allclose(g, flat) for g in gathered)
        else:
            same = True
        rt.report({"loss": float(loss.item()), "params_synced": bool(same)})

    result = TorchTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["params_synced"] is True
    assert "loss" in result.metrics


def test_accelerate_trainer(ray_start_regular):
    """AccelerateTrainer parity (reference: train/huggingface/accelerate
    AccelerateTrainer): the user loop builds accelerate.Accelerator()
    over the gang's pre-initialized gloo group; prepare()/backward()/
    gather() work, and DDP-averaged params end identical across ranks."""
    from ray_tpu.train.accelerate import AccelerateTrainer

    def loop(config):
        import torch
        from accelerate import Accelerator
        from ray_tpu import train as rt

        accelerator = Accelerator(cpu=True)
        assert accelerator.num_processes == 2
        # The accelerate_config dict must actually reach Accelerator()
        # (exported as the ACCELERATE_* env contract).
        assert accelerator.gradient_accumulation_steps == 2
        torch.manual_seed(rt.session.get_world_rank())
        model = torch.nn.Linear(4, 1)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        model, opt = accelerator.prepare(model, opt)
        x = torch.ones(8, 4) * (rt.session.get_world_rank() + 1)
        y = torch.zeros(8, 1)
        for _ in range(3):
            opt.zero_grad()
            loss = ((model(x) - y) ** 2).mean()
            accelerator.backward(loss)
            opt.step()
        flat = torch.cat([p.detach().flatten()
                          for p in model.parameters()])
        gathered = accelerator.gather(flat.unsqueeze(0))
        same = bool(torch.allclose(gathered[0], gathered[1]))
        rt.report({"loss": float(loss.item()), "params_synced": same,
                   "world": accelerator.num_processes})

    result = AccelerateTrainer(
        loop, accelerate_config={"gradient_accumulation_steps": 2},
        scaling_config=ScalingConfig(num_workers=2)).fit()
    assert result.metrics["params_synced"] is True
    assert result.metrics["world"] == 2


def test_elastic_restart_restores_checkpoint(ray_start_regular, tmp_path):
    """A worker crash mid-fit retries the whole gang; the retry resumes
    from the last reported checkpoint via session.get_checkpoint()
    (elasticity = checkpoint-restart for fixed-shape XLA programs)."""
    from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer,
                               RunConfig, ScalingConfig, session)

    crash_flag = str(tmp_path / "crashed_once")

    def loop(cfg):
        import os
        import time

        start = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_dict()["step"] + 1
        for step in range(start, 6):
            session.report({"step": step, "resumed_from": start},
                           checkpoint={"step": step})
            # Let the driver's 50ms poll drain this report before a crash —
            # un-polled reports die with the worker (by design), which
            # would make the resume point nondeterministic.
            time.sleep(0.2)
            if step == 3 and not os.path.exists(cfg["crash_flag"]):
                open(cfg["crash_flag"], "w").close()
                os._exit(1)  # hard crash, not an exception

    trainer = JaxTrainer(
        loop, train_loop_config={"crash_flag": crash_flag},
        scaling_config=ScalingConfig(num_workers=1, use_tpu=False),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.metrics["step"] == 5
    # The retry resumed from the last checkpoint the DRIVER had received
    # before the crash (reports are async, so it may trail the crash step
    # by a poll interval) — but it must not have started from scratch.
    assert result.metrics["resumed_from"] >= 1


def test_sklearn_trainer(ray_start_regular, tmp_path):
    """SklearnTrainer parity (reference: train/sklearn/sklearn_trainer.py):
    fit in a worker, scores in metrics, fitted estimator in the
    checkpoint."""
    import numpy as np
    import pytest

    sklearn = pytest.importorskip("sklearn")
    from sklearn.linear_model import LogisticRegression

    from ray_tpu import data
    from ray_tpu.train.sklearn import SklearnTrainer

    rng = np.random.default_rng(0)
    X = rng.standard_normal((120, 3))
    y = (X @ np.array([1.0, -2.0, 0.5]) > 0).astype(int)
    rows = [{"a": float(x[0]), "b": float(x[1]), "c": float(x[2]),
             "label": int(t)} for x, t in zip(X, y)]
    trainer = SklearnTrainer(
        estimator=LogisticRegression(),
        datasets={"train": data.from_items(rows[:100]),
                  "valid": data.from_items(rows[100:])},
        label_column="label")
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["train_score"] > 0.9
    assert result.metrics["valid_score"] > 0.8
    model = SklearnTrainer.get_model(result.checkpoint)
    preds = model.predict(np.asarray([[2.0, -3.0, 1.0]]))
    assert preds[0] == 1
