"""Multi-node semantics via the fake cluster: spillback scheduling,
cross-node object transfer, node death.

Parity: reference python/ray/tests/test_multi_node*.py +
test_object_reconstruction* over cluster_utils.Cluster.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_two_nodes_spillback(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.connect()
    cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes(2)

    @ray_tpu.remote(num_cpus=1)
    def where():
        # Long enough that all 5 leases are concurrently occupied even with
        # multi-second worker cold-starts, so lease reuse can't serialize
        # everything through one node.
        time.sleep(5)
        return ray_tpu.get_runtime_context().node_id

    # 5 concurrent 1-CPU tasks on a 1+4 CPU cluster must use both nodes.
    refs = [where.remote() for _ in range(5)]
    nodes = set(ray_tpu.get(refs, timeout=120))
    assert len(nodes) == 2


def test_cross_node_object_transfer(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    n2 = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes(2)

    @ray_tpu.remote(num_cpus=2)
    def produce():
        return np.full((512, 1024), 7.0)  # 4MB, lands in producer's store

    @ray_tpu.remote(num_cpus=2)
    def consume(arr):
        return float(arr.sum())

    # Force produce and consume onto different nodes by saturating each.
    ref = produce.remote()
    ray_tpu.wait([ref], timeout=30)
    total = ray_tpu.get(consume.remote(ref), timeout=60)
    assert total == 7.0 * 512 * 1024


def test_driver_gets_remote_object(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes(2)

    @ray_tpu.remote(num_cpus=3)  # only fits on the second node
    def produce():
        return np.arange(1 << 20, dtype=np.float64)  # 8MB

    out = ray_tpu.get(produce.remote(), timeout=60)
    assert out.shape == (1 << 20,)
    assert out[123] == 123.0


def test_node_death_detected(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    n2 = cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes(2)
    cluster.remove_node(n2)
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        if len(alive) == 1:
            return
        time.sleep(0.1)
    pytest.fail("node death not detected")


def test_task_retry_after_node_death(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    n2 = cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes(2)

    @ray_tpu.remote(num_cpus=3, max_retries=3)
    def slow_task():
        time.sleep(3)
        return "done"

    ref = slow_task.remote()
    time.sleep(1.0)  # task is now running on n2
    cluster.remove_node(n2)
    cluster.add_node(num_cpus=4)
    # Retry must reschedule onto the new node.
    assert ray_tpu.get(ref, timeout=120) == "done"


def test_object_reconstruction_after_node_death(ray_start_cluster_head):
    """Lineage recovery: all copies of a task-produced object are lost with
    its node; the owner resubmits the creating task (reference:
    object_recovery_manager.h:96 ReconstructObject)."""
    cluster = ray_start_cluster_head
    n2 = cluster.add_node(num_cpus=4)
    cluster.wait_for_nodes(2)

    @ray_tpu.remote(num_cpus=3, max_retries=3)
    def produce():
        return np.ones(1 << 20)  # 8MB: stored in producer node's shm

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=60)
    cluster.remove_node(n2)
    cluster.add_node(num_cpus=4)
    time.sleep(0.5)
    out = ray_tpu.get(ref, timeout=120)
    assert out.sum() == float(1 << 20)


def test_connect_by_address_only(ray_start_cluster):
    """ray_tpu.init(address=...) bootstraps from the GCS node table with no
    raylet hints (reference: ray.init(address=...) connect path)."""
    import ray_tpu

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.gcs_address)
    try:
        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get(f.remote(41)) == 42
        assert len([n for n in ray_tpu.nodes() if n["alive"]]) == 1
    finally:
        ray_tpu.shutdown()


def test_large_object_broadcast(ray_start_cluster):
    """A multi-chunk (64MB > parallel-stripe threshold) object broadcasts
    from its creating node to every other node via the chunked native
    transfer plane (reference: the 1 GiB broadcast scalability-envelope
    row, release/benchmarks; full-size run lives in release_tests.yaml
    object_broadcast)."""
    from ray_tpu.cluster_utils import Cluster  # noqa: F401
    from ray_tpu._private.config import Config

    cluster = ray_start_cluster
    cluster._node.config.object_store_memory = 192 * 1024 * 1024
    cluster.add_node(num_cpus=1)
    cluster.connect()
    n2 = cluster.add_node(num_cpus=1)
    n3 = cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes(3)

    blob = np.arange(8 * 1024 * 1024, dtype=np.float64)  # 64MB
    ref = ray_tpu.put(blob)

    @ray_tpu.remote(num_cpus=1)
    def consume(x):
        # Chunked native pull into THIS node's store, then zero-copy read.
        return float(x[0]), float(x[-1]), int(x.nbytes)

    # Two consumers pinned to the two non-owner nodes via spread.
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    outs = ray_tpu.get(
        [consume.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=n.node_id)).remote(ref) for n in (n2, n3)],
        timeout=300)
    for first, last, nbytes in outs:
        assert first == 0.0
        assert last == float(8 * 1024 * 1024 - 1)
        assert nbytes == 64 * 1024 * 1024
