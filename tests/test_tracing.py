"""Distributed tracing: span creation + cross-process context propagation.

Parity: reference python/ray/tests/test_tracing.py (spans around
submission/execution, context rides in the TaskSpec). The builtin W3C
propagation works without an OpenTelemetry SDK installed; an SDK provider,
when present, additionally receives real spans.
"""

import ray_tpu
from ray_tpu.util import tracing


def test_traceparent_propagates_to_task(ray_start_regular):
    tracing.setup_tracing()

    @ray_tpu.remote
    def traced_task():
        # Workers auto-enable via RAY_TPU_TRACING; the execute span's
        # context is live inside the user function.
        from ray_tpu.util import tracing as worker_tracing

        return worker_tracing.current_traceparent()

    with tracing.submit_span("driver-root", "root") as root_tp:
        assert root_tp.startswith("00-")
        root_trace_id = root_tp.split("-")[1]
        worker_tp = ray_tpu.get(traced_task.remote(), timeout=60)

    # Worker-side context carries the SAME trace id as the driver root
    # (submission span -> TaskSpec.trace_ctx -> execution span).
    assert worker_tp, "worker did not produce a traceparent"
    assert worker_tp.split("-")[1] == root_trace_id
    # ...but a distinct span id (it is a child, not the same span).
    assert worker_tp.split("-")[2] != root_tp.split("-")[2]


def test_actor_call_propagates(ray_start_regular):
    tracing.setup_tracing()

    @ray_tpu.remote
    class Traced:
        def tp(self):
            from ray_tpu.util import tracing as worker_tracing

            return worker_tracing.current_traceparent()

    a = Traced.remote()
    with tracing.submit_span("driver-root", "root") as root_tp:
        got = ray_tpu.get(a.tp.remote(), timeout=60)
    assert got.split("-")[1] == root_tp.split("-")[1]


def test_traceparent_format_roundtrip():
    tp = tracing._format_traceparent("a" * 32, "b" * 16)
    assert tracing._parse_traceparent(tp) == ("a" * 32, "b" * 16)
    assert tracing._parse_traceparent("junk") is None
    assert tracing._parse_traceparent("00-short-bad-01") is None


def test_spec_default_has_no_trace():
    from ray_tpu._private.common import TaskSpec

    spec = TaskSpec(task_id="t", job_id="j", name="n", func_key="k")
    assert spec.trace_ctx == ""
