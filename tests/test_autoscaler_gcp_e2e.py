"""Hermetic end-to-end test of the GCP TPU provider reconcile loop:
provision (queued-resources) -> READY -> bootstrap (ssh fan-out, with a
failure retried) -> idle -> drain -> terminate — against a FAKE gcloud
binary so the whole flow runs without GCP (reference model:
autoscaler fake-provider tests + the queued-resources TPU-VM flow)."""

import json
import os
import stat
import sys

import pytest

from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.gcp_tpu import GCPTPUNodeProvider
from ray_tpu.autoscaler.node_provider import NodeType

FAKE_GCLOUD = '''#!{python}
import json, os, sys
STATE = {state!r}
LOG = {log!r}
def load():
    if os.path.exists(STATE):
        with open(STATE) as f:
            return json.load(f)
    return {{"tpus": {{}}, "queued": {{}}, "fail_ssh": 0}}
def save(s):
    with open(STATE, "w") as f:
        json.dump(s, f)
args = sys.argv[1:]
with open(LOG, "a") as f:
    f.write(json.dumps(args) + chr(10))
s = load()
op = args[:4]
if op == ["compute", "tpus", "queued-resources", "create"]:
    s["queued"][args[4]] = "WAITING_FOR_RESOURCES"
    save(s); sys.exit(0)
if op == ["compute", "tpus", "tpu-vm", "list"]:
    print(json.dumps([{{"name": n, "state": st}}
                      for n, st in s["tpus"].items()])); sys.exit(0)
if op == ["compute", "tpus", "queued-resources", "list"]:
    print(json.dumps([{{"name": n, "state": {{"state": st}}}}
                      for n, st in s["queued"].items()])); sys.exit(0)
if op == ["compute", "tpus", "tpu-vm", "ssh"]:
    if s.get("fail_ssh", 0) > 0:
        s["fail_ssh"] -= 1; save(s)
        sys.stderr.write("ssh: connect refused" + chr(10)); sys.exit(1)
    sys.exit(0)
if op == ["compute", "tpus", "queued-resources", "delete"]:
    s["queued"].pop(args[4], None); s["tpus"].pop(args[4], None)
    save(s); sys.exit(0)
if op == ["compute", "tpus", "tpu-vm", "delete"]:
    s["tpus"].pop(args[4], None); save(s); sys.exit(0)
sys.stderr.write("fake gcloud: unknown op " + repr(args[:4]) + chr(10))
sys.exit(2)
'''


@pytest.fixture()
def fake_gcloud(tmp_path, monkeypatch):
    state = tmp_path / "gcloud_state.json"
    log = tmp_path / "gcloud_calls.log"
    exe = tmp_path / "gcloud"
    exe.write_text(FAKE_GCLOUD.format(python=sys.executable,
                                      state=str(state), log=str(log)))
    exe.chmod(exe.stat().st_mode | stat.S_IXUSR)
    monkeypatch.setenv("PATH", f"{tmp_path}{os.pathsep}"
                               f"{os.environ.get('PATH', '')}")

    class Ctl:
        def calls(self):
            if not log.exists():
                return []
            return [json.loads(l) for l in log.read_text().splitlines()]

        def state(self):
            return json.loads(state.read_text())

        def set_state(self, s):
            state.write_text(json.dumps(s))

    return Ctl()


def _provider():
    return GCPTPUNodeProvider({
        "project": "proj", "zone": "us-central2-b",
        "accelerator_type": "v5e-8", "runtime_version": "tpu-ubuntu2204",
        "head_address": "10.0.0.1:6379",
    })


def test_provision_bootstrap_drain_terminate_cycle(fake_gcloud):
    provider = _provider()
    tpu_type = NodeType("tpu", {"TPU": 8.0}, max_workers=4)
    drained: list = []
    status = {"nodes": [], "pending_demand": [{"TPU": 8.0}],
              "pending_placement_groups": []}
    scaler = StandardAutoscaler(
        provider, [tpu_type], get_cluster_status=lambda: status,
        drain_node=lambda nid, **kw: drained.append((nid, kw)),
        idle_timeout_s=0.0)

    # Tick 1: unmet TPU demand -> queued-resource created.
    scaler.update()
    st = fake_gcloud.state()
    assert len(st["queued"]) == 1
    (name,) = st["queued"]
    assert name.startswith("ray-tpu-")
    assert st["queued"][name] == "WAITING_FOR_RESOURCES"

    # Tick 2: still waiting -> queued capacity counts, NO duplicate launch.
    scaler.update()
    assert len(fake_gcloud.state()["queued"]) == 1

    # Capacity arrives; first bootstrap SSH fails and must be retried.
    st = fake_gcloud.state()
    st["tpus"][name] = "READY"
    st["fail_ssh"] = 1
    fake_gcloud.set_state(st)
    scaler.update()
    info = provider._nodes[name]
    assert info.get("bootstrap_failures") == 1
    assert "bootstrap_error" in info
    scaler.update()  # retried next tick
    assert provider._nodes[name].get("bootstrapped") is True
    ssh_calls = [c for c in fake_gcloud.calls() if c[2:4] == ["tpu-vm", "ssh"]]
    assert len(ssh_calls) == 2
    assert any(f"TPU_NAME={name}" in arg
               for arg in ssh_calls[-1] if "--command=" in arg)

    # The slice registers with the GCS under its own node ids, carrying
    # the tpu-slice label; demand clears -> idle -> drain -> terminate.
    status["pending_demand"] = []
    status["nodes"] = [
        {"node_id": f"gcsnode{i}", "alive": True,
         "available_resources": {"TPU": 8.0},
         "total_resources": {"TPU": 8.0},
         "labels": {"tpu-slice": name}}
        for i in range(2)
    ]
    scaler.update()  # marks idle
    scaler.update()  # terminates after the (0s) timeout
    assert [d[0] for d in drained] == ["gcsnode0", "gcsnode1"]
    assert all(kw["reason"] == "idle" and kw["deadline_s"] > 0
               for _nid, kw in drained)
    assert fake_gcloud.state()["queued"] == {}
    assert provider.non_terminated_nodes() == []
    deletes = [c for c in fake_gcloud.calls()
               if c[2:4] == ["queued-resources", "delete"]]
    assert len(deletes) == 1 and deletes[0][4] == name


def test_busy_slice_not_terminated(fake_gcloud):
    provider = _provider()
    tpu_type = NodeType("tpu", {"TPU": 8.0}, max_workers=4)
    status = {"nodes": [], "pending_demand": [{"TPU": 8.0}],
              "pending_placement_groups": []}
    scaler = StandardAutoscaler(
        provider, [tpu_type], get_cluster_status=lambda: status,
        idle_timeout_s=0.0)
    scaler.update()
    (name,) = fake_gcloud.state()["queued"]
    st = fake_gcloud.state()
    st["tpus"][name] = "READY"
    fake_gcloud.set_state(st)
    scaler.update()
    # One host busy (resources in use): the slice must NOT be terminated
    # even with zero demand.
    status["pending_demand"] = []
    status["nodes"] = [
        {"node_id": "a", "alive": True,
         "available_resources": {"TPU": 0.0},
         "total_resources": {"TPU": 8.0}, "labels": {"tpu-slice": name}},
        {"node_id": "b", "alive": True,
         "available_resources": {"TPU": 8.0},
         "total_resources": {"TPU": 8.0}, "labels": {"tpu-slice": name}},
    ]
    scaler.update()
    scaler.update()
    assert name in fake_gcloud.state()["tpus"]
