"""Structured event framework (parity: reference src/ray/util/event.h +
dashboard event module)."""

import glob
import os

import ray_tpu
from ray_tpu.util.events import configure, list_events, record


def test_record_and_list(tmp_path):
    configure(str(tmp_path), "unit")
    record("INFO", "test", "hello", a=1)
    record("ERROR", "test", "boom")
    record("DEBUG", "other", "noise")
    evts = list_events(str(tmp_path))
    assert [e["message"] for e in evts] == ["hello", "boom", "noise"]
    errs = list_events(str(tmp_path), min_severity="ERROR")
    assert [e["message"] for e in errs] == ["boom"]
    assert evts[0]["fields"] == {"a": 1}
    only = list_events(str(tmp_path), source="other")
    assert [e["message"] for e in only] == ["noise"]


def test_daemons_emit_lifecycle_events(ray_start_regular):
    @ray_tpu.remote
    def ping():
        return 1

    assert ray_tpu.get(ping.remote()) == 1
    sessions = sorted(glob.glob("/tmp/ray_tpu_sessions/session-*"),
                      key=os.path.getmtime)
    evts = list_events(sessions[-1])
    messages = {e["message"] for e in evts}
    assert "node started" in messages  # raylet boot event
    sources = {e["source"] for e in evts}
    assert "raylet" in sources
