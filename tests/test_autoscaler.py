"""Autoscaler tests (parity: reference tests/test_autoscaler.py unit tests
+ test_autoscaler_fake_multinode.py end-to-end)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import FakeNodeProvider, NodeType, StandardAutoscaler


def test_bin_packing_unit():
    sched = StandardAutoscaler(
        provider=None,
        node_types=[NodeType("cpu4", {"CPU": 4.0}),
                    NodeType("tpu_host", {"CPU": 8.0, "TPU": 4.0})],
        get_cluster_status=lambda: None)
    # 6 one-CPU tasks, 2 CPU free on existing nodes -> 1 new cpu4 node.
    out = sched.get_nodes_to_launch(
        [{"CPU": 1.0}] * 6, [], [{"CPU": 2.0}])
    assert out == {"cpu4": 1}
    # TPU demand picks the TPU type.
    out = sched.get_nodes_to_launch([{"TPU": 4.0}], [], [])
    assert out == {"tpu_host": 1}


def test_strict_ici_launches_slice():
    sched = StandardAutoscaler(
        provider=None,
        node_types=[NodeType("v4_slice", {"CPU": 8.0, "TPU": 4.0},
                             hosts_per_slice=4)],
        get_cluster_status=lambda: None)
    out = sched.get_nodes_to_launch(
        [], [{"strategy": "STRICT_ICI",
              "bundles": [{"TPU": 4.0}] * 4}], [])
    assert out == {"v4_slice": 1}


def test_autoscaler_end_to_end(ray_start_cluster_head):
    """Infeasible demand -> fake provider launches a node -> task runs."""
    cluster = ray_start_cluster_head  # head: 2 CPUs
    provider = FakeNodeProvider(cluster._node)
    cw = ray_tpu._private.api_internal.get_core_worker()

    def get_status():
        return cw._run(cw.gcs.call("GetClusterStatus", {}))

    autoscaler = StandardAutoscaler(
        provider,
        node_types=[NodeType("cpu8", {"CPU": 8.0}, max_workers=2)],
        get_cluster_status=get_status,
        idle_timeout_s=3600)
    autoscaler.start(interval_s=0.5)
    try:
        @ray_tpu.remote(num_cpus=8)  # does not fit the 2-CPU head
        def big():
            return "scaled"

        assert ray_tpu.get(big.remote(), timeout=120) == "scaled"
        assert len(provider.non_terminated_nodes()) == 1
    finally:
        autoscaler.stop()


def test_fake_provider_slice_labels(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    provider = FakeNodeProvider(cluster._node)
    created = provider.create_node(
        NodeType("v4_slice", {"CPU": 1.0, "TPU": 4.0}, hosts_per_slice=2))
    assert len(created) == 2
    cluster.wait_for_nodes(3)
    by_id = {n["node_id"]: n for n in ray_tpu.nodes()}
    labels = [by_id[nid]["labels"] for nid in created]
    assert labels[0]["tpu-slice"] == labels[1]["tpu-slice"]
    assert {l["tpu-worker-id"] for l in labels} == {"0", "1"}


def test_monitor_notifies_gcs_when_terminating_undrained():
    """When a drain fails (or times out) the autoscaler terminates the
    node anyway — the monitor must hand the GCS a NotifyNodeDead death
    certificate so failover starts immediately instead of waiting out
    heartbeat grace."""
    from ray_tpu.autoscaler.monitor import Monitor

    calls = []

    class FakeConn:
        def call(self, method, payload, **kw):
            calls.append((method, payload))
            if method == "DrainNode":
                return {"ok": False, "error": "raylet wedged"}
            return {"ok": True}

    mon = object.__new__(Monitor)
    mon._conn = FakeConn()
    mon._call_async = lambda resp, timeout=30.0: resp

    assert mon.drain_node("deadbeef" * 8, reason="idle") is False
    drains = [c for c in calls if c[0] == "DrainNode"]
    assert len(drains) == 2  # retried once before escalating
    notifies = [c for c in calls if c[0] == "NotifyNodeDead"]
    assert len(notifies) == 1
    assert notifies[0][1]["node_id"] == "deadbeef" * 8
    assert "drain failed" in notifies[0][1]["reason"]


def test_gcp_tpu_provider_commands():
    """The gcloud argv surfaces are the provider contract (no cloud in
    tests); reference: gcp/tpu_command_runner.py --worker=all fan-out."""
    from ray_tpu.autoscaler.gcp_tpu import GCPTPUNodeProvider

    p = GCPTPUNodeProvider({
        "project": "proj", "zone": "us-central2-b",
        "accelerator_type": "v5e-8",
        "runtime_version": "tpu-ubuntu2204-base", "spot": True})
    create = p.create_command("n1", NodeType("tpu", {"TPU": 8}))
    assert "queued-resources" in create and "--spot" in create
    assert "--accelerator-type=v5e-8" in create
    ssh = p.ssh_fanout_command("n1", "echo hi")
    assert "--worker=all" in ssh  # every host of the slice
    delete = p.delete_command("n1")
    assert "--quiet" in delete and "delete" in delete
    assert p.node_resources("n1") == {"TPU": 8.0}
    import pytest as _pytest

    with _pytest.raises(ValueError):
        GCPTPUNodeProvider({"project": "p"})


def test_cluster_config_yaml(tmp_path):
    from ray_tpu.autoscaler import (load_cluster_config,
                                    node_types_from_config)

    cfg_path = tmp_path / "cluster.yaml"
    cfg_path.write_text("""
cluster_name: demo
max_workers: 4
provider:
  type: gcp_tpu
  project: proj
  zone: us-central2-b
  accelerator_type: v5e-8
  runtime_version: tpu-ubuntu2204-base
available_node_types:
  tpu_worker:
    resources: {"TPU": 8, "CPU": 16}
    min_workers: 0
    hosts_per_slice: 2
""")
    cfg = load_cluster_config(str(cfg_path))
    types = node_types_from_config(cfg)
    assert types[0].name == "tpu_worker"
    assert types[0].hosts_per_slice == 2
    assert types[0].resources["TPU"] == 8

    bad = tmp_path / "bad.yaml"
    bad.write_text("cluster_name: x\n")
    import pytest as _pytest

    with _pytest.raises(ValueError):
        load_cluster_config(str(bad))
