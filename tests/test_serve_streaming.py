"""Serve streaming responses + replica-death retry + Data stats/readers
(reference test models: serve/tests/test_streaming_response.py,
test_replica_failure.py; data/tests/test_stats.py)."""

import json
import os
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture()
def serve_cluster(ray_start_regular):
    yield
    serve.shutdown()


def test_streaming_handle(serve_cluster):
    @serve.deployment
    def tokens(payload):
        for i in range(int(payload.get("n", 5))):
            yield f"tok{i}"

    serve.run(tokens.bind())
    handle = serve.get_deployment_handle("tokens")
    out = list(handle.options(stream=True).remote({"n": 7}))
    assert out == [f"tok{i}" for i in range(7)]


def test_streaming_http_chunked(serve_cluster):
    @serve.deployment
    def counter(payload):
        for i in range(int(payload.get("n", 3))):
            yield i * 10

    serve.run(counter.bind())
    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/counter?stream=1",
        data=json.dumps({"n": 4}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        lines = [json.loads(ln) for ln in r.read().decode().splitlines() if ln]
    assert [d["chunk"] for d in lines] == [0, 10, 20, 30]


def test_retry_on_replica_death(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, payload):
            return {"pid": os.getpid(), "v": payload["v"]}

    serve.run(Echo.bind())
    handle = serve.get_deployment_handle("Echo")
    # Warm the router, then kill one replica out from under it: the
    # in-flight response retries on a survivor instead of failing.
    assert handle.remote({"v": 1}).result(timeout=60)["v"] == 1
    controller = serve._get_controller()
    replicas = ray_tpu.get(controller.get_replicas.remote("Echo"))
    resp = handle.remote({"v": 2})
    ray_tpu.kill(replicas[0])
    ray_tpu.kill(replicas[1])
    # At least one of the two kills lands on the serving replica; retry
    # must reroute once the controller restarts replicas.
    out = resp.result(timeout=120)
    assert out["v"] == 2


def test_data_stats_and_new_readers(ray_start_regular, tmp_path):
    from ray_tpu import data

    ds = data.range(100).map(lambda x: x * 2)
    total = ds.sum()
    assert total == sum(x * 2 for x in range(100))
    s = ds.stats()
    assert "blocks" in s and "Wall time" in s

    # read_binary_files
    p1 = tmp_path / "a.bin"
    p2 = tmp_path / "b.bin"
    p1.write_bytes(b"\x01\x02")
    p2.write_bytes(b"\x03")
    bds = data.read_binary_files([str(p1), str(p2)], include_paths=True)
    rows = sorted(bds.take_all(), key=lambda r: r["path"])
    assert rows[0]["bytes"] == b"\x01\x02" and rows[1]["bytes"] == b"\x03"

    # from_arrow (gated on pyarrow presence)
    try:
        import pyarrow as pa
    except ImportError:
        return
    t = pa.table({"x": [1, 2, 3]})
    assert data.from_arrow(t).count() == 3


def test_deployment_response_awaitable(serve_cluster):
    """`await handle.remote(...)` works in async handlers (the reference's
    async DeploymentHandle surface)."""
    import asyncio

    @serve.deployment
    def triple(p):
        return p * 3

    serve.run(triple.bind())
    handle = serve.get_deployment_handle("triple")

    async def drive():
        return await handle.remote(14)

    assert asyncio.run(drive()) == 42
