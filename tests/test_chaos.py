"""Chaos tests: workloads survive random node kills (parity model:
reference python/ray/tests/chaos/ + NodeKillerActor suites)."""

import time

import pytest

import ray_tpu
from ray_tpu.test_utils import NodeKiller, wait_for_condition


@ray_tpu.remote
def _compute(x):
    time.sleep(0.05)
    return x * 2


def test_tasks_survive_node_churn(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    for _ in range(2):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    with NodeKiller(cluster, interval_s=0.7, respawn=True,
                    node_args={"num_cpus": 2}, max_kills=2, seed=0) as killer:
        refs = [_compute.options(max_retries=10).remote(i) for i in range(60)]
        results = ray_tpu.get(refs, timeout=120)
    assert results == [i * 2 for i in range(60)]
    assert killer.kills >= 1


def test_actor_restart_after_chaos_kill(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    n2 = cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    # Actor pinned to the doomed node; max_restarts lets GCS reschedule it.
    a = Counter.options(max_restarts=5, resources={"side": 0.1}).remote()
    assert ray_tpu.get(a.incr.remote()) == 1
    cluster.remove_node(n2)
    # Replacement node also offers the 'side' resource.
    cluster.add_node(num_cpus=2, resources={"side": 1})

    def restarted():
        try:
            return ray_tpu.get(a.incr.remote(), timeout=10) >= 1
        except ray_tpu.exceptions.RayTpuError:
            return False

    wait_for_condition(restarted, timeout=60)


def test_wait_for_condition_raises():
    with pytest.raises(TimeoutError):
        wait_for_condition(lambda: False, timeout=0.3)
