"""Chaos tests: workloads survive random node kills (parity model:
reference python/ray/tests/chaos/ + NodeKillerActor suites), and
PREEMPTED nodes — drain-with-deadline then kill, via NodePreempter —
die as non-events: zero lineage reconstructions, zero actor errors."""

import time

import pytest

import ray_tpu
from ray_tpu.test_utils import (NodeKiller, NodePreempter,
                                wait_for_condition)


@ray_tpu.remote
def _compute(x):
    time.sleep(0.05)
    return x * 2


def test_tasks_survive_node_churn(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    for _ in range(2):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()

    with NodeKiller(cluster, interval_s=0.7, respawn=True,
                    node_args={"num_cpus": 2}, max_kills=2, seed=0) as killer:
        refs = [_compute.options(max_retries=10).remote(i) for i in range(60)]
        results = ray_tpu.get(refs, timeout=120)
    assert results == [i * 2 for i in range(60)]
    assert killer.kills >= 1


def test_actor_restart_after_chaos_kill(ray_start_cluster_head):
    cluster = ray_start_cluster_head
    n2 = cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    # Actor pinned to the doomed node; max_restarts lets GCS reschedule it.
    a = Counter.options(max_restarts=5, resources={"side": 0.1}).remote()
    assert ray_tpu.get(a.incr.remote()) == 1
    cluster.remove_node(n2)
    # Replacement node also offers the 'side' resource.
    cluster.add_node(num_cpus=2, resources={"side": 1})

    def restarted():
        try:
            return ray_tpu.get(a.incr.remote(), timeout=10) >= 1
        except ray_tpu.exceptions.RayTpuError:
            return False

    wait_for_condition(restarted, timeout=60)


def test_wait_for_condition_raises():
    with pytest.raises(TimeoutError):
        wait_for_condition(lambda: False, timeout=0.3)


@pytest.mark.smoke
def test_preempted_node_is_a_non_event(ray_start_cluster_head):
    """NodeKiller's inverse: a node that is DRAINED before it dies must
    cost nothing — the workload finishes with zero lineage
    reconstructions and zero actor-death errors (drain evacuated the
    queued leases, the actor, and the primary object copies first)."""
    from ray_tpu._private.api_internal import get_core_worker

    cluster = ray_start_cluster_head
    target = cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.wait_for_nodes()
    cw = get_core_worker()

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    actor = Counter.options(max_restarts=5, name="preempt-counter",
                            resources={"side": 0.1}).remote()
    assert ray_tpu.get(actor.incr.remote(), timeout=30) == 1

    @ray_tpu.remote(resources={"side": 0.1})
    def payload():
        return bytes(bytearray(1 << 18))

    blob = payload.remote()
    ray_tpu.wait([blob], timeout=30)
    refs = [_compute.options(max_retries=10).remote(i) for i in range(30)]

    preempter = NodePreempter(cluster, deadline_s=10, reason="preemption")
    result = preempter.preempt(target)
    assert result.get("state") == "DRAINED", result
    assert preempter.preemptions == 1

    assert ray_tpu.get(refs, timeout=120) == [i * 2 for i in range(30)]
    assert len(ray_tpu.get(blob, timeout=30)) == 1 << 18
    # Actor calls never error — at worst they wait out a RESTARTING
    # window while the GCS migrates the actor off the draining node.
    assert ray_tpu.get(actor.incr.remote(), timeout=60) >= 1
    assert cw._num_reconstructions == 0


@pytest.mark.smoke
def test_preemption_deadline_fail_fast(ray_start_cluster_head):
    """Work that exceeds the drain deadline is failed fast and
    RETRYABLE: the drain completes on time and the task finishes on a
    surviving node instead of being failed infeasible."""
    cluster = ray_start_cluster_head
    target = cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"side": 0.1}, max_retries=3)
    def outlives_deadline(x):
        time.sleep(20.0)
        return x * 3

    ref = outlives_deadline.remote(5)
    time.sleep(1.5)
    preempter = NodePreempter(cluster, deadline_s=2)
    t0 = time.monotonic()
    result = preempter.preempt(target)
    assert result.get("state") == "DRAINED", result
    assert time.monotonic() - t0 < 15
    assert ray_tpu.get(ref, timeout=90) == 15


@ray_tpu.remote(resources={"side": 0.1})
def _side_compute(x):
    time.sleep(0.05)
    return x * 2


@pytest.mark.smoke
def test_stochastic_step_schedule_preemption(ray_start_cluster_head):
    """NodePreempter's seeded STEP schedule (spot-reclamation model for
    elastic training): a preemption fires once the workload's own step
    counter crosses a gap drawn from the seeded rng (~step_interval
    ± jitter), the fired step is recorded in step_schedule, and the
    drain-then-kill stays a non-event for the retried tasks."""
    cluster = ray_start_cluster_head
    for _ in range(2):
        cluster.add_node(num_cpus=2, resources={"side": 1})
    cluster.wait_for_nodes()

    done = []
    preempter = NodePreempter(
        cluster, deadline_s=5, step_interval=10, step_jitter=0.2,
        seed=1, respawn=True, max_preemptions=1,
        node_args={"num_cpus": 2, "resources": {"side": 1}},
        step_source=lambda: len(done))
    with preempter:
        for i in range(30):
            done.append(ray_tpu.get(
                _side_compute.options(max_retries=10).remote(i),
                timeout=60))
    assert done == [i * 2 for i in range(30)]
    assert preempter.preemptions == 1
    # Fired at (or a poll past) the first seeded gap ∈ [8, 12].
    assert preempter.step_schedule
    assert 8 <= preempter.step_schedule[0] <= 20


@pytest.mark.smoke
def test_partition_flap_composes_with_preemption(ray_start_cluster_head):
    """The two seeded fault injectors together (PR 10): one node's GCS
    link runs through a NetChaos proxy and flaps inside the heartbeat
    grace window while ANOTHER node is spot-preempted (drain-then-kill).
    The workload still finishes exactly, the flapped node recovers
    through the SUSPECT rung (a non-event), and the driver counts zero
    lineage reconstructions — neither fault is allowed to amplify the
    other into a false death."""
    from ray_tpu._private.api_internal import get_core_worker
    from ray_tpu.test_utils import NetChaos

    cluster = ray_start_cluster_head
    cw = get_core_worker()
    chaos = NetChaos(seed=3).start()
    try:
        gcs_host, gcs_port = cluster.gcs_address.rsplit(":", 1)
        proxy = chaos.link("flappy-gcs", gcs_host, int(gcs_port))
        flappy = cluster.add_node(num_cpus=2, resources={"side": 1},
                                  gcs_addr=proxy)
        doomed = cluster.add_node(num_cpus=2, resources={"side": 1})
        cluster.wait_for_nodes()

        refs = [_side_compute.options(max_retries=10).remote(i)
                for i in range(40)]
        # Flap (0.4s, under the 0.2s x 5 = 1s grace) then immediately
        # preempt the other 'side' node while the flapped one may still
        # be SUSPECT — its capacity must come back for the re-spilled
        # leases.
        chaos.flap("flappy-gcs", down_s=0.4)
        preempter = NodePreempter(cluster, deadline_s=10,
                                  reason="preemption")
        result = preempter.preempt(doomed)
        assert result.get("state") == "DRAINED", result

        assert ray_tpu.get(refs, timeout=120) == [i * 2 for i in range(40)]

        def row():
            return next((n for n in ray_tpu.nodes()
                         if n["node_id"] == flappy.node_id), {})

        wait_for_condition(lambda: row().get("state") == "ALIVE",
                           timeout=15)
        assert row().get("suspect_recoveries", 0) >= 1, row()
        assert preempter.preemptions == 1
        assert cw._num_reconstructions == 0
    finally:
        chaos.stop()
