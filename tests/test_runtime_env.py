"""runtime_env tests (parity model: reference python/ray/tests/
test_runtime_env*.py — env_vars, working_dir, py_modules, plugins,
job-level inheritance)."""

import os
import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu.runtime_env import (
    RuntimeEnv,
    register_plugin,
    runtime_env_context,
    unregister_plugin,
)
from ray_tpu import exceptions as exc


def test_runtime_env_validation():
    env = RuntimeEnv(env_vars={"A": "1"}, working_dir="/tmp")
    assert env["env_vars"] == {"A": "1"}
    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})
    with pytest.raises(ValueError):
        RuntimeEnv(nonexistent_plugin={"x": 1})


def test_merge_semantics():
    parent = {"env_vars": {"A": "1", "B": "2"}, "working_dir": "/p"}
    child = {"env_vars": {"B": "3"}}
    merged = RuntimeEnv.merge(parent, child)
    assert merged["env_vars"] == {"A": "1", "B": "3"}
    assert merged["working_dir"] == "/p"
    assert RuntimeEnv.merge(None, None) is None
    assert RuntimeEnv.merge(parent, None) == parent


def test_context_restores_state(tmp_path):
    marker = "RAY_TPU_TEST_ENVVAR"
    assert marker not in os.environ
    cwd = os.getcwd()
    with runtime_env_context({"env_vars": {marker: "on"},
                              "working_dir": str(tmp_path)}):
        assert os.environ[marker] == "on"
        assert os.getcwd() == str(tmp_path)
    assert marker not in os.environ
    assert os.getcwd() == cwd


def test_task_env_vars(ray_start_regular):
    @ray_tpu.remote
    def read_env(name):
        return os.environ.get(name)

    ref = read_env.options(
        runtime_env={"env_vars": {"MY_TASK_VAR": "42"}}).remote("MY_TASK_VAR")
    assert ray_tpu.get(ref) == "42"
    # Next task on the (possibly same) worker must NOT see it.
    assert ray_tpu.get(read_env.remote("MY_TASK_VAR")) is None


def test_actor_env_vars_persist(ray_start_regular):
    @ray_tpu.remote
    class EnvActor:
        def read(self, name):
            return os.environ.get(name)

    a = EnvActor.options(
        runtime_env={"env_vars": {"ACTOR_VAR": "yes"}}).remote()
    assert ray_tpu.get(a.read.remote("ACTOR_VAR")) == "yes"
    # Persists across calls (dedicated process).
    assert ray_tpu.get(a.read.remote("ACTOR_VAR")) == "yes"


def test_py_modules_import(ray_start_regular, tmp_path):
    mod_dir = tmp_path / "mymods"
    mod_dir.mkdir()
    (mod_dir / "secret_mod_77.py").write_text("VALUE = 1234\n")

    @ray_tpu.remote
    def use_module():
        import secret_mod_77

        return secret_mod_77.VALUE

    ref = use_module.options(
        runtime_env={"py_modules": [str(mod_dir)]}).remote()
    assert ray_tpu.get(ref) == 1234


def test_working_dir_missing_fails(ray_start_regular):
    @ray_tpu.remote
    def f():
        return os.getcwd()

    with pytest.raises((exc.TaskError, exc.RuntimeEnvSetupError)):
        ray_tpu.get(f.options(
            runtime_env={"working_dir": "/definitely/not/a/dir"}).remote())


def test_plugin_hook():
    calls = []
    register_plugin("my_plugin", lambda value, env: calls.append(value))
    try:
        env = RuntimeEnv(my_plugin={"knob": 1})
        with runtime_env_context(env):
            pass
        assert calls == [{"knob": 1}]
    finally:
        unregister_plugin("my_plugin")
