"""Distributed borrower-protocol tests (reference semantics:
src/ray/core_worker/reference_count.cc — nested refs serialized into
payloads keep objects alive exactly as long as some holder exists, and no
longer; python/ray/tests/test_reference_counting*.py is the spec model).
"""

import gc
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private.api_internal import ObjectRef, core_worker_or_none
from ray_tpu._private.ids import ObjectID


def _driver_owns(oid_hex: str) -> bool:
    cw = core_worker_or_none()
    return oid_hex in cw.objects


def _wait(pred, timeout=10.0, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    raise AssertionError(f"condition not reached: {msg}")


def test_return_nested_put_ref(ray_start_regular):
    """A ref created (put) inside a task survives the task: the caller
    becomes a borrower of the worker-owned object."""
    @ray_tpu.remote
    def make():
        inner = ray_tpu.put({"payload": 123})
        return [inner]

    (inner_ref,) = ray_tpu.get(make.remote())
    # Far past task completion, the worker-owned object is still alive
    # because this process is registered as a borrower.
    time.sleep(1.0)
    assert ray_tpu.get(inner_ref) == {"payload": 123}
    assert ray_tpu.get(inner_ref) == {"payload": 123}


def test_arg_nested_ref_released_after_task(ray_start_regular):
    """A driver-owned ref passed INSIDE a list arg is held only until the
    task completes; after the driver drops its handle the object frees
    (round 1 pinned it for the job lifetime)."""
    @ray_tpu.remote
    def use(box):
        return ray_tpu.get(box[0])

    ref = ray_tpu.put("nested-payload")
    oid_hex = ref.hex()
    assert ray_tpu.get(use.remote([ref])) == "nested-payload"
    assert _driver_owns(oid_hex)
    del ref
    gc.collect()
    _wait(lambda: not _driver_owns(oid_hex), msg="nested arg ref freed")


def test_borrower_outlives_owner_task(ray_start_regular):
    """An actor that stashes a borrowed ref keeps the object alive after
    the driver drops its own handle; releasing the stash frees it."""
    @ray_tpu.remote
    class Keeper:
        def keep(self, box):
            self.box = box
            return True

        def read(self):
            return ray_tpu.get(self.box[0])

        def drop(self):
            self.box = None
            gc.collect()
            return True

    k = Keeper.remote()
    ref = ray_tpu.put({"kept": 1})
    oid_hex = ref.hex()
    assert ray_tpu.get(k.keep.remote([ref]))
    del ref
    gc.collect()
    time.sleep(1.0)
    # Driver dropped its handle, but the actor's borrow keeps it alive.
    assert _driver_owns(oid_hex)
    assert ray_tpu.get(k.read.remote()) == {"kept": 1}
    assert ray_tpu.get(k.drop.remote())
    _wait(lambda: not _driver_owns(oid_hex),
          msg="object freed after borrower released")


def test_owner_death_fails_borrower_get(ray_start_regular):
    """Owner (an actor process) dies: the borrower's get on the orphaned
    ref raises (reference: OwnerDiedError semantics)."""
    @ray_tpu.remote
    class Owner:
        def make(self):
            return [ray_tpu.put("actor-owned")]

    o = Owner.remote()
    (inner,) = ray_tpu.get(o.make.remote())
    assert ray_tpu.get(inner) == "actor-owned"
    ray_tpu.kill(o)
    time.sleep(0.5)
    with pytest.raises((exc.OwnerDiedError, exc.ObjectLostError,
                        exc.RayTpuError)):
        ray_tpu.get(inner, timeout=10)


def test_forwarded_borrow_chain(ray_start_regular):
    """Driver ref forwarded task1 -> task2: the chain of holds keeps the
    object alive end to end, then releases."""
    @ray_tpu.remote
    def inner_task(box):
        return ray_tpu.get(box[0]) * 2

    @ray_tpu.remote
    def outer_task(box):
        return ray_tpu.get(inner_task.remote(box))

    ref = ray_tpu.put(21)
    oid_hex = ref.hex()
    assert ray_tpu.get(outer_task.remote([ref])) == 42
    del ref
    gc.collect()
    _wait(lambda: not _driver_owns(oid_hex), msg="forwarded ref freed")


def test_nested_ref_in_shm_stored_return(ray_start_regular):
    """Nested ref inside a LARGE (shm-stored, not inline) return value
    still resolves (container nested list travels on the wire)."""
    import numpy as np

    @ray_tpu.remote
    def make():
        inner = ray_tpu.put("big-container-inner")
        return {"blob": np.zeros(300_000), "ref": inner}

    out = ray_tpu.get(make.remote())
    assert ray_tpu.get(out["ref"]) == "big-container-inner"


def test_bare_pickle_falls_back_to_pin(ray_start_regular):
    """User-level pickle outside the runtime keeps the legacy job-lifetime
    pin (no recipient to track)."""
    import pickle

    ref = ray_tpu.put("pinned")
    blob = pickle.dumps(ref)
    oid_hex = ref.hex()
    del ref
    gc.collect()
    time.sleep(0.5)
    assert _driver_owns(oid_hex)  # pinned despite no live handle
    ref2 = pickle.loads(blob)
    assert ray_tpu.get(ref2) == "pinned"
