"""Shared fixtures (parity: reference python/ray/tests/conftest.py
ray_start_regular:410 / ray_start_cluster:491 fixture tiers).

JAX-dependent tests run against a virtual 8-device CPU mesh — the "fake
backend" for SPMD logic (SURVEY.md §4 rebuild guidance).
"""

import os

# Must be set before jax import (any test importing jax sees 8 CPU devices).
# Hard overrides: the machine env pins JAX_PLATFORMS to the real TPU tunnel,
# but tests always run on the virtual CPU mesh.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Spawned ray_tpu worker processes honor this (see _private/worker.py main).
os.environ["RAY_TPU_JAX_PLATFORM"] = "cpu"

import pytest  # noqa: E402

try:
    import jax

    # The machine image force-registers the 'axon' TPU platform via config
    # (env JAX_PLATFORMS is ignored); override back to the CPU fake backend.
    jax.config.update("jax_platforms", "cpu")
    # Deterministic, tight-tolerance numerics for kernel-correctness tests
    # on the CPU fake backend (default CPU matmul precision is loose).
    jax.config.update("jax_default_matmul_precision", "highest")
except ImportError:
    pass

import ray_tpu  # noqa: E402
from ray_tpu._private.config import Config  # noqa: E402


def _fast_config() -> Config:
    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.num_heartbeats_timeout = 5
    cfg.worker_lease_timeout_s = 10.0
    cfg.object_store_memory = 64 * 1024 * 1024
    return cfg


@pytest.fixture
def ray_start_regular():
    """Single-node cluster, 4 CPUs."""
    ray_tpu.init(num_cpus=4, config=_fast_config())
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """Bare Cluster factory; test adds nodes itself."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=False, config=_fast_config())
    yield cluster
    cluster.shutdown()


@pytest.fixture
def ray_start_cluster_head():
    """Cluster with a 2-CPU head node, connected."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 2}, config=_fast_config())
    yield cluster
    cluster.shutdown()


# ---- teardown-hygiene enforcement (VERDICT r3 weak #5) ----
# "Task was destroyed but it is pending!" is emitted through the asyncio
# logger from Task.__del__, not as a warning, so filterwarnings cannot
# catch it. This handler turns any such record produced while a test
# (including its fixture teardown) runs into a test failure.

import logging as _logging


class _AsyncioNoiseCollector(_logging.Handler):
    def __init__(self):
        super().__init__(level=_logging.ERROR)
        self.records: list[str] = []

    def emit(self, record):
        msg = record.getMessage()
        if "Task was destroyed but it is pending" in msg \
                or "Future exception was never retrieved" in msg:
            self.records.append(msg)


_asyncio_noise = _AsyncioNoiseCollector()
_logging.getLogger("asyncio").addHandler(_asyncio_noise)


@pytest.fixture(autouse=True)
def _no_asyncio_teardown_noise(request):
    import gc

    start = len(_asyncio_noise.records)
    yield
    # Task.__del__ fires on gc; collect so a leak from THIS test is
    # attributed to it, not a later one.
    gc.collect()
    new = _asyncio_noise.records[start:]
    assert not new, (
        f"asyncio teardown noise during {request.node.nodeid}: {new[:3]}")
