"""Wide-cluster chaos certification (issue 20) tier-1 tests.

Covers the pieces the 256-node release gate (`bench.py --scale-chaos`)
leans on, at unit/e2e scale:

- pubsub fanout backpressure: a stalled subscriber no longer
  head-of-line blocks delivery to healthy peers (the Python fallback
  path's serial-await regression), latest-wins coalescing on state
  channels, bounded drop-counted queues, counters on GetClusterStatus;
- streaming GCS recovery: a restarted GCS answers within the bounded
  priority prefix while the rest of the persisted state streams in the
  background, `recovering` flips off when the stream drains;
- per-job fair-share lease scheduling: round-robin across job queues
  with the starvation counter;
- scheduler behavior at width: 128+ fake-node SPREAD/PACK placement and
  spillback-chain distribution against the simulated cluster view — no
  live sockets.
"""

import asyncio
import collections
import time
import types

import pytest

from ray_tpu._private import gcs as gcs_mod
from ray_tpu._private import rpc
from ray_tpu._private.common import NodeInfo, normalize_resources
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet
from ray_tpu.test_utils import NetChaos


def run(coro):
    return asyncio.run(coro)


async def _wait_for(predicate, timeout=10.0, what="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        if predicate():
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(0.02)


# ---------------------------------------------------------------------------
# Pubsub fanout backpressure
# ---------------------------------------------------------------------------


def _force_python_fanout(monkeypatch):
    """Run the GCS on the asyncio transport with the Python pubsub
    path — the fallback whose serial-await loop had the head-of-line
    blocking bug."""
    monkeypatch.setenv("RAY_TPU_FASTPATH", "0")
    monkeypatch.setenv("RAY_TPU_NATIVE_GCS_SERVICE", "0")
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "0")


def test_stalled_subscriber_does_not_block_peers(monkeypatch):
    """Regression (issue 20 satellite): one dead-slow NetChaos-proxied
    subscriber must not delay delivery to healthy subscribers on the
    same channel. The old publish() awaited each subscriber socket in
    turn, so the stalled conn's full TCP window stalled everyone."""
    _force_python_fanout(monkeypatch)
    # Small bound so the stalled subscriber's queue overflow (counted
    # drops) is observable without megabytes of backlog.
    monkeypatch.setattr(gcs_mod, "_FANOUT_DEPTH", 8)

    async def main():
        gcs = GcsServer()
        host, port = await gcs.start()
        chaos = NetChaos(seed=7).start()
        try:
            ch, cp = chaos.link("sub", host, port)
            stalled_got = []
            healthy_got = []

            def on_pub(got):
                def h(conn, payload):
                    got.append(payload["message"])
                return h

            stalled = await rpc.connect_session(
                ch, cp, handlers={"Publish": on_pub(stalled_got)},
                name="stalled-sub")
            await stalled.call("Subscribe", {"channels": ["LOGS"]})
            healthy = await rpc.connect_session(
                host, port, handlers={"Publish": on_pub(healthy_got)},
                name="healthy-sub")
            await healthy.call("Subscribe", {"channels": ["LOGS"]})

            # Stall the proxied link: a huge per-frame delay stops the
            # proxy reading, so the GCS-side socket backs up for real
            # (partition() would read-and-discard, never stalling the
            # sender).
            chaos.set_faults("sub", delay_s=60.0)

            driver = await rpc.connect_session(host, port, name="driver")
            n = 40
            pad = "x" * (256 << 10)
            t0 = time.monotonic()
            for i in range(n):
                await driver.call(
                    "Publish",
                    {"channel": "LOGS", "message": {"i": i, "pad": pad}})
            publish_s = time.monotonic() - t0
            # publish() is enqueue-and-return: pushing 10MB at a wedged
            # subscriber must not slow the publisher itself.
            assert publish_s < 10.0, f"publish path stalled: {publish_s:.1f}s"

            await _wait_for(lambda: len(healthy_got) == n, timeout=10.0,
                            what="healthy subscriber delivery")
            assert [m["i"] for m in healthy_got] == list(range(n))
            # The stalled subscriber got (at most) what fit down the
            # wedged pipe before it filled.
            assert len(stalled_got) < n

            st = await driver.call("GetClusterStatus", {})
            fo = st["fanout"]
            assert fo["sent"] >= n           # healthy deliveries
            assert fo["enqueued"] >= 2 * n   # both subscribers enqueued
            assert fo["dropped"] > 0         # stalled queue overflowed
            assert fo["max_depth"] > 0
            assert "recovering" in st and st["recovering"] is False

            await driver.close()
            await healthy.close()
            await stalled.close()
        finally:
            chaos.stop()
            await gcs.stop()

    run(main())


def test_fanout_coalesces_state_channels(monkeypatch):
    """NODE/ACTOR channel queues are latest-wins per entity: a backed-up
    subscriber sees the newest state, not a replay of every edge."""
    _force_python_fanout(monkeypatch)

    async def main():
        stats = {"enqueued": 0, "sent": 0, "coalesced": 0, "dropped": 0,
                 "batches": 0, "max_depth": 0, "native_batches": 0}
        gate = asyncio.Event()
        sent = []

        class _Conn:
            closed = False

            async def notify(self, method, payload):
                await gate.wait()
                sent.append(payload["message"])

        pump = gcs_mod._SubscriberPump(_Conn(), stats)
        # First push wakes the sender, which parks on the gate; the
        # next four supersede each other latest-wins.
        pump.push("NODE", {"event": "alive", "node": {"node_id": "n1"}})
        await asyncio.sleep(0.05)
        for ev in ("suspect", "alive", "suspect", "dead"):
            pump.push("NODE", {"event": ev, "node_id": "n1"})
        pump.push("ACTOR", {"actor_id": "a1", "state": "PENDING_CREATION"})
        pump.push("ACTOR", {"actor_id": "a1", "state": "ALIVE"})
        gate.set()
        await _wait_for(lambda: stats["sent"] == 3, what="pump drain")
        assert stats["coalesced"] == 4  # 3 NODE + 1 ACTOR superseded
        # Latest state won for both entities.
        node_msgs = [m for m in sent if "event" in m]
        assert node_msgs[-1]["event"] == "dead"
        actor_msgs = [m for m in sent if "state" in m and "actor_id" in m]
        assert actor_msgs == [{"actor_id": "a1", "state": "ALIVE"}]
        pump.close()

    run(main())


# ---------------------------------------------------------------------------
# Streaming GCS recovery
# ---------------------------------------------------------------------------


def _settled_actor(aid, job_id="job-a"):
    return {
        "actor_id": aid, "state": gcs_mod.ACTOR_DEAD, "address": None,
        "node_id": None, "class_name": "Settled", "name": "",
        "namespace": "default", "job_id": job_id, "restarts": 0,
        "max_restarts": 0, "death_cause": "exit", "spec": b"",
        "dead_worker_ids": set(),
    }


def test_streaming_recovery_prefix_then_stream(monkeypatch, tmp_path):
    """A restarted GCS answers from the bounded priority prefix (all
    nodes, pending creations) while settled actors / jobs / PGs stream
    in behind it; reads that race the stream fault their rows in, and
    `recovering` flips off when the backlog drains."""
    _force_python_fanout(monkeypatch)
    path = str(tmp_path / "gcs_state")
    node_id = "bb" * 16

    async def main():
        # --- phase 1: build a cluster worth recovering -----------------
        gcs = GcsServer(persistence_path=path)
        host, port = await gcs.start()
        node = await rpc.connect_session(host, port, name="node")
        r = await node.call("RegisterNode", {
            "host": "127.0.0.1", "node_id": node_id, "raylet_port": 47011,
            "total_resources": {"CPU": 4.0}})
        assert r["ok"]
        driver = await rpc.connect_session(host, port, name="driver")
        # Unsatisfiable resources: the creation stays PENDING, which is
        # exactly the in-flight shape the recovery prefix must re-kick.
        r = await driver.call("RegisterActor", {
            "actor_id": "pend-1", "spec": b"\x01s", "max_restarts": 0,
            "class_name": "Pending", "job_id": "job-a",
            "resources": {"CPU": 64.0}})
        assert r["ok"]
        # The workload-proportional bulk that must NOT gate answering.
        for i in range(40):
            aid = f"done-{i}"
            gcs.actors[aid] = _settled_actor(aid)
        gcs.jobs["job-z"] = {"job_id": "job-z", "status": "RUNNING",
                             "start_time": 1.0, "entrypoint": ""}
        gcs.named_actors[("default", "bob")] = "done-0"
        gcs.placement_groups["pg-1"] = {
            "pg_id": "pg-1", "name": "", "strategy": "PACK",
            "bundles": [{"resources": {"CPU": 1.0}, "node_id": None,
                         "available": {}}],
            "state": gcs_mod.PG_CREATED, "creator": "", "job_id": "job-z"}
        gcs.mark_dirty()
        await driver.close()
        await node.close()
        await gcs.stop()  # final flush + compact

        # --- phase 2: restart with the stream held at the gate ---------
        release = asyncio.Event()
        orig_stream = GcsServer._recovery_stream

        async def gated_stream(self):
            await release.wait()
            await orig_stream(self)

        monkeypatch.setattr(GcsServer, "_recovery_stream", gated_stream)
        gcs2 = GcsServer(persistence_path=path)
        host2, port2 = await gcs2.start()
        try:
            assert gcs2.recovering is True
            # Prefix: the full node table (placement needs width), alive
            # only on re-registration; and the in-flight creation.
            assert node_id in gcs2.nodes
            assert gcs2.nodes[node_id].alive is False
            assert "pend-1" in gcs2.actors
            assert gcs2.actors["pend-1"]["state"] == gcs_mod.ACTOR_PENDING
            # Bulk is still parked on the backlog.
            assert "done-0" not in gcs2.actors

            d2 = await rpc.connect_session(host2, port2, name="driver2")
            st = await d2.call("GetClusterStatus", {})
            assert st["recovering"] is True
            assert st["recovery"]["backlog_rows"] > 0
            assert st["recovery"]["prefix_rows"] >= 2

            # A read racing the stream faults its row in synchronously.
            info = await d2.call("GetActorInfo", {"actor_id": "done-7"})
            assert info["found"] and info["state"] == gcs_mod.ACTOR_DEAD
            assert "done-7" in gcs2.actors
            jobs = await d2.call("ListJobs", {})
            assert any(j["job_id"] == "job-z" for j in jobs["jobs"])

            # Open the gate: the stream drains and the flag flips.
            release.set()
            await _wait_for(lambda: not gcs2.recovering,
                            what="recovery stream drain")
            assert all(f"done-{i}" in gcs2.actors for i in range(40))
            assert ("default", "bob") in gcs2.named_actors
            assert "pg-1" in gcs2.placement_groups
            assert gcs2._recovery_stats["streamed_rows"] >= 40
            st = await d2.call("GetClusterStatus", {})
            assert st["recovering"] is False
            assert st["recovery"]["backlog_rows"] == 0
            await d2.close()
        finally:
            await gcs2.stop()

    run(main())


# ---------------------------------------------------------------------------
# Per-job fair-share lease scheduling
# ---------------------------------------------------------------------------


class _FakeLeaseRaylet:
    """The minimal surface _pump_pending_leases touches, with the real
    Raylet pump/spillback logic bound onto it — exercises the queue
    policy without workers, rcore, or sockets."""

    def __init__(self, capacity=0, peers=None):
        self.node_id = "self-node"
        self.pending_leases = collections.deque()
        self._lease_rr_last = ""
        self._lease_grants_by_job = {}
        self._lease_starvation = 0
        self._starvation_threshold_s = 5.0
        self._native_sched = None
        self.cluster_view = peers or {}
        self.available = {}
        self.capacity = capacity
        self.grant_order = []
        self._pump_pending_leases = types.MethodType(
            Raylet._pump_pending_leases, self)
        self._pick_spillback = types.MethodType(Raylet._pick_spillback, self)

    def _acquire(self, resources, pg_id, bundle_index):
        if self.capacity <= 0:
            return None
        self.capacity -= 1
        return f"lease-{self.capacity}"

    async def _grant_lease(self, lease_id, resources, pg_id, bundle_index,
                           received_at=None):
        return {"granted": True, "lease_id": lease_id}


def _queue_lease(r, job_id, received_at=None):
    fut = asyncio.get_event_loop().create_future()
    r.pending_leases.append(
        ({"CPU": 1.0}, "", -1, fut, False, received_at or time.time(),
         job_id))
    return fut


def test_fair_share_round_robin():
    """Under contention the pump interleaves per-job lanes: a tenant
    with 2 queued leases behind a peer's 8-lease burst gets half of the
    4 freed slots, not zero (strict FIFO would serve burst×4)."""

    async def main():
        r = _FakeLeaseRaylet(capacity=4)
        burst = [_queue_lease(r, "job-burst") for _ in range(8)]
        latency = [_queue_lease(r, "job-latency") for _ in range(2)]
        r._pump_pending_leases()
        assert r._lease_grants_by_job == {"job-burst": 2, "job-latency": 2}
        await asyncio.sleep(0.05)  # let the grant tasks resolve futures
        assert all(f.done() for f in latency)
        assert sum(1 for f in burst if f.done()) == 2
        # Per-job FIFO within a lane: the burst grants are its oldest.
        assert burst[0].done() and burst[1].done()
        assert r._lease_starvation == 0

        # The rotation cursor persists: next pass starts after the last
        # job served, so freed slots keep alternating.
        r.capacity = 2
        r._pump_pending_leases()
        assert r._lease_grants_by_job["job-burst"] == 4
        assert sum(r._lease_grants_by_job.values()) == 6

    run(main())


def test_fair_share_starvation_counter():
    """A grant that sat queued past the starvation threshold is counted
    — the release gate's 'starvation counter 0' invariant reads this."""

    async def main():
        r = _FakeLeaseRaylet(capacity=1)
        _queue_lease(r, "job-old", received_at=time.time() - 30.0)
        r._pump_pending_leases()
        assert r._lease_starvation == 1
        await asyncio.sleep(0.02)

    run(main())


# ---------------------------------------------------------------------------
# Scheduler behavior at width (128+ fake nodes, no sockets)
# ---------------------------------------------------------------------------


def _wide_gcs(n_nodes, cpus=4.0, native=False):
    g = GcsServer()
    if not native:
        if g.native_sched is not None:
            g.native_sched.close()
        g.native_sched = None
    for i in range(n_nodes):
        nid = f"node-{i:04d}"
        info = NodeInfo(node_id=nid, host="127.0.0.1", raylet_port=40000 + i,
                        total_resources={"CPU": cpus},
                        available_resources={"CPU": cpus})
        g.nodes[nid] = info
        if g.native_sched is not None:
            g.native_sched.update_node(nid, total=info.total_resources,
                                       available=info.available_resources,
                                       alive=True)
    return g


def _place(g, resources, strategy=None):
    """_pick_node_for + the same transient debit _schedule_actor does,
    so successive picks see the evolving load picture."""
    from ray_tpu._private.common import subtract_resources

    nid = g._pick_node_for(resources, strategy)
    if nid is None:
        return None
    subtract_resources(g.nodes[nid].available_resources, resources)
    if g.native_sched is not None:
        g.native_sched.debit_node(nid, resources)
    return nid


def _native_param():
    try:
        from ray_tpu._private import native_scheduler
        natives = [True] if native_scheduler.available() else []
    except Exception:
        natives = []
    return [False] + natives


@pytest.mark.parametrize("native", _native_param())
def test_width_spread_distribution(native):
    """256 SPREAD placements over 128 nodes land ~2 per node: every
    node is used and no node takes more than double its fair share."""
    g = _wide_gcs(128, native=native)
    counts = collections.Counter()
    for _ in range(256):
        nid = _place(g, {"CPU": 1.0}, strategy=("spread",))
        assert nid is not None
        counts[nid] += 1
    assert len(counts) == 128
    assert max(counts.values()) <= 4


@pytest.mark.parametrize("native", _native_param())
def test_width_pack_concentrates(native):
    """PACK placements at width bin-pack instead of spraying: 8 CPU-1
    placements across 128 empty CPU-4 nodes fill whole nodes first."""
    g = _wide_gcs(128, native=native)
    counts = collections.Counter()
    for _ in range(8):
        nid = _place(g, {"CPU": 1.0})
        assert nid is not None
        counts[nid] += 1
    assert len(counts) <= 3  # 2 full nodes (+1 for a tie-break seam)
    assert max(counts.values()) == 4


@pytest.mark.parametrize("native", _native_param())
def test_width_strict_spread_pg(native):
    """A 128-bundle STRICT_SPREAD group over 128 nodes places every
    bundle on a distinct node."""
    g = _wide_gcs(128, native=native)
    pg = {"strategy": "STRICT_SPREAD",
          "bundles": [{"resources": {"CPU": 1.0}, "node_id": None,
                       "available": {}} for _ in range(128)]}
    placement = g._pack_bundles(pg)
    assert placement is not None
    nodes_used = {nid for _idx, nid in placement}
    assert len(nodes_used) == 128


@pytest.mark.parametrize("native", _native_param())
def test_width_spread_pg_balance(native):
    """SPREAD bundles beyond cluster width wrap evenly: 256 bundles on
    128 CPU-4 nodes put at most the capacity-forced 4 on any node and
    touch the whole fleet."""
    g = _wide_gcs(128, native=native)
    pg = {"strategy": "SPREAD",
          "bundles": [{"resources": {"CPU": 1.0}, "node_id": None,
                       "available": {}} for _ in range(256)]}
    placement = g._pack_bundles(pg)
    assert placement is not None
    counts = collections.Counter(nid for _idx, nid in placement)
    assert len(counts) >= 64
    assert max(counts.values()) <= 4


def test_width_spillback_fans_out():
    """A saturated raylet re-scheduling 64 queued spillable leases in
    one pump pass fans them out across peers via the debited view —
    each peer absorbs only what fits, nothing herds onto one 'best'
    node (the stale-view thundering herd)."""

    async def main():
        peers = {
            f"peer-{i:03d}": {
                "host": "127.0.0.1", "raylet_port": 41000 + i,
                "state": "ALIVE", "total_resources": {"CPU": 4.0},
                "available_resources": {"CPU": 4.0},
            } for i in range(32)}
        r = _FakeLeaseRaylet(capacity=0, peers=peers)
        futs = []
        for i in range(64):
            fut = asyncio.get_event_loop().create_future()
            r.pending_leases.append(
                ({"CPU": 1.0}, "", -1, fut, True, time.time(),
                 f"job-{i % 4}"))
            futs.append(fut)
        r._pump_pending_leases()
        targets = collections.Counter()
        for fut in futs:
            assert fut.done()
            spill = fut.result()["spillback"]
            targets[spill["node_id"]] += 1
        assert sum(targets.values()) == 64
        assert max(targets.values()) <= 4   # never past a peer's capacity
        assert len(targets) == 16           # 64 leases / 4 CPU per peer

    run(main())
