"""Optimizer rule catalog + Mongo connector tests.

Parity model: reference python/ray/data/tests/test_execution_optimizer.py
(rule-level assertions on the optimized plan + end-to-end result checks)
and test_mongo.py (connector against a stand-in for the server — the
image ships neither mongod nor pymongo, so a file-backed fake client
exercises the same aggregate/insert_many surface)."""

import functools
import json
import os

import pytest

import ray_tpu  # noqa: F401  (fixtures init the cluster)
from ray_tpu import data as rdata
from ray_tpu.data.optimizer import (
    DropRedundantRandomize,
    FuseMapStages,
    LogicalPlan,
    MergeProjections,
    ReorderRandomizeBlocks,
    Rule,
    optimize,
    register_optimizer_rule,
)
from ray_tpu.data.optimizer import _user_rules


# ---- plan-level rule assertions (no cluster needed) ----------------------


def _plan(ds):
    return LogicalPlan(list(ds._source), list(ds._stages))


def test_fuse_map_stages_collapses_chain():
    ds = rdata.range(10).map(lambda v: v + 1) \
        .map(lambda v: v * 2) \
        .map(lambda v: v - 3)
    out = FuseMapStages().apply(_plan(ds))
    assert len(out.stages) == 1
    assert out.stages[0].name == "map->map->map"


def test_fusion_stops_at_barriers():
    ds = rdata.range(10).map(lambda r: r).random_shuffle() \
        .map(lambda r: r).map(lambda r: r)
    out = FuseMapStages().apply(_plan(ds))
    names = [s.name for s in out.stages]
    assert names == ["map", "random_shuffle", "map->map"]


def test_merge_projections_keeps_narrower():
    ds = rdata.range(5).select_columns(["id"]).select_columns(["id"])
    out = MergeProjections().apply(_plan(ds))
    assert len(out.stages) == 1
    assert out.stages[0].pushdown_projection == ("id",) or \
        list(out.stages[0].pushdown_projection) == ["id"]


def test_merge_projections_preserves_error_contract():
    # select(a) then select(b) with b not in a must KEEP both stages so
    # the runtime KeyError still fires.
    ds = rdata.from_items([{"a": 1, "b": 2}]) \
        .select_columns(["a"]).select_columns(["b"])
    out = MergeProjections().apply(_plan(ds))
    assert len(out.stages) == 2


def test_randomize_dropped_under_later_shuffle():
    ds = rdata.range(8).randomize_block_order().random_shuffle()
    out = DropRedundantRandomize().apply(_plan(ds))
    assert [s.name for s in out.stages] == ["random_shuffle"]


def test_randomize_bubbled_to_source():
    # The reorder barrier moves toward the SOURCE (refs are still lazy
    # there — permuting them is free) and un-splits the map chain.
    ds = rdata.range(8).map(lambda r: r) \
        .randomize_block_order().map(lambda r: r)
    out = ReorderRandomizeBlocks().apply(_plan(ds))
    assert [s.name for s in out.stages] == \
        ["randomize_block_order", "map", "map"]
    # ...which lets the full catalog fuse the now-adjacent maps:
    full = optimize(_plan(ds))
    assert [s.name for s in full.stages] == \
        ["randomize_block_order", "map->map"]


def test_explain_shows_optimization():
    ds = rdata.range(8).map(lambda r: r).map(lambda r: r)
    text = ds.explain()
    assert "logical" in text and "map -> map" in text
    assert "map->map" in text  # fused form on the optimized line


def test_user_rule_registration():
    class DropEverySecondMap(Rule):
        name = "drop-second"

        def apply(self, plan):
            return LogicalPlan(plan.source, plan.stages[:1])

    register_optimizer_rule(DropEverySecondMap())
    try:
        ds = rdata.range(4).map(lambda r: r).map(lambda r: r)
        out = optimize(_plan(ds))
        assert len(out.stages) == 1
    finally:
        _user_rules.pop()


# ---- end-to-end semantics under the optimizer ----------------------------


def test_fused_pipeline_end_to_end(ray_start_regular):
    ds = rdata.range(20, override_num_blocks=4) \
        .map(lambda v: v + 1) \
        .map(lambda v: v * 2) \
        .filter(lambda v: v % 4 == 0)
    got = sorted(ds.iter_rows())
    want = sorted(v for v in ((i + 1) * 2 for i in range(20)) if v % 4 == 0)
    assert got == want


def test_randomize_block_order_end_to_end(ray_start_regular):
    ds = rdata.range(40, override_num_blocks=8)
    plain = list(ds.iter_rows())
    shuffled = list(ds.randomize_block_order(seed=7).iter_rows())
    assert sorted(shuffled) == sorted(plain)
    assert shuffled != plain  # 8! orderings; seed 7 must move something
    # Within a block, row order is untouched (order-only barrier).
    again = list(ds.randomize_block_order(seed=7).iter_rows())
    assert again == shuffled  # seeded determinism


# ---- Mongo connector ------------------------------------------------------


class FakeMongoClient:
    """File-backed stand-in for pymongo.MongoClient: one JSONL file per
    (database, collection) under a shared root, so driver and remote
    read/write tasks observe the same state."""

    def __init__(self, root):
        self.root = root

    def __getitem__(self, database):
        return _FakeDB(self.root, database)


class _FakeDB:
    def __init__(self, root, database):
        self.root, self.database = root, database

    def __getitem__(self, collection):
        return _FakeCollection(os.path.join(
            self.root, f"{self.database}.{collection}.jsonl"))


class _FakeCollection:
    def __init__(self, path):
        self.path = path

    def _load(self):
        try:
            with open(self.path) as f:
                return [json.loads(line) for line in f]
        except FileNotFoundError:
            return []

    def count_documents(self, flt):
        return len(self._load())

    def insert_many(self, docs):
        with open(self.path, "a") as f:
            for i, d in enumerate(docs):
                d = dict(d)
                d.setdefault("_id", f"{os.getpid()}-{i}-{len(docs)}")
                f.write(json.dumps(d) + "\n")

    def aggregate(self, stages):
        docs = self._load()
        for st in stages:
            if "$sort" in st:
                for key, direction in reversed(list(st["$sort"].items())):
                    docs.sort(key=lambda d: d.get(key),
                              reverse=direction < 0)
            elif "$match" in st:
                docs = [d for d in docs
                        if all(d.get(k) == v
                               for k, v in st["$match"].items())]
            elif "$skip" in st:
                docs = docs[st["$skip"]:]
            elif "$limit" in st:
                docs = docs[:st["$limit"]]
            elif "$count" in st:
                docs = [{st["$count"]: len(docs)}]
            else:
                raise ValueError(f"fake mongo: unsupported stage {st}")
        return iter(docs)


def _seed_collection(root, database, collection, n):
    coll = FakeMongoClient(root)[database][collection]
    coll.insert_many([{"_id": f"{i:04d}", "x": i, "parity": i % 2}
                      for i in range(n)])


def test_read_mongo_single_block(ray_start_regular, tmp_path):
    root = str(tmp_path)
    _seed_collection(root, "db", "items", 10)
    ds = rdata.read_mongo(
        "mongodb://unused", "db", "items",
        client_factory=functools.partial(FakeMongoClient, root))
    rows = sorted(r["x"] for r in ds.iter_rows())
    assert rows == list(range(10))


def test_read_mongo_sharded_and_pipeline(ray_start_regular, tmp_path):
    root = str(tmp_path)
    _seed_collection(root, "db", "items", 23)
    factory = functools.partial(FakeMongoClient, root)
    ds = rdata.read_mongo("mongodb://unused", "db", "items",
                          override_num_blocks=4, client_factory=factory)
    assert len(ds._source) == 4
    rows = sorted(r["x"] for r in ds.iter_rows())
    assert rows == list(range(23))  # shard boundaries cover exactly once

    filtered = rdata.read_mongo(
        "mongodb://unused", "db", "items",
        pipeline=[{"$match": {"parity": 1}}],
        override_num_blocks=3, client_factory=factory)
    got = sorted(r["x"] for r in filtered.iter_rows())
    assert got == [i for i in range(23) if i % 2 == 1]


def test_write_mongo_roundtrip(ray_start_regular, tmp_path):
    root = str(tmp_path)
    factory = functools.partial(FakeMongoClient, root)
    ds = rdata.from_items([{"x": i} for i in range(12)])
    written = ds.write_mongo("mongodb://unused", "db", "out",
                             client_factory=factory)
    assert written == 12
    back = rdata.read_mongo("mongodb://unused", "db", "out",
                            client_factory=factory)
    assert sorted(r["x"] for r in back.iter_rows()) == list(range(12))


def test_read_mongo_empty_collection_sharded(ray_start_regular, tmp_path):
    # Sharding an empty collection must not emit {$limit: 0} read tasks
    # (real MongoDB rejects a zero limit) — it falls back to one
    # unsharded read returning nothing.
    factory = functools.partial(FakeMongoClient, str(tmp_path))
    ds = rdata.read_mongo("mongodb://unused", "db", "nothing",
                          override_num_blocks=4, client_factory=factory)
    assert len(ds._source) == 1
    assert list(ds.iter_rows()) == []


def test_read_mongo_order_destroying_pipeline_not_sharded(tmp_path):
    # $group output order is undefined, so N independent skip/limit
    # slices would duplicate/drop rows — the connector must refuse to
    # shard and read in one task instead.
    factory = functools.partial(FakeMongoClient, str(tmp_path))
    ds = rdata.read_mongo(
        "mongodb://unused", "db", "items",
        pipeline=[{"$group": {"_id": "$parity"}}],
        override_num_blocks=4, client_factory=factory)
    assert len(ds._source) == 1


def test_read_mongo_without_driver_raises():
    # Sharded reads hit the client on the driver immediately (count for
    # shard planning) — no pymongo in the image, no factory: clear error.
    with pytest.raises(ImportError, match="pymongo"):
        rdata.read_mongo("mongodb://localhost", "db", "c",
                         override_num_blocks=2)
