"""Dask-on-ray scheduler over hand-built dask graphs (the graph protocol
is plain data, so the scheduler is fully testable without dask — which
is not in this image; reference: python/ray/util/dask/scheduler.py and
its test suite's graph semantics)."""

import operator

import numpy as np

import ray_tpu
from ray_tpu.util.dask import ray_dask_get


def test_simple_graph(ray_start_regular):
    dsk = {
        "x": 1,
        "y": 2,
        "z": (operator.add, "x", "y"),
        "w": (sum, ["x", "y", "z"]),
    }
    assert ray_dask_get(dsk, "z") == 3
    assert ray_dask_get(dsk, "w") == 6
    # Nested key lists mirror the output structure (dask get contract).
    assert ray_dask_get(dsk, [["x", "z"], "w"]) == [[1, 3], 6]


def test_nested_tasks_and_literals(ray_start_regular):
    def scale(a, factor):
        return [v * factor for v in a]

    dsk = {
        "data": [1, 2, 3],
        # task nested INSIDE a task's argument list
        "out": (scale, "data", (operator.mul, 2, 3)),
    }
    assert ray_dask_get(dsk, "out") == [6, 12, 18]


def test_fan_out_fan_in_numpy(ray_start_regular):
    """Diamond graph: one source, parallel middle tasks (cluster tasks),
    one reducer — intermediates stay in the object store."""
    dsk = {"src": np.arange(1000.0)}
    for i in range(4):
        dsk[f"part{i}"] = (lambda a, k=i: float(a[k::4].sum()), "src")
    dsk["total"] = (lambda *parts: sum(parts),
                    *[f"part{i}" for i in range(4)])
    assert ray_dask_get(dsk, "total") == float(np.arange(1000.0).sum())


def test_key_alias(ray_start_regular):
    dsk = {"a": 41, "b": "a", "c": (operator.add, "b", 1)}
    assert ray_dask_get(dsk, "c") == 42


def test_shared_dep_computed_once(ray_start_regular):
    calls = []

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def hit(self):
            self.n += 1
            return self.n

        def total(self):
            return self.n

    counter = Counter.remote()

    def expensive(c):
        import ray_tpu as rt

        rt.get(c.hit.remote())
        return 7

    dsk = {
        "c": counter,
        "shared": (expensive, "c"),
        "u1": (operator.add, "shared", 1),
        "u2": (operator.add, "shared", 2),
        "out": (operator.add, "u1", "u2"),
    }
    assert ray_dask_get(dsk, "out") == 17
    # The shared node ran ONCE (memoized ref), not once per consumer.
    assert ray_tpu.get(counter.total.remote()) == 1
