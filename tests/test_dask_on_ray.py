"""Dask-on-ray scheduler over hand-built dask graphs (the graph protocol
is plain data, so the scheduler is fully testable without dask — which
is not in this image; reference: python/ray/util/dask/scheduler.py and
its test suite's graph semantics)."""

import operator

import numpy as np

import ray_tpu
from ray_tpu.util.dask import ray_dask_get


def test_simple_graph(ray_start_regular):
    dsk = {
        "x": 1,
        "y": 2,
        "z": (operator.add, "x", "y"),
        "w": (sum, ["x", "y", "z"]),
    }
    assert ray_dask_get(dsk, "z") == 3
    assert ray_dask_get(dsk, "w") == 6
    # Nested key lists mirror the output structure (dask get contract).
    assert ray_dask_get(dsk, [["x", "z"], "w"]) == [[1, 3], 6]


def test_nested_tasks_and_literals(ray_start_regular):
    def scale(a, factor):
        return [v * factor for v in a]

    dsk = {
        "data": [1, 2, 3],
        # task nested INSIDE a task's argument list
        "out": (scale, "data", (operator.mul, 2, 3)),
    }
    assert ray_dask_get(dsk, "out") == [6, 12, 18]


def test_fan_out_fan_in_numpy(ray_start_regular):
    """Diamond graph: one source, parallel middle tasks (cluster tasks),
    one reducer — intermediates stay in the object store."""
    dsk = {"src": np.arange(1000.0)}
    for i in range(4):
        dsk[f"part{i}"] = (lambda a, k=i: float(a[k::4].sum()), "src")
    dsk["total"] = (lambda *parts: sum(parts),
                    *[f"part{i}" for i in range(4)])
    assert ray_dask_get(dsk, "total") == float(np.arange(1000.0).sum())


def test_key_alias(ray_start_regular):
    dsk = {"a": 41, "b": "a", "c": (operator.add, "b", 1)}
    assert ray_dask_get(dsk, "c") == 42


def test_shared_dep_computed_once(ray_start_regular):
    calls = []

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def hit(self):
            self.n += 1
            return self.n

        def total(self):
            return self.n

    counter = Counter.remote()

    def expensive(c):
        import ray_tpu as rt

        rt.get(c.hit.remote())
        return 7

    dsk = {
        "c": counter,
        "shared": (expensive, "c"),
        "u1": (operator.add, "shared", 1),
        "u2": (operator.add, "shared", 2),
        "out": (operator.add, "u1", "u2"),
    }
    assert ray_dask_get(dsk, "out") == 17
    # The shared node ran ONCE (memoized ref), not once per consumer.
    assert ray_tpu.get(counter.total.remote()) == 1


def test_tuple_keys_collection_style(ray_start_regular):
    """Tuple keys are THE key format of dask.array/dataframe graphs —
    they are key references, never literal tuples (review-reproduced
    failure)."""
    dsk = {
        ("x", 0): 5,
        ("x", 1): 7,
        "sum": (operator.add, ("x", 0), ("x", 1)),
        "nested": (sum, [("x", 0), ("x", 1), "sum"]),
    }
    assert ray_dask_get(dsk, "sum") == 12
    assert ray_dask_get(dsk, "nested") == 24
    assert ray_dask_get(dsk, [("x", 0), "sum"]) == [5, 12]


def test_list_of_keys_value(ray_start_regular):
    """A bare list-of-keys VALUE substitutes its keys (dask
    _execute_task semantics; the common final aggregation node)."""
    dsk = {"x": 1, "y": 2, "w": ["x", "y"]}
    assert ray_dask_get(dsk, "w") == [1, 2]


def test_deep_chain_no_recursion_limit(ray_start_regular):
    n = 2000
    dsk = {"k0": 0}
    for i in range(1, n):
        dsk[f"k{i}"] = (operator.add, f"k{i-1}", 1)
    # Far beyond the default recursion limit if walked recursively.
    assert ray_dask_get(dsk, f"k{n-1}") == n - 1


def test_cycle_detected(ray_start_regular):
    import pytest

    dsk = {"a": (operator.add, "b", 1), "b": (operator.add, "a", 1)}
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get(dsk, "a")
