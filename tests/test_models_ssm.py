"""SSM (Mamba-family) tests: causality, recurrence correctness vs a
sequential reference, and LM convergence on the CPU fake backend."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jaxlib():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    return jax, jnp


def test_selective_scan_matches_sequential(jaxlib):
    jax, jnp = jaxlib
    from ray_tpu.models.ssm import _selective_scan

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(0.1, 0.99, (2, 9, 3, 4)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((2, 9, 3, 4)).astype(np.float32))
    h = np.asarray(_selective_scan(a, b))
    ref = np.zeros_like(h)
    acc = np.zeros((2, 3, 4), np.float32)
    for t in range(9):
        acc = np.asarray(a)[:, t] * acc + np.asarray(b)[:, t]
        ref[:, t] = acc
    np.testing.assert_allclose(h, ref, rtol=1e-5, atol=1e-5)


def test_ssm_model_is_causal(jaxlib):
    jax, jnp = jaxlib
    from ray_tpu.models import TINY_SSM, SSMModel

    model = SSMModel(TINY_SSM)
    tokens = jnp.ones((1, 12), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    base = np.asarray(model.apply(params, tokens))
    # Changing token t=8 must not change logits at positions < 8.
    perturbed = np.asarray(model.apply(params, tokens.at[0, 8].set(7)))
    np.testing.assert_allclose(base[:, :8], perturbed[:, :8],
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(base[:, 8:], perturbed[:, 8:])


def test_ssm_lm_trains(jaxlib):
    jax, jnp = jaxlib
    import optax

    from ray_tpu.models import TINY_SSM, SSMModel, cross_entropy_loss

    model = SSMModel(TINY_SSM)
    rng = np.random.default_rng(0)
    # Predictable sequence: t+1 = (t*3 + 1) % 200 — learnable by an LM.
    seq = [5]
    for _ in range(32):
        seq.append((seq[-1] * 3 + 1) % 200)
    data = jnp.asarray([seq], jnp.int32)
    inp, tgt = data[:, :-1], data[:, 1:]
    params = model.init(jax.random.PRNGKey(0), inp)
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: cross_entropy_loss(model.apply(p, inp), tgt))(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, first = step(params, opt_state)
    for _ in range(80):
        params, opt_state, loss = step(params, opt_state)
    assert float(loss) < float(first) * 0.3
