"""SSM (Mamba-family) tests: causality, recurrence correctness vs a
sequential reference, and LM convergence on the CPU fake backend."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jaxlib():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    return jax, jnp


def test_selective_scan_matches_sequential(jaxlib):
    jax, jnp = jaxlib
    from ray_tpu.models.ssm import _selective_scan

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(0.1, 0.99, (2, 9, 3, 4)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((2, 9, 3, 4)).astype(np.float32))
    h = np.asarray(_selective_scan(a, b))
    ref = np.zeros_like(h)
    acc = np.zeros((2, 3, 4), np.float32)
    for t in range(9):
        acc = np.asarray(a)[:, t] * acc + np.asarray(b)[:, t]
        ref[:, t] = acc
    np.testing.assert_allclose(h, ref, rtol=1e-5, atol=1e-5)


def test_ssm_model_is_causal(jaxlib):
    jax, jnp = jaxlib
    from ray_tpu.models import TINY_SSM, SSMModel

    model = SSMModel(TINY_SSM)
    tokens = jnp.ones((1, 12), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    base = np.asarray(model.apply(params, tokens))
    # Changing token t=8 must not change logits at positions < 8.
    perturbed = np.asarray(model.apply(params, tokens.at[0, 8].set(7)))
    np.testing.assert_allclose(base[:, :8], perturbed[:, :8],
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(base[:, 8:], perturbed[:, 8:])


def test_ssm_lm_trains(jaxlib):
    jax, jnp = jaxlib
    import optax

    from ray_tpu.models import TINY_SSM, SSMModel, cross_entropy_loss

    model = SSMModel(TINY_SSM)
    rng = np.random.default_rng(0)
    # Predictable sequence: t+1 = (t*3 + 1) % 200 — learnable by an LM.
    seq = [5]
    for _ in range(32):
        seq.append((seq[-1] * 3 + 1) % 200)
    data = jnp.asarray([seq], jnp.int32)
    inp, tgt = data[:, :-1], data[:, 1:]
    params = model.init(jax.random.PRNGKey(0), inp)
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: cross_entropy_loss(model.apply(p, inp), tgt))(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, first = step(params, opt_state)
    for _ in range(80):
        params, opt_state, loss = step(params, opt_state)
    assert float(loss) < float(first) * 0.3


def test_ssm_incremental_decode_matches_parallel(jaxlib):
    """O(1) stateful decode reproduces the full-sequence forward exactly
    (the SSM analog of KV-cache-vs-full-attention equivalence)."""
    jax, jnp = jaxlib
    import numpy as np

    from ray_tpu.models import TINY_SSM, SSMModel
    from ray_tpu.models.ssm import init_ssm_state, ssm_decode_step

    model = SSMModel(TINY_SSM)
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, 256, (2, 10)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    full = np.asarray(model.apply(params, tokens))  # (2, 10, V)

    states = init_ssm_state(TINY_SSM, batch=2)
    step = jax.jit(lambda p, t, s: ssm_decode_step(model, p, t, s))
    for t in range(10):
        logits, states = step(params, tokens[:, t], states)
        np.testing.assert_allclose(np.asarray(logits), full[:, t],
                                   rtol=2e-4, atol=2e-4)


def test_ssm_prefill_then_decode(jaxlib):
    """One parallel prefill primes the decode state: continuing from it
    matches the full-sequence forward position-for-position."""
    jax, jnp = jaxlib
    import numpy as np

    from ray_tpu.models import TINY_SSM, SSMModel
    from ray_tpu.models.ssm import ssm_decode_step, ssm_prefill

    model = SSMModel(TINY_SSM)
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.integers(0, 256, (2, 12)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    full = np.asarray(model.apply(params, tokens))  # (2, 12, V)

    last_logits, states = ssm_prefill(model, params, tokens[:, :8])
    np.testing.assert_allclose(np.asarray(last_logits), full[:, 7],
                               rtol=2e-4, atol=2e-4)
    step = jax.jit(lambda p, t, s: ssm_decode_step(model, p, t, s))
    for t in range(8, 12):
        logits, states = step(params, tokens[:, t], states)
        np.testing.assert_allclose(np.asarray(logits), full[:, t],
                                   rtol=2e-4, atol=2e-4)


def test_ssm_trains_under_sharded_mesh(jaxlib):
    """SSM_RULES shard the model over a dp x fsdp x tp mesh and one
    sharded train step runs (the dryrun_multichip pattern for this
    family)."""
    jax, jnp = jaxlib
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models import TINY_SSM, SSMModel, cross_entropy_loss
    from ray_tpu.models.ssm import SSM_RULES
    from ray_tpu.parallel import MeshConfig, make_mesh
    from ray_tpu.train.spmd import (init_sharded_state, make_train_step,
                                    shard_train_step)

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    model = SSMModel(TINY_SSM)
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    tokens = jnp.zeros((4, 16), jnp.int32)
    opt = optax.adam(1e-3)
    state, specs = init_sharded_state(
        mesh, lambda t: model.init(jax.random.PRNGKey(0), t),
        SSM_RULES, opt, tokens)

    def loss_fn(params, batch):
        inp, tgt = batch
        return cross_entropy_loss(model.apply(params, inp), tgt)

    step = make_train_step(loss_fn, opt)
    bspec = (P(("dp", "fsdp"), None), P(("dp", "fsdp"), None))
    sstep = shard_train_step(step, mesh, specs, bspec)
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (4, 17)), jnp.int32)
    ex = jax.device_put(
        (data[:, :-1], data[:, 1:]),
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), bspec,
                               is_leaf=lambda x: isinstance(x, P)))
    state, metrics = sstep(state, ex)
    assert np.isfinite(float(metrics["loss"]))


def test_encoder_trains_under_sharded_mesh(jaxlib):
    """The encoder family shards with the standard TRANSFORMER_RULES
    (its projection names match) over the same mesh."""
    jax, jnp = jaxlib
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models import TINY_ENCODER, Encoder, mlm_loss
    from ray_tpu.parallel import MeshConfig, TRANSFORMER_RULES, make_mesh
    from ray_tpu.train.spmd import (init_sharded_state, make_train_step,
                                    shard_train_step)

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    model = Encoder(TINY_ENCODER)
    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    tokens = jnp.zeros((4, 16), jnp.int32)
    opt = optax.adam(1e-3)
    state, specs = init_sharded_state(
        mesh, lambda t: model.init(jax.random.PRNGKey(0), t),
        TRANSFORMER_RULES, opt, tokens)

    def loss_fn(params, batch):
        inp, tgt, mask = batch
        _, logits = model.apply(params, inp)
        return mlm_loss(logits, tgt, mask)

    step = make_train_step(loss_fn, opt)
    bspec = (P(("dp", "fsdp"), None),) * 3
    sstep = shard_train_step(step, mesh, specs, bspec)
    rng = np.random.default_rng(0)
    tgt = jnp.asarray(rng.integers(3, 256, (4, 16)), jnp.int32)
    mask = jnp.asarray(rng.random((4, 16)) < 0.3)
    inp = jnp.where(mask, 1, tgt)
    ex = jax.device_put(
        (inp, tgt, mask),
        jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), bspec,
                               is_leaf=lambda x: isinstance(x, P)))
    state, metrics = sstep(state, ex)
    assert np.isfinite(float(metrics["loss"]))
