"""Job submission + dashboard + timeline tests (parity model: reference
dashboard/modules/job/tests and `ray timeline`)."""

import json
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.job_submission import (
    FAILED,
    STOPPED,
    SUCCEEDED,
    JobSubmissionClient,
)


def test_submit_job_succeeds(ray_start_regular):
    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('hello from job')\"")
    status = client.wait_until_finished(sid, timeout=60)
    assert status == SUCCEEDED
    assert "hello from job" in client.get_job_logs(sid)
    infos = client.list_jobs()
    assert any(j.submission_id == sid for j in infos)


def test_submit_job_failure_reported(ray_start_regular):
    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"raise SystemExit(3)\"")
    assert client.wait_until_finished(sid, timeout=60) == FAILED
    info = client.get_job_info(sid)
    assert "code 3" in info.message


def test_job_env_vars(ray_start_regular):
    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=(f"{sys.executable} -c "
                    "\"import os; print('VAR=' + os.environ['JOBVAR'])\""),
        runtime_env={"env_vars": {"JOBVAR": "jv1"}})
    assert client.wait_until_finished(sid, timeout=60) == SUCCEEDED
    assert "VAR=jv1" in client.get_job_logs(sid)


def test_stop_job(ray_start_regular):
    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import time; time.sleep(60)\"")
    deadline = time.monotonic() + 30
    while client.get_job_status(sid) != "RUNNING":
        assert time.monotonic() < deadline
        time.sleep(0.1)
    # Give the subprocess a moment to actually spawn.
    time.sleep(0.3)
    assert client.stop_job(sid)
    assert client.wait_until_finished(sid, timeout=30) == STOPPED


def test_dashboard_endpoints(ray_start_regular):
    from ray_tpu import dashboard

    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get([noop.remote() for _ in range(3)])
    port = dashboard.start(port=0)
    try:
        base = f"http://127.0.0.1:{port}"
        page = urllib.request.urlopen(f"{base}/").read().decode()
        assert "ray_tpu dashboard" in page
        nodes = json.loads(urllib.request.urlopen(f"{base}/api/nodes").read())
        assert len(nodes) == 1
        status = json.loads(
            urllib.request.urlopen(f"{base}/api/cluster_status").read())
        assert "nodes" in status or status
        ver = json.loads(urllib.request.urlopen(f"{base}/api/version").read())
        assert ver["version"] == ray_tpu.__version__
        # Observability additions: lifecycle latency breakdown + daemon
        # event-loop stats.
        lat = json.loads(urllib.request.urlopen(
            f"{base}/api/summary/task_latency").read())
        # Flush cadence is 1s, so counts may still be 0 here — this is
        # the endpoint contract check; test_task_latency covers content.
        assert "stages" in lat and "tasks" in lat
        pump = json.loads(urllib.request.urlopen(
            f"{base}/api/pump_stats").read())
        assert sum(h["count"] for h in
                   pump["gcs"]["server"]["handlers"].values()) > 0
    finally:
        dashboard.stop()


def test_dashboard_spa_contract(ray_start_regular):
    """The SPA (dashboard_static/app.js) and the server must agree:
    every endpoint the client fetches answers 200 with the right
    content type, the static assets serve, and path traversal 404s
    (parity model: reference dashboard/client against head.py routes —
    there the contract is typed via API clients; here it's enforced by
    extracting every fetch target from the shipped app.js)."""
    import re
    import urllib.error

    from ray_tpu import dashboard

    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get([noop.remote() for _ in range(2)])
    port = dashboard.start(port=0)
    try:
        base = f"http://127.0.0.1:{port}"

        def fetch(p):
            with urllib.request.urlopen(base + p, timeout=60) as r:
                return r.status, r.headers.get("Content-Type", ""), r.read()

        # SPA shell + assets (the reference serves its built React app the
        # same way: GET / -> SPA, which then talks JSON).
        st, ctype, body = fetch("/")
        assert st == 200 and "text/html" in ctype
        assert b"app.js" in body
        st, ctype, js = fetch("/static/app.js")
        assert st == 200 and "javascript" in ctype
        st, ctype, _ = fetch("/static/app.css")
        assert st == 200 and "css" in ctype

        # Traversal attempts and unknown assets must 404.
        for bad in ["/static/../dashboard.py", "/static/nope.js"]:
            with pytest.raises(urllib.error.HTTPError) as e:
                fetch(bad)
            assert e.value.code == 404

        # Every URL the client code fetches must answer. /api/profile is
        # excluded: it samples live workers for N seconds (covered by
        # test_dashboard_log_and_reporter_views) and would stall this test.
        src = js.decode()
        # Both quote styles: getJSON("/api/x") and getText(`/logs/view?...`)
        # — a template-literal fetch must not escape the sweep.
        urls = set(re.findall(r'get(?:JSON|Text)\((["`])(/[^"`?$]+)', src))
        urls = {u for _, u in urls}
        urls.discard("/api/profile")
        urls.discard("/api/submission_jobs/logs")  # needs ?id=, below
        assert "/api/cluster_status" in urls and "/api/events" in urls
        for u in sorted(urls):
            st, ctype, body = fetch(u)
            assert st == 200, (u, st)
            if "json" in ctype:
                json.loads(body)

        # Endpoints with query params that app.js builds dynamically:
        # unknown submission ids are a clean 404, not a 500.
        with pytest.raises(urllib.error.HTTPError) as e:
            fetch("/api/submission_jobs/logs?id=nope")
        assert e.value.code == 404

        # Shape contracts the SPA's drill-down views rely on (a 200 with
        # the wrong fields renders an empty page, so pin them): node
        # detail filters worker_stats/logs rows by FULL node_id and
        # narrows the log fan-out with ?node=.
        nid = json.loads(fetch("/api/nodes")[2])[0]["node_id"]
        ws = json.loads(fetch("/api/worker_stats")[2])
        assert ws and all(r["node_id"] == nid for r in ws)
        assert any(r["worker_id"] != "(raylet)" for r in ws)
        logs = json.loads(fetch("/api/logs?node=" + nid)[2])
        assert logs and all(r["node_id"] == nid for r in logs)
        assert json.loads(fetch("/api/logs?node=ffffffffff")[2]) == []
    finally:
        dashboard.stop()


def test_timeline_dump(ray_start_regular, tmp_path):
    from ray_tpu.util.timeline import build_trace_events, dump_timeline

    @ray_tpu.remote
    def work(x):
        time.sleep(0.01)
        return x

    ray_tpu.get([work.remote(i) for i in range(5)])
    time.sleep(1.5)  # task-event flush cadence is 1s
    path = str(tmp_path / "trace.json")
    dump_timeline(path)
    with open(path) as f:
        trace = json.load(f)
    complete = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(complete) >= 5
    assert all(e["dur"] >= 0 for e in complete)


def test_build_trace_events_pairs():
    from ray_tpu.util.timeline import build_trace_events

    events = [
        {"task_id": "t1", "name": "f", "state": "RUNNING", "ts": 10.0,
         "node_id": "n1", "worker_id": "w1", "job_id": "j"},
        {"task_id": "t1", "name": "f", "state": "FINISHED", "ts": 10.5,
         "node_id": "n1", "worker_id": "w1", "job_id": "j"},
        {"task_id": "t2", "name": "g", "state": "RUNNING", "ts": 11.0,
         "node_id": "n1", "worker_id": "w1", "job_id": "j"},
    ]
    trace = build_trace_events(events)
    x = [e for e in trace if e["ph"] == "X"]
    assert len(x) == 1 and abs(x[0]["dur"] - 0.5e6) < 1
    assert len([e for e in trace if e["ph"] == "i"]) == 1


def test_prometheus_metrics_endpoint(ray_start_regular):
    """/metrics serves Prometheus text exposition (parity: reference
    metrics agent prometheus_exporter endpoint)."""
    import time
    import urllib.request

    from ray_tpu import dashboard
    from ray_tpu.util.metrics import Counter, Histogram

    c = Counter("dash_requests_total", description="reqs",
                tag_keys=("route",))
    c.inc(3, tags={"route": "a"})
    h = Histogram("dash_latency_seconds", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)
    time.sleep(1.2)
    c.inc(0, tags={"route": "a"})  # force a flush past the interval

    port = dashboard.start(port=0)
    try:
        deadline = time.monotonic() + 10
        text = ""
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            if "dash_requests_total" in text:
                break
            time.sleep(0.3)
        assert "ray_tpu_cluster_nodes_alive 1" in text
        assert 'resource="CPU"' in text
        assert "# TYPE dash_requests_total counter" in text
        assert 'route="a"' in text
        assert "# TYPE dash_latency_seconds histogram" in text
        assert 'dash_latency_seconds_bucket' in text
        assert 'le="+Inf"' in text
        assert "dash_latency_seconds_count" in text
        assert "dash_latency_seconds_sum" in text
    finally:
        dashboard.stop()


def test_dashboard_log_and_reporter_views(ray_start_regular):
    """Log browser + tail, worker cpu/rss stats, and stack dumps — the
    reference's dashboard log + reporter module data views."""
    import json as _json
    import urllib.request

    import ray_tpu
    from ray_tpu import dashboard

    @ray_tpu.remote
    def chatty():
        print("dashboard-log-marker")
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=120) == 1
    port = dashboard.start(port=0)
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=30) as r:
                return r.read().decode()

        logs = _json.loads(get("/api/logs"))
        assert logs, "at least one worker log must be listed"
        worker_logs = [l for l in logs if l["file"].startswith("worker-")]
        assert worker_logs
        # Tail one worker log through the view endpoint; find the marker.
        found = False
        for entry in worker_logs:
            body = get(entry["view"])
            if "dashboard-log-marker" in body:
                found = True
                break
        assert found, "task stdout must be visible through the log viewer"

        stats = _json.loads(get("/api/worker_stats"))
        assert any(r["worker_id"] == "(raylet)" for r in stats)
        workers = [r for r in stats if r["worker_id"] != "(raylet)"]
        assert workers and all(r.get("rss_mb", 0) > 0 for r in workers)

        stacks = _json.loads(get("/api/stacks"))
        assert stacks and any(n.get("workers") for n in stacks)
    finally:
        dashboard.stop()


def test_grafana_dashboard_generation():
    """Generated Grafana JSON (reference: dashboard/modules/metrics
    grafana_dashboard_factory): core panels always present, registered
    user metrics appended with type-appropriate queries."""
    from ray_tpu.util import metrics
    from ray_tpu.util.grafana import generate_dashboard, write_dashboard

    metrics.Counter("graftest_requests", "test counter")
    metrics.Histogram("graftest_latency", "test histogram",
                      boundaries=[0.1, 1.0])
    dash = generate_dashboard()
    assert dash["schemaVersion"] >= 30 and dash["panels"]
    titles = [p["title"] for p in dash["panels"]]
    assert any("Task throughput" in t for t in titles)
    # Every core panel must target a metric the /metrics exporter can
    # actually emit — keep grafana.py and metrics.py mechanically in
    # sync (a renamed gauge must fail here, not show 'No data' live).
    import inspect
    import re

    from ray_tpu.util import metrics as _metrics
    from ray_tpu.util import grafana as _grafana

    exporter_src = inspect.getsource(_metrics)
    for _title, _kind, expr in _grafana._CORE_PANELS:
        base = re.findall(r"ray_tpu_[a-z_]+", expr)[0]
        assert base in exporter_src, f"core panel metric {base} not exported"
    exprs = [p["targets"][0]["expr"] for p in dash["panels"]]
    assert any("rate(graftest_requests_total[1m])" in e for e in exprs)
    assert any("histogram_quantile(0.95" in e and "graftest_latency" in e
               for e in exprs)
    # Every panel targets the templated prometheus datasource.
    assert all(p["datasource"]["uid"] == "${datasource}"
               for p in dash["panels"])

    import json as _json
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", mode="r") as f:
        write_dashboard(f.name)
        model = _json.load(open(f.name))
    assert model["uid"] == "ray_tpu-autogen"


def test_metrics_history_contract(ray_start_regular):
    """/api/metrics/history feeds the SPA's time-series panels: samples
    accumulate on a ring, each carrying per-node cpu/store/workers plus
    a cluster task rate (reference: dashboard/modules/metrics/ renders
    the same series via Prometheus+Grafana)."""
    import time

    from ray_tpu import dashboard

    @ray_tpu.remote
    def noop():
        return 1

    port = dashboard.start(port=0)
    try:
        base = f"http://127.0.0.1:{port}"
        ray_tpu.get([noop.remote() for _ in range(20)])
        deadline = time.monotonic() + 30
        hist = {"samples": []}
        while time.monotonic() < deadline and len(hist["samples"]) < 2:
            time.sleep(1.0)
            hist = json.loads(urllib.request.urlopen(
                f"{base}/api/metrics/history").read())
        assert hist["interval_s"] > 0
        assert len(hist["samples"]) >= 2, hist
        s = hist["samples"][-1]
        assert "ts" in s and "task_rate_per_s" in s
        assert s["nodes"], "per-node series missing"
        node = next(iter(s["nodes"].values()))
        for k in ("cpu_used", "cpu_total", "workers", "store_mb",
                  "pending_leases"):
            assert k in node, f"missing {k}: {node}"
    finally:
        dashboard.stop()
