"""Serve tests (parity: reference python/ray/serve/tests)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster(ray_start_regular):
    yield
    serve.shutdown()


def test_function_deployment(serve_cluster):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert handle.remote(42).result() == {"echo": 42}


def test_class_deployment_with_state(serve_cluster):
    @serve.deployment
    class Model:
        def __init__(self, scale):
            self.scale = scale

        def __call__(self, x):
            return x * self.scale

        def describe(self):
            return {"scale": self.scale}

    handle = serve.run(Model.bind(10))
    assert handle.remote(4).result() == 40
    assert handle.options(method_name="describe").remote().result() == \
        {"scale": 10}


def test_multiple_replicas_route(serve_cluster):
    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __call__(self, _):
            import os

            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    pids = {handle.remote(None).result() for _ in range(12)}
    assert len(pids) == 2  # both replicas served traffic


def test_batching(serve_cluster):
    @serve.deployment
    class Batched:
        def __call__(self, items):
            # Receives a list when called through a batching handle.
            return [i * 2 for i in items]

    serve.run(Batched.bind())
    handle = serve.get_deployment_handle("Batched").options(
        batching=(4, 0.05))
    responses = [handle.remote(i) for i in range(8)]
    assert [r.result() for r in responses] == [i * 2 for i in range(8)]


def test_status_and_delete(serve_cluster):
    @serve.deployment
    def f(x):
        return x

    serve.run(f.bind())
    st = serve.status()
    assert st["f"]["num_replicas"] == 1
    serve.delete("f")
    assert "f" not in serve.status()


def test_http_proxy(serve_cluster):
    @serve.deployment
    def classify(payload):
        return {"label": "ok", "score": payload.get("value", 0) * 2}

    serve.run(classify.bind())
    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/classify",
        data=json.dumps({"value": 21}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.load(resp)
    assert body["result"] == {"label": "ok", "score": 42}


def test_autoscaling_up(serve_cluster):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0, "upscale_delay_s": 0.0})
    class Slow:
        def __call__(self, _):
            time.sleep(0.5)
            return 1

    handle = serve.run(Slow.bind())
    responses = [handle.remote(None) for _ in range(9)]
    deadline = time.time() + 30
    while time.time() < deadline:
        if serve.status()["Slow"]["num_replicas"] > 1:
            break
        time.sleep(0.2)
    assert serve.status()["Slow"]["num_replicas"] > 1
    for r in responses:
        r.result(timeout=120)


def test_replica_replaced_on_crash(serve_cluster):
    @serve.deployment(num_replicas=1)
    class Fragile:
        def __call__(self, x):
            if x == "die":
                import os

                os._exit(1)
            return "alive"

    handle = serve.run(Fragile.bind())
    assert handle.remote("hi").result(timeout=60) == "alive"
    try:
        handle.remote("die").result(timeout=10)
    except Exception:
        pass
    # The controller health loop replaces the dead replica.
    deadline = time.time() + 40
    while time.time() < deadline:
        try:
            if handle.remote("hi").result(timeout=10) == "alive":
                break
        except Exception:
            time.sleep(0.5)
    assert handle.remote("hi").result(timeout=30) == "alive"


def test_multiplexed_models(serve_cluster):
    @serve.deployment(num_replicas=2)
    class MultiModel:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            return {"id": model_id, "pid_loaded": __import__("os").getpid()}

        def __call__(self, _):
            mid = serve.get_multiplexed_model_id()
            model = self.get_model(mid)
            return {"model": model["id"], "pid": __import__("os").getpid()}

    handle = serve.run(MultiModel.bind())
    r1 = handle.options(multiplexed_model_id="m1").remote(None).result(timeout=60)
    assert r1["model"] == "m1"
    # Subsequent m1 requests stick to a replica that has m1 resident.
    pids = {handle.options(multiplexed_model_id="m1")
            .remote(None).result(timeout=60)["pid"] for _ in range(4)}
    assert pids == {r1["pid"]}


def test_route_prefix(serve_cluster):
    import json
    import urllib.request

    @serve.deployment
    def api(payload):
        return {"got": payload}

    serve.run(api.bind(), route_prefix="/v1/api")
    port = serve.start_http_proxy(port=0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/api/anything",
        data=json.dumps({"k": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.load(resp)
    assert body["result"] == {"got": {"k": 1}}


def test_deployment_graph_composition(ray_start_regular):
    """serve.run of a bound graph deploys children first and hands the
    parent live handles (parity: deployment-graph DAG composition)."""
    from ray_tpu import serve

    @serve.deployment(name="adder")
    class Adder:
        def __call__(self, x):
            return x + 1

    @serve.deployment(name="doubler")
    class Doubler:
        def __call__(self, x):
            return x * 2

    @serve.deployment(name="ensemble")
    class Ensemble:
        def __init__(self, adder, doubler):
            self.adder = adder
            self.doubler = doubler

        def __call__(self, x):
            a = self.adder.remote(x).result(timeout=30)
            d = self.doubler.remote(x).result(timeout=30)
            return a + d

    try:
        handle = serve.run(Ensemble.bind(Adder.bind(), Doubler.bind()))
        # (5+1) + (5*2) = 16, through two nested deployment calls.
        assert handle.remote(5).result(timeout=60) == 16
        assert set(serve.status()) >= {"adder", "doubler", "ensemble"}
    finally:
        serve.shutdown()


def test_rolling_update_zero_downtime(serve_cluster):
    """Code redeploy rolls replicas one at a time: a client hammering the
    deployment throughout the rollout sees ZERO failed requests and
    eventually the new code's answers (reference: deployment_state.py:1149
    versioned rolling updates + graceful drain; long_poll.py pushes the
    changing replica set to handles)."""
    import threading

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    def versioned(payload=None):
        return "v1"

    handle = serve.run(versioned.bind(), name="roll")
    assert handle.remote().result(timeout=60) == "v1"

    results, errors = [], []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                results.append(handle.remote().result(timeout=60))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(2)]
    for t in threads:
        t.start()
    try:
        import time

        time.sleep(1.0)

        @serve.deployment(num_replicas=2)
        def versioned(payload=None):  # noqa: F811  (new code version)
            return "v2"

        serve.run(versioned.bind(), name="roll")  # rolling redeploy
        # After the redeploy returns, answers must be v2.
        deadline = time.time() + 30
        while time.time() < deadline:
            if handle.remote().result(timeout=60) == "v2":
                break
        assert handle.remote().result(timeout=60) == "v2"
        time.sleep(0.5)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)

    assert not errors, f"requests failed during rollout: {errors[:3]}"
    assert "v1" in results and "v2" in results
    # No interleaved stale answers after the rollout completed.
    serve.delete("roll")


def test_long_poll_pushes_updates(serve_cluster):
    """Handles learn of replica-set changes via the controller's held
    long-poll connection, not TTL polling (reference: long_poll.py:63)."""
    import time

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.controller import CONTROLLER_NAME

    @serve.deployment(num_replicas=1)
    def app(payload=None):
        return "ok"

    handle = serve.run(app.bind(), name="lp")
    assert handle.remote().result(timeout=60) == "ok"
    router = handle._router
    assert router.poll_thread is not None and router.poll_thread.is_alive()
    deadline = time.time() + 20
    while time.time() < deadline and router.poll_version == 0:
        time.sleep(0.2)  # starved-box tolerance for the first push
    v0 = router.poll_version
    assert v0 > 0  # first push observed

    # Scale up through a redeploy; the push must bump the version and
    # grow the replica set without any request-driven refresh.
    @serve.deployment(num_replicas=3)
    def app(payload=None):  # noqa: F811
        return "ok"

    serve.run(app.bind(), name="lp")
    deadline = time.time() + 20
    while time.time() < deadline and len(router.replicas) != 3:
        time.sleep(0.2)
    assert len(router.replicas) == 3
    assert router.poll_version > v0
    serve.delete("lp")


def test_rpc_binary_ingress(serve_cluster):
    """The second ingress protocol (reference: the proxy's gRPC listener
    beside HTTP, proxy.py:13-38): a client calls a deployment over the
    binary msgpack-RPC framing — unary, routed-by-prefix, and a
    streaming response delivered as per-chunk notifies."""
    from ray_tpu.serve.rpc_ingress import RpcIngressClient

    @serve.deployment
    def echo(payload):
        return {"echo": payload.get("msg"), "n": payload.get("n", 0) + 1}

    @serve.deployment
    def tokens(payload):
        for i in range(payload.get("count", 3)):
            yield {"tok": i}

    serve.run(echo.bind(), route_prefix="/api/echo")
    serve.run(tokens.bind())
    port = serve.start_rpc_proxy(port=0)
    client = RpcIngressClient("127.0.0.1", port)
    try:
        # unary by deployment name
        out = client.call({"msg": "hi", "n": 41}, deployment="echo")
        assert out == {"echo": "hi", "n": 42}
        # unary by route prefix
        out = client.call({"msg": "routed"}, route="/api/echo/sub")
        assert out["echo"] == "routed"
        # unknown deployment -> error, connection stays usable
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            client.call({}, deployment="nope-not-here")
        assert client.call({"msg": "still-alive"},
                           deployment="echo")["echo"] == "still-alive"
        # streaming response
        chunks = list(client.stream({"count": 4}, deployment="tokens"))
        assert chunks == [{"tok": 0}, {"tok": 1}, {"tok": 2}, {"tok": 3}]
    finally:
        client.close()
