"""Unit tests for owner-side worker internals (no cluster spin-up).

Covers the retry re-enqueue ordering protocol: a retried producer must
re-enter its queue AHEAD of any later-submitted task (a tail re-enqueue
can place a dependent consumer first in the same sequential push batch,
deadlocking the worker exec thread — advisor finding, round 2).
"""

from collections import defaultdict

from ray_tpu._private.common import TaskSpec
from ray_tpu._private.worker import _PendingTask
import ray_tpu._private.worker as worker_mod


class _QueueHarness:
    """Just enough of Worker for _enqueue_task: queues + pending map."""

    def __init__(self):
        self._queues = defaultdict(list)
        self.pending_tasks = {}
        self.pumped = []

    def _spawn(self, coro):
        coro.close()  # never run the pump; we only inspect queue order

    def _pump_queue(self, shape, spec):
        async def noop():
            self.pumped.append(shape)
        return noop()

    def enqueue(self, pt):
        self.pending_tasks[pt.spec.task_id] = pt
        worker_mod.CoreWorker._enqueue_task(self, pt)

    def queue(self):
        [(shape, q)] = self._queues.items()
        return q


def _pt(task_id: str) -> _PendingTask:
    return _PendingTask(
        TaskSpec(task_id=task_id, job_id="j", name=task_id, func_key="f"),
        retries_left=3)


def test_fresh_submissions_append_in_order():
    h = _QueueHarness()
    pts = [_pt(f"t{i}") for i in range(4)]
    for pt in pts:
        h.enqueue(pt)
    assert h.queue() == ["t0", "t1", "t2", "t3"]


def test_retry_reenqueues_before_later_submissions():
    h = _QueueHarness()
    producer, consumer = _pt("producer"), _pt("consumer")
    h.enqueue(producer)
    h.enqueue(consumer)
    # Producer gets popped for a push attempt that fails retryably...
    h.queue().remove("producer")
    # ...and must re-enter AHEAD of the later-submitted consumer.
    worker_mod.CoreWorker._enqueue_task(h, producer)
    assert h.queue() == ["producer", "consumer"]


def test_multiple_retries_preserve_relative_order():
    h = _QueueHarness()
    p1, p2, c = _pt("p1"), _pt("p2"), _pt("c")
    for pt in (p1, p2, c):
        h.enqueue(pt)
    h.queue().remove("p1")
    h.queue().remove("p2")
    # Retry in batch order p1 then p2 (the order a failed batch is walked):
    worker_mod.CoreWorker._enqueue_task(h, p1)
    worker_mod.CoreWorker._enqueue_task(h, p2)
    assert h.queue() == ["p1", "p2", "c"]


def test_stale_queue_ids_do_not_break_ordering():
    h = _QueueHarness()
    p, c = _pt("p"), _pt("c")
    h.enqueue(p)
    h.enqueue(c)
    # A completed task whose id still sits in the queue (popped lazily).
    h.queue().insert(0, "gone")
    h.queue().remove("p")
    worker_mod.CoreWorker._enqueue_task(h, p)
    # p lands after the stale entry but before the younger consumer.
    assert h.queue() == ["gone", "p", "c"]
