"""Ecosystem shims: multiprocessing.Pool and the joblib backend.

Parity: reference python/ray/tests/test_multiprocessing.py and
python/ray/util/joblib tests.
"""

import pytest

from ray_tpu.util.multiprocessing import Pool


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


@pytest.fixture
def pool(ray_start_regular):
    p = Pool(processes=2)
    yield p
    p.terminate()


def test_pool_map(pool):
    assert pool.map(_sq, range(10)) == [x * x for x in range(10)]


def test_pool_map_chunked(pool):
    assert pool.map(_sq, range(7), chunksize=3) == [x * x for x in range(7)]


def test_pool_apply(pool):
    assert pool.apply(_add, (2, 3)) == 5
    res = pool.apply_async(_add, (4, 5))
    res.wait(timeout=30)
    assert res.ready()
    assert res.get() == 9


def test_pool_starmap(pool):
    assert pool.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]


def test_pool_imap(pool):
    assert list(pool.imap(_sq, range(5), chunksize=2)) == [0, 1, 4, 9, 16]
    assert sorted(pool.imap_unordered(_sq, range(5), chunksize=2)) == \
        sorted([0, 1, 4, 9, 16])


def test_pool_lifecycle(ray_start_regular):
    p = Pool(processes=1)
    p.close()
    with pytest.raises(ValueError):
        p.map(_sq, [1])
    p.join()


def test_pool_context_manager(ray_start_regular):
    with Pool(processes=1) as p:
        assert p.map(_sq, [3]) == [9]


def test_joblib_backend(ray_start_regular):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray", n_jobs=2):
        got = joblib.Parallel()(joblib.delayed(_sq)(i) for i in range(6))
    assert got == [x * x for x in range(6)]
