"""Ecosystem shims: multiprocessing.Pool and the joblib backend.

Parity: reference python/ray/tests/test_multiprocessing.py and
python/ray/util/joblib tests.
"""

import pytest

from ray_tpu.util.multiprocessing import Pool


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


@pytest.fixture
def pool(ray_start_regular):
    p = Pool(processes=2)
    yield p
    p.terminate()


def test_pool_map(pool):
    assert pool.map(_sq, range(10)) == [x * x for x in range(10)]


def test_pool_map_chunked(pool):
    assert pool.map(_sq, range(7), chunksize=3) == [x * x for x in range(7)]


def test_pool_apply(pool):
    assert pool.apply(_add, (2, 3)) == 5
    res = pool.apply_async(_add, (4, 5))
    res.wait(timeout=30)
    assert res.ready()
    assert res.get() == 9


def test_pool_starmap(pool):
    assert pool.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]


def test_pool_imap(pool):
    assert list(pool.imap(_sq, range(5), chunksize=2)) == [0, 1, 4, 9, 16]
    assert sorted(pool.imap_unordered(_sq, range(5), chunksize=2)) == \
        sorted([0, 1, 4, 9, 16])


def test_pool_lifecycle(ray_start_regular):
    p = Pool(processes=1)
    p.close()
    with pytest.raises(ValueError):
        p.map(_sq, [1])
    p.join()


def test_pool_context_manager(ray_start_regular):
    with Pool(processes=1) as p:
        assert p.map(_sq, [3]) == [9]


def test_joblib_backend(ray_start_regular):
    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray", n_jobs=2):
        got = joblib.Parallel()(joblib.delayed(_sq)(i) for i in range(6))
    assert got == [x * x for x in range(6)]


def test_workflow_dynamic_continuation(ray_start_regular, tmp_path):
    """A step returning another step recurses (reference:
    workflow.continuation) — here a durable recursive factorial."""
    from ray_tpu import workflow

    @workflow.step
    def fact(n, acc=1):
        if n <= 1:
            return acc
        return fact.step(n - 1, acc * n)

    out = workflow.run(fact.step(6), workflow_id="wf-dyn",
                       storage=str(tmp_path))
    assert out == 720
    assert workflow.get_output("wf-dyn", storage=str(tmp_path)) == 720


def test_workflow_wait_for_event(ray_start_regular, tmp_path):
    """Events are durable steps: the workflow blocks until the listener
    fires, and a resumed run reuses the checkpointed payload."""
    import threading
    import time

    from ray_tpu import workflow

    flag = tmp_path / "fired"

    class FileEvent(workflow.EventListener):
        def poll_for_event(self, path):
            import os
            import time as t

            for _ in range(200):
                if os.path.exists(path):
                    with open(path) as f:
                        return f.read()
                t.sleep(0.05)
            raise TimeoutError("event never fired")

    @workflow.step
    def combine(payload):
        return f"got:{payload}"

    def fire():
        time.sleep(0.5)
        flag.write_text("payload-1")

    threading.Thread(target=fire, daemon=True).start()
    dag = combine.step(workflow.wait_for_event(FileEvent, str(flag)))
    out = workflow.run(dag, workflow_id="wf-evt", storage=str(tmp_path))
    assert out == "got:payload-1"
    # Resume: event checkpoint short-circuits (file removed → would hang
    # if re-awaited).
    flag.unlink()
    out2 = workflow.run(dag, workflow_id="wf-evt", storage=str(tmp_path))
    assert out2 == "got:payload-1"


def test_workflow_continuation_sibling_ids(ray_start_regular, tmp_path):
    """Continuation sub-steps are id-scoped under their parent, so a
    sibling step with the same name keeps its own checkpoint on re-run."""
    from ray_tpu import workflow

    @workflow.step
    def inner(x):
        return x * 10

    @workflow.step
    def outer():
        return inner.step(1)  # continuation uses the same step name

    @workflow.step
    def add(a, b):
        return a + b

    dag = add.step(outer.step(), inner.step(5))
    assert workflow.run(dag, workflow_id="wf-sib",
                        storage=str(tmp_path)) == 60
    # Re-run (fully checkpointed): ids must map exactly as before.
    assert workflow.run(dag, workflow_id="wf-sib",
                        storage=str(tmp_path)) == 60


def test_workflow_continuation_catch_exceptions(ray_start_regular, tmp_path):
    """catch_exceptions covers failures inside a returned continuation."""
    from ray_tpu import workflow

    @workflow.step
    def boom():
        raise ValueError("continuation bang")

    @workflow.step(catch_exceptions=True)
    def outer():
        return boom.step()

    value, err = workflow.run(outer.step(), workflow_id="wf-catch",
                              storage=str(tmp_path))
    assert value is None
    assert "continuation bang" in str(err)
