"""graftlint gate + rule fixtures (tier-1).

Two jobs:

1. The GATE: `ray_tpu/` must lint clean against the checked-in
   baseline. A new raw create_task, a blocking sleep on a daemon loop,
   or an unvalidated `payload[...]` in a handler fails this test — the
   bug classes hand-fixed in PRs 1-4 stay un-reintroducible.

2. Rule unit coverage: every rule gets a positive fixture (violation
   detected), a negative fixture (compliant code passes), and a
   suppression fixture (`# graftlint: disable=Rn` works). R2/R3 found
   zero violations on the current tree, so without fixtures nothing
   would prove they fire at all.

Fixtures are linted in-memory via lint_source(); `filename` (or the
`# graftlint: daemon-module` marker) makes a snippet count as a daemon
module for R2.
"""

import json
import os
import subprocess
import sys

import pytest

from ray_tpu._private.lint import (ALL_PROGRAM_RULES, ALL_RULES,
                                   DEFAULT_BASELINE_PATH, WIRE_EXTERNAL,
                                   counts_by_rule_path, generate_contract,
                                   lint_source, lint_sources, load_baseline,
                                   regressions, run_lint)

import ray_tpu

PKG_DIR = ray_tpu.__path__[0]
REPO_ROOT = os.path.dirname(PKG_DIR)

DAEMON_NAME = "ray_tpu/_private/raylet.py"  # impersonate a daemon module


def rules_of(report):
    return [v.rule for v in report.violations]


# ---------------------------------------------------------------------------
# The gate: the real tree must be clean modulo the checked-in baseline.
# ---------------------------------------------------------------------------


def test_tree_lints_clean_against_baseline():
    report = run_lint([PKG_DIR])
    assert not report.parse_errors, report.parse_errors
    new = regressions(report.violations, load_baseline())
    assert not new, (
        "graftlint regressions (run `python -m ray_tpu._private.lint "
        "ray_tpu/` for details):\n"
        + "\n".join(v.format() for v in new))


def test_daemon_modules_have_zero_r1_baseline():
    """The burn-down is done: no daemon module may carry R1 debt."""
    baseline = load_baseline()
    r1 = baseline.get("R1", {})
    daemon_entries = {p: n for p, n in r1.items() if "_private" in p}
    assert not daemon_entries, daemon_entries


def test_cli_exits_zero_on_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu._private.lint", PKG_DIR],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# R1: raw spawns
# ---------------------------------------------------------------------------


R1_BAD = """
import asyncio

async def main():
    asyncio.create_task(work())
    t = asyncio.ensure_future(work())
"""

R1_GOOD = """
from ray_tpu._private.common import supervised_task

async def main():
    supervised_task(work(), name="work")
"""


def test_r1_flags_raw_spawns():
    assert rules_of(lint_source(R1_BAD)) == ["R1", "R1"]


def test_r1_passes_supervised():
    assert rules_of(lint_source(R1_GOOD)) == []


def test_r1_suppression():
    src = R1_BAD.replace("asyncio.create_task(work())",
                         "asyncio.create_task(work())  # graftlint: disable=R1")
    report = lint_source(src)
    assert rules_of(report) == ["R1"]  # only the unsuppressed ensure_future
    assert report.suppressed == 1


def test_r1_comment_line_covers_next_line():
    src = (
        "import asyncio\n"
        "async def main():\n"
        "    # graftlint: disable=R1\n"
        "    asyncio.create_task(work())\n"
    )
    report = lint_source(src)
    assert rules_of(report) == []
    assert report.suppressed == 1


# ---------------------------------------------------------------------------
# R2: blocking calls on daemon loops
# ---------------------------------------------------------------------------


R2_BAD = """
import time
import subprocess as sp
from time import sleep

async def handle_lease(self, conn, payload):
    time.sleep(1)
    sp.run(["ls"])
    sleep(0.1)
"""

R2_GOOD = """
import asyncio
import time

async def handle_lease(self, conn, payload):
    await asyncio.sleep(1)

def sync_helper():
    time.sleep(1)  # fine: not on the event loop
"""


def test_r2_flags_blocking_in_daemon_async():
    report = lint_source(R2_BAD, filename=DAEMON_NAME)
    assert rules_of(report) == ["R2", "R2", "R2"]


def test_r2_resolves_import_aliases():
    msgs = [v.message for v in lint_source(R2_BAD, filename=DAEMON_NAME).violations]
    assert any("subprocess.run" in m for m in msgs)
    assert any("time.sleep" in m for m in msgs)


def test_r2_ignores_non_daemon_modules():
    assert rules_of(lint_source(R2_BAD, filename="ray_tpu/util/misc.py")) == []


def test_r2_daemon_marker_comment():
    src = "# graftlint: daemon-module\n" + R2_BAD
    assert "R2" in rules_of(lint_source(src, filename="ray_tpu/util/misc.py"))


def test_r2_passes_async_equivalents():
    assert rules_of(lint_source(R2_GOOD, filename=DAEMON_NAME)) == []


def test_r2_sync_scope_inside_async_module_ok():
    # A nested sync def (executor target) may block.
    src = (
        "import time\n"
        "async def handle_x(self, conn, payload):\n"
        "    def gather():\n"
        "        time.sleep(1)\n"
        "    return gather\n"
    )
    assert rules_of(lint_source(src, filename=DAEMON_NAME)) == []


# ---------------------------------------------------------------------------
# R3: shared-container iteration across await
# ---------------------------------------------------------------------------


R3_BAD = """
class Raylet:
    async def reap(self):
        for wid, w in self._workers.items():
            await w.close()
"""

R3_GOOD = """
class Raylet:
    async def reap(self):
        for wid, w in list(self._workers.items()):
            await w.close()

    async def no_await(self):
        for w in self._workers:
            w.touch()
"""


def test_r3_flags_unsnapshotted_iteration():
    report = lint_source(R3_BAD)
    assert rules_of(report) == ["R3"]
    assert "self._workers.items()" in report.violations[0].message


def test_r3_passes_snapshot_and_awaitless():
    assert rules_of(lint_source(R3_GOOD)) == []


def test_r3_subscripted_container():
    src = (
        "class S:\n"
        "    async def run(self, k):\n"
        "        for item in self._queues[k]:\n"
        "            await item.go()\n"
    )
    assert rules_of(lint_source(src)) == ["R3"]


def test_r3_nested_sync_def_await_not_counted():
    src = (
        "class S:\n"
        "    async def run(self):\n"
        "        for item in self._queues:\n"
        "            async def later():\n"
        "                await item.go()\n"
        "            register(later)\n"
    )
    assert rules_of(lint_source(src)) == []


def test_r3_suppression():
    src = R3_BAD.replace(
        "for wid, w in self._workers.items():",
        "for wid, w in self._workers.items():  # graftlint: disable=R3")
    report = lint_source(src)
    assert rules_of(report) == []
    assert report.suppressed == 1


# ---------------------------------------------------------------------------
# R4: swallowed exceptions in handlers
# ---------------------------------------------------------------------------


R4_BAD = """
class Gcs:
    async def handle_drain_node(self, conn, payload):
        for node in list(self.nodes):
            try:
                await node.evacuate()
            except Exception:
                continue
        try:
            await self.publish()
        except Exception:
            pass
"""

R4_GOOD = """
import logging
logger = logging.getLogger(__name__)

class Gcs:
    async def handle_drain_node(self, conn, payload):
        try:
            await self.publish()
        except Exception:
            logger.warning("publish failed", exc_info=True)
        try:
            await self.touch()
        except ConnectionResetError:
            pass  # narrow except is allowed

    async def not_a_handler(self):
        try:
            await self.publish()
        except Exception:
            pass  # outside handle_*: R4 does not apply
"""


def test_r4_flags_silent_broad_excepts():
    assert rules_of(lint_source(R4_BAD)) == ["R4", "R4"]


def test_r4_passes_logged_narrow_and_non_handler():
    assert rules_of(lint_source(R4_GOOD)) == []


def test_r4_bare_except():
    src = (
        "async def handle_x(self, conn, payload):\n"
        "    try:\n"
        "        await go()\n"
        "    except:\n"
        "        pass\n"
    )
    assert rules_of(lint_source(src)) == ["R4"]


def test_r4_suppression():
    src = R4_BAD.replace("except Exception:\n                continue",
                         "except Exception:  # graftlint: disable=R4\n"
                         "                continue")
    assert rules_of(lint_source(src)) == ["R4"]  # the `pass` one remains


# ---------------------------------------------------------------------------
# R5: unvalidated payload access in handlers
# ---------------------------------------------------------------------------


R5_BAD = """
class Gcs:
    async def handle_kv_put(self, conn, payload):
        self.kv[payload["key"]] = payload["value"]
        return {"ok": True}
"""

R5_GOOD = """
from ray_tpu._private.common import require_fields

class Gcs:
    async def handle_kv_put(self, conn, payload):
        require_fields(payload, "key", "value", method="handle_kv_put")
        self.kv[payload["key"]] = payload["value"]
        return {"ok": True}

    async def handle_kv_get(self, conn, payload):
        if "key" not in payload:
            return {"error": "Malformed"}
        return {"value": self.kv.get(payload["key"])}

    async def handle_stats(self, conn, payload):
        return {"entries": payload.get("entries")}
"""


def test_r5_flags_unvalidated_subscripts():
    report = lint_source(R5_BAD)
    assert rules_of(report) == ["R5", "R5"]
    keys = {v.message.split("'")[1] for v in report.violations}
    assert keys == {"key", "value"}


def test_r5_passes_require_fields_membership_and_get():
    assert rules_of(lint_source(R5_GOOD)) == []


def test_r5_branch_local_require_fields_counts():
    # The validated-set is function-wide: a branch-local require_fields
    # (handle_repin's conditional routes) satisfies the rule.
    src = (
        "async def handle_repin(self, conn, payload):\n"
        "    if payload.get('route') == 'collective':\n"
        "        require_fields(payload, 'tags', method='handle_repin')\n"
        "        return payload['tags']\n"
        "    return None\n"
    )
    assert rules_of(lint_source(src)) == []


def test_r5_non_handler_free_to_subscript():
    src = (
        "async def apply(self, payload):\n"
        "    return payload['key']\n"
    )
    assert rules_of(lint_source(src)) == []


def test_r5_suppression():
    src = R5_BAD.replace(
        'self.kv[payload["key"]] = payload["value"]',
        'self.kv[payload["key"]] = payload["value"]  # graftlint: disable=R5')
    report = lint_source(src)
    assert rules_of(report) == []
    assert report.suppressed == 2


# ---------------------------------------------------------------------------
# R6: ad-hoc connection management outside the session layer
# ---------------------------------------------------------------------------


R6_BAD = """
from ray_tpu._private import rpc

async def attach(host, port):
    conn = await rpc.connect(host, port)
    conn2 = await rpc.connect_retry(host, port)
    try:
        await conn.call("Ping", {})
    except rpc.ConnectionLost:
        pass
"""

R6_GOOD = """
import logging
from ray_tpu._private import rpc

logger = logging.getLogger(__name__)

async def attach(host, port):
    conn = await rpc.dial(host, port)
    sess = await rpc.connect_session(host, port, name="x")
    try:
        await conn.call("Ping", {})
    except rpc.ConnectionLost:
        logger.warning("peer died; treating as node death")
        raise

def tcp(sock, addr):
    sock.connect(addr)  # not rpc.connect: out of scope
"""


def test_r6_flags_raw_connects_and_silent_catch():
    assert rules_of(lint_source(R6_BAD)) == ["R6", "R6", "R6"]


def test_r6_alias_aware():
    src = (
        "from ray_tpu._private import rpc as _r\n"
        "from ray_tpu._private.rpc import connect_retry\n"
        "async def go(h, p):\n"
        "    await _r.connect(h, p)\n"
        "    await connect_retry(h, p)\n"
    )
    assert rules_of(lint_source(src)) == ["R6", "R6"]


def test_r6_session_layer_exempt():
    assert rules_of(lint_source(
        R6_BAD, filename="ray_tpu/_private/rpc.py")) == []
    assert rules_of(lint_source(
        R6_BAD, filename="ray_tpu/_private/fast_rpc.py")) == []


def test_r6_tuple_catch_with_pass():
    src = (
        "import asyncio\n"
        "from ray_tpu._private import rpc\n"
        "async def beat(conn):\n"
        "    try:\n"
        "        await conn.call('Heartbeat', {})\n"
        "    except (rpc.ConnectionLost, asyncio.TimeoutError):\n"
        "        pass\n"
    )
    assert rules_of(lint_source(src)) == ["R6"]


def test_r6_passes_dial_session_and_handled_catch():
    assert rules_of(lint_source(R6_GOOD)) == []


def test_r6_suppression():
    src = R6_BAD.replace(
        "conn = await rpc.connect(host, port)",
        "conn = await rpc.connect(host, port)  # graftlint: disable=R6")
    report = lint_source(src)
    assert rules_of(report) == ["R6", "R6"]
    assert report.suppressed == 1


# ---------------------------------------------------------------------------
# Baseline ratchet
# ---------------------------------------------------------------------------


def test_baseline_is_a_ratchet(tmp_path):
    """Counts above baseline are regressions; at-or-below are not."""
    report = lint_source(R1_BAD)  # two R1 violations at <fixture>.py
    counts = counts_by_rule_path(report.violations)
    assert counts == {"R1": {"<fixture>.py": 2}}

    # Exactly-baselined: no regressions.
    assert regressions(report.violations, {"R1": {"<fixture>.py": 2}}) == []
    # Over-baselined (debt paid down elsewhere): still no regressions.
    assert regressions(report.violations, {"R1": {"<fixture>.py": 5}}) == []
    # One more violation than baselined: exactly one regression, and it
    # is the LAST one (newest line) — the old debt stays allowlisted.
    new = regressions(report.violations, {"R1": {"<fixture>.py": 1}})
    assert len(new) == 1
    assert new[0].line == max(v.line for v in report.violations)
    # Unknown (rule, path): everything is a regression.
    assert len(regressions(report.violations, {})) == 2


def test_checked_in_baseline_total_only_decreases():
    """The checked-in baseline reached zero in this PR; it must never
    grow again. If a future PR must baseline NEW debt, that is exactly
    the situation this gate exists to prevent — fix the violation
    instead."""
    with open(DEFAULT_BASELINE_PATH, encoding="utf-8") as f:
        data = json.load(f)
    total = sum(n for paths in data.get("rules", {}).values()
                for n in paths.values())
    assert total == 0, (
        f"baseline grew to {total} allowlisted violations; the ratchet "
        "only turns one way")


def test_update_baseline_drops_zeroed_entries(tmp_path):
    from ray_tpu._private.lint.baseline import load_baseline as load
    from ray_tpu._private.lint.baseline import save_baseline as save

    path = str(tmp_path / "baseline.json")
    save({"R1": {"a.py": 2, "b.py": 0}, "R4": {}}, path=path)
    assert load(path) == {"R1": {"a.py": 2}}


def test_all_rules_registered():
    assert [r.id for r in ALL_RULES] == ["R1", "R2", "R3", "R4", "R5", "R6"]
    assert [r.id for r in ALL_PROGRAM_RULES] == ["WIRE", "W5"]


# ---------------------------------------------------------------------------
# W1-W4: whole-program wire contracts (graftwire)
#
# Fixtures are multi-module programs fed through lint_sources(): a
# caller module, a handler module, and a stub rpc.py carrying the
# replay registries. wires_of() filters to W-rules so R-rule noise in a
# fixture can't silently mask (or fake) a wire finding.
# ---------------------------------------------------------------------------


WIRE_RPC_STUB = """
SESSION_EXEMPT_METHODS = frozenset({"KVPut"})

REPLAY_IDEMPOTENT = {
    "KVPut": "last-write-wins",
}
"""

WIRE_HANDLER = """
from ray_tpu._private.common import require_fields

class Server:
    def _handlers(self):
        return {"GetThing": self.handle_get_thing}

    async def handle_get_thing(self, conn, payload):
        require_fields(payload, "thing_id", method="GetThing")
        return {"thing": self.things.get(payload["thing_id"])}
"""

WIRE_CALLER_GOOD = """
async def fetch(conn, tid):
    resp = await conn.call("GetThing", {"thing_id": tid})
    return resp["thing"]
"""


def wire_report(**mods):
    sources = {"ray_tpu/_private/rpc.py": WIRE_RPC_STUB}
    sources.update({name.replace("__", "/") + ".py": src
                    for name, src in mods.items()})
    return lint_sources(sources, wire=True)


def wires_of(report):
    return [(v.rule, v.path) for v in report.violations
            if v.rule.startswith("W")]


def wire_messages(report):
    return [v.message for v in report.violations if v.rule.startswith("W")]


def test_wire_clean_pair_passes():
    report = wire_report(caller=WIRE_CALLER_GOOD, server=WIRE_HANDLER)
    assert wires_of(report) == []


def test_w1_call_without_handler():
    src = WIRE_CALLER_GOOD.replace("GetThing", "GetThingy")
    report = wire_report(caller=src, server=WIRE_HANDLER)
    rules = wires_of(report)
    # the misnamed call AND the now-orphaned handler both surface
    assert ("W1", "caller.py") in rules
    assert ("W1", "server.py") in rules
    assert any("no registered handler" in m for m in wire_messages(report))


def test_w1_handler_without_caller():
    report = wire_report(server=WIRE_HANDLER)
    assert wires_of(report) == [("W1", "server.py")]
    assert "never called" in wire_messages(report)[0]


def test_w1_external_allowlist():
    assert "Ping" in WIRE_EXTERNAL  # audited: dialed by tests/operators
    src = WIRE_HANDLER.replace("GetThing", "Ping").replace(
        "handle_get_thing", "handle_ping")
    report = wire_report(server=src)
    assert wires_of(report) == []


def test_w1_suppression():
    src = WIRE_CALLER_GOOD.replace("GetThing", "GetThingy").replace(
        'await conn.call("GetThingy", {"thing_id": tid})',
        'await conn.call("GetThingy", {"thing_id": tid})'
        '  # graftlint: disable=W1')
    report = wire_report(caller=src)
    assert wires_of(report) == []
    assert report.suppressed_by_rule.get("W1") == 1


def test_w2_required_field_never_sent():
    src = WIRE_CALLER_GOOD.replace('{"thing_id": tid}', '{}')
    report = wire_report(caller=src, server=WIRE_HANDLER)
    assert wires_of(report) == [("W2", "caller.py")]
    assert "omits required field 'thing_id'" in wire_messages(report)[0]


def test_w2_sent_field_never_read():
    src = WIRE_CALLER_GOOD.replace(
        '{"thing_id": tid}', '{"thing_id": tid, "thingg_id": tid}')
    report = wire_report(caller=src, server=WIRE_HANDLER)
    assert wires_of(report) == [("W2", "caller.py")]
    assert "'thingg_id'" in wire_messages(report)[0]
    assert "no handler ever reads it" in wire_messages(report)[0]


def test_w2_opaque_payload_not_judged():
    src = """
async def fetch(conn, req):
    resp = await conn.call("GetThing", req)
    return resp["thing"]
"""
    report = wire_report(caller=src, server=WIRE_HANDLER)
    assert wires_of(report) == []


def test_w2_session_stamp_keys_exempt():
    src = WIRE_CALLER_GOOD.replace(
        '{"thing_id": tid}', '{"thing_id": tid, "_session": s, "_rseq": 1}')
    report = wire_report(caller=src, server=WIRE_HANDLER)
    assert wires_of(report) == []


def test_w3_reply_field_never_produced():
    src = WIRE_CALLER_GOOD.replace('resp["thing"]', 'resp["things"]')
    report = wire_report(caller=src, server=WIRE_HANDLER)
    assert wires_of(report) == [("W3", "caller.py")]
    assert "no handler return path produces" in wire_messages(report)[0]


def test_w3_any_return_path_counts():
    handler = WIRE_HANDLER.replace(
        'return {"thing": self.things.get(payload["thing_id"])}',
        'if payload.get("fast"):\n'
        '            return {"thing": None}\n'
        '        return {"thing": 1, "slow": True}')
    src = WIRE_CALLER_GOOD.replace('resp["thing"]', 'resp["slow"]')
    report = wire_report(caller=src, server=handler)
    assert wires_of(report) == []


def test_w4_exempt_without_audit():
    stub = WIRE_RPC_STUB.replace('frozenset({"KVPut"})',
                                 'frozenset({"KVPut", "KVZap"})')
    report = lint_sources({"ray_tpu/_private/rpc.py": stub}, wire=True)
    assert wires_of(report) == [("W4", "ray_tpu/_private/rpc.py")]
    assert "'KVZap'" in wire_messages(report)[0]
    assert "no audited justification" in wire_messages(report)[0]


def test_w4_stale_audit_entry():
    stub = WIRE_RPC_STUB.replace(
        '"KVPut": "last-write-wins",',
        '"KVPut": "last-write-wins",\n    "Retired": "was exempt once",')
    report = lint_sources({"ray_tpu/_private/rpc.py": stub}, wire=True)
    assert wires_of(report) == [("W4", "ray_tpu/_private/rpc.py")]
    assert "stale REPLAY_IDEMPOTENT entry 'Retired'" in \
        wire_messages(report)[0]


def test_w4_empty_justification():
    stub = WIRE_RPC_STUB.replace('"last-write-wins"', '""')
    report = lint_sources({"ray_tpu/_private/rpc.py": stub}, wire=True)
    assert wires_of(report) == [("W4", "ray_tpu/_private/rpc.py")]
    assert "empty" in wire_messages(report)[0]


def test_w4_mutating_method_with_unstampable_payload():
    registry = """
class Gcs:
    _MUTATING = {
        "AddThing": ("things",),
    }
"""
    caller = """
async def add(conn, tid):
    await conn.call("AddThing", [tid])
"""
    handler = WIRE_HANDLER.replace("GetThing", "AddThing").replace(
        "handle_get_thing", "handle_add_thing")
    report = wire_report(caller=caller, server=handler, registry=registry)
    w4 = [(r, p) for r, p in wires_of(report) if r == "W4"]
    assert w4 == [("W4", "caller.py")]
    assert any("cannot stamp" in m for m in wire_messages(report))


# ---------------------------------------------------------------------------
# W5: pjit sharding handoff
# ---------------------------------------------------------------------------


W5_BAD = """
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

def build(mesh, step, apply_fn, x):
    f = jax.jit(step, out_shardings=NamedSharding(mesh, P("dp")))
    g = jax.jit(apply_fn, in_shardings=NamedSharding(mesh, P()))
    y = f(x)
    z = g(y)
    return z
"""

W5_NAME = "ray_tpu/train/step.py"


def test_w5_flags_provable_handoff_mismatch():
    report = lint_sources({W5_NAME: W5_BAD}, wire=True)
    assert [v.rule for v in report.violations] == ["W5"]
    assert "silently reshard" in report.violations[0].message


def test_w5_matching_handoff_passes():
    src = W5_BAD.replace('P("dp")', 'P()')
    report = lint_sources({W5_NAME: src}, wire=True)
    assert [v.rule for v in report.violations] == []


def test_w5_unprovable_stays_silent():
    # mesh vs mesh2 differ by a Name: a guess, not a proof — no finding.
    src = W5_BAD.replace(
        'in_shardings=NamedSharding(mesh, P())',
        'in_shardings=NamedSharding(mesh2, P())')
    report = lint_sources({W5_NAME: src}, wire=True)
    assert [v.rule for v in report.violations] == []


def test_w5_scoped_to_sharded_modules():
    report = lint_sources({"ray_tpu/util/misc.py": W5_BAD}, wire=True)
    assert [v.rule for v in report.violations] == []


def test_w5_suppression():
    src = W5_BAD.replace("z = g(y)", "z = g(y)  # graftlint: disable=W5")
    report = lint_sources({W5_NAME: src}, wire=True)
    assert [v.rule for v in report.violations] == []
    assert report.suppressed_by_rule.get("W5") == 1


# ---------------------------------------------------------------------------
# The wire gate on the real tree + the generated contract
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tree_report():
    return run_lint([PKG_DIR])


@pytest.fixture(scope="module")
def tree_contract():
    return generate_contract([PKG_DIR])


def test_tree_wire_clean_with_zero_suppressions(tree_report):
    """The wire baseline SHIPS EMPTY and nothing is suppressed: every
    W-finding on the live tree was a real fix or an audited
    WIRE_EXTERNAL entry, not an allowlist line."""
    wire_v = [v for v in tree_report.violations if v.rule.startswith("W")]
    assert not wire_v, "\n".join(v.format() for v in wire_v)
    w_suppressed = {r: n for r, n in tree_report.suppressed_by_rule.items()
                    if r.startswith("W")}
    assert not w_suppressed, (
        f"wire findings are being suppressed inline ({w_suppressed}); "
        "fix the drift or audit it in wire.WIRE_EXTERNAL / "
        "rpc.REPLAY_IDEMPOTENT instead")


def test_parallel_jobs_equivalent(tree_report):
    par = run_lint([PKG_DIR], jobs=2)
    assert [v.format() for v in par.violations] == \
        [v.format() for v in tree_report.violations]
    assert par.suppressed == tree_report.suppressed
    assert par.files_checked == tree_report.files_checked


def test_contract_round_trips_and_matches_registries(tree_contract):
    from ray_tpu._private import rpc
    from ray_tpu._private.gcs import GcsServer

    blob = json.dumps(tree_contract, sort_keys=True)
    assert json.loads(blob) == tree_contract

    methods = tree_contract["methods"]
    # Every contract entry is grounded: a registered handler, an
    # in-tree caller, or an audited external endpoint.
    for name, m in methods.items():
        assert m["handlers"] or m["callers"] or m.get("external"), name
    # The replay column mirrors the RUNTIME registries exactly.
    for method in rpc.SESSION_EXEMPT_METHODS:
        assert methods[method]["replay"].startswith("idempotent-exempt"), \
            method
    assert set(rpc.REPLAY_IDEMPOTENT) == set(rpc.SESSION_EXEMPT_METHODS)
    # Every side-effecting GCS method is marked mutating, and is either
    # reply-cached or carries an audited idempotency justification.
    for method in GcsServer._MUTATING:
        assert methods[method]["mutating"] is True, method
        if method in rpc.SESSION_EXEMPT_METHODS:
            assert methods[method]["replay_justification"].strip(), method
        else:
            assert methods[method]["replay"] == "cached", method


def test_wire_contract_docs_are_fresh(tmp_path):
    """Regenerate-and-diff: docs/wire_contract.{json,md} must match what
    the tree produces NOW. If this fails, run
    `python -m ray_tpu._private.lint --emit-contract docs/`."""
    from ray_tpu._private.lint.__main__ import emit_contract

    emit_contract([PKG_DIR], str(tmp_path))
    for name in ("wire_contract.json", "wire_contract.md"):
        with open(os.path.join(REPO_ROOT, "docs", name),
                  encoding="utf-8") as f:
            checked_in = f.read()
        with open(tmp_path / name, encoding="utf-8") as f:
            fresh = f.read()
        assert fresh == checked_in, (
            f"docs/{name} is stale — regenerate with "
            "`python -m ray_tpu._private.lint --emit-contract docs/` "
            "(or `make contract`)")


def test_contract_records_fixed_drift(tree_contract):
    """Regression pins for the wire defects this analyzer flushed out:
    the dead endpoints stay deleted and the KillActorWorker payload
    stays minimal. If one of these methods reappears, it needs BOTH a
    caller and a handler to pass the W1 gate anyway — this test just
    names the history."""
    methods = tree_contract["methods"]
    for dead in ("PushTask", "CancelTask", "Exit", "ObjectInfo",
                 "GetNodeInfo", "ReportWorkerDeath"):
        assert dead not in methods, f"dead endpoint {dead!r} resurrected"
    kaw = methods["KillActorWorker"]
    assert kaw["request_fields"] == ["actor_id"]
    assert kaw["required_fields"] == ["actor_id"]
    # The three endpoints this PR wired callers for are live again.
    for wired in ("NodeDebugTasks", "NotifyNodeDead", "ClientGcsCall"):
        assert methods[wired]["callers"] >= 1, wired
        assert methods[wired]["handlers"], wired


# ---------------------------------------------------------------------------
# Engine details that correctness of the gate depends on
# ---------------------------------------------------------------------------


def test_parse_error_is_reported_not_raised():
    report = lint_source("def broken(:\n")
    assert report.parse_errors
    assert report.files_checked == 0


def test_require_fields_runtime_behavior():
    from ray_tpu._private.common import MalformedError, require_fields

    ok = {"key": "k", "value": b"v"}
    assert require_fields(ok, "key", "value", method="KvPut") is ok
    with pytest.raises(MalformedError, match="Malformed request in KvPut"):
        require_fields({"key": "k"}, "key", "value", method="KvPut")
    with pytest.raises(MalformedError, match="payload must be a map"):
        require_fields(["not", "a", "map"], "key", method="KvPut")
