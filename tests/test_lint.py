"""graftlint gate + rule fixtures (tier-1).

Two jobs:

1. The GATE: `ray_tpu/` must lint clean against the checked-in
   baseline. A new raw create_task, a blocking sleep on a daemon loop,
   or an unvalidated `payload[...]` in a handler fails this test — the
   bug classes hand-fixed in PRs 1-4 stay un-reintroducible.

2. Rule unit coverage: every rule gets a positive fixture (violation
   detected), a negative fixture (compliant code passes), and a
   suppression fixture (`# graftlint: disable=Rn` works). R2/R3 found
   zero violations on the current tree, so without fixtures nothing
   would prove they fire at all.

Fixtures are linted in-memory via lint_source(); `filename` (or the
`# graftlint: daemon-module` marker) makes a snippet count as a daemon
module for R2.
"""

import json
import subprocess
import sys

import pytest

from ray_tpu._private.lint import (ALL_RULES, DEFAULT_BASELINE_PATH,
                                   counts_by_rule_path, lint_source,
                                   load_baseline, regressions, run_lint)

import ray_tpu

PKG_DIR = ray_tpu.__path__[0]

DAEMON_NAME = "ray_tpu/_private/raylet.py"  # impersonate a daemon module


def rules_of(report):
    return [v.rule for v in report.violations]


# ---------------------------------------------------------------------------
# The gate: the real tree must be clean modulo the checked-in baseline.
# ---------------------------------------------------------------------------


def test_tree_lints_clean_against_baseline():
    report = run_lint([PKG_DIR])
    assert not report.parse_errors, report.parse_errors
    new = regressions(report.violations, load_baseline())
    assert not new, (
        "graftlint regressions (run `python -m ray_tpu._private.lint "
        "ray_tpu/` for details):\n"
        + "\n".join(v.format() for v in new))


def test_daemon_modules_have_zero_r1_baseline():
    """The burn-down is done: no daemon module may carry R1 debt."""
    baseline = load_baseline()
    r1 = baseline.get("R1", {})
    daemon_entries = {p: n for p, n in r1.items() if "_private" in p}
    assert not daemon_entries, daemon_entries


def test_cli_exits_zero_on_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu._private.lint", PKG_DIR],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# R1: raw spawns
# ---------------------------------------------------------------------------


R1_BAD = """
import asyncio

async def main():
    asyncio.create_task(work())
    t = asyncio.ensure_future(work())
"""

R1_GOOD = """
from ray_tpu._private.common import supervised_task

async def main():
    supervised_task(work(), name="work")
"""


def test_r1_flags_raw_spawns():
    assert rules_of(lint_source(R1_BAD)) == ["R1", "R1"]


def test_r1_passes_supervised():
    assert rules_of(lint_source(R1_GOOD)) == []


def test_r1_suppression():
    src = R1_BAD.replace("asyncio.create_task(work())",
                         "asyncio.create_task(work())  # graftlint: disable=R1")
    report = lint_source(src)
    assert rules_of(report) == ["R1"]  # only the unsuppressed ensure_future
    assert report.suppressed == 1


def test_r1_comment_line_covers_next_line():
    src = (
        "import asyncio\n"
        "async def main():\n"
        "    # graftlint: disable=R1\n"
        "    asyncio.create_task(work())\n"
    )
    report = lint_source(src)
    assert rules_of(report) == []
    assert report.suppressed == 1


# ---------------------------------------------------------------------------
# R2: blocking calls on daemon loops
# ---------------------------------------------------------------------------


R2_BAD = """
import time
import subprocess as sp
from time import sleep

async def handle_lease(self, conn, payload):
    time.sleep(1)
    sp.run(["ls"])
    sleep(0.1)
"""

R2_GOOD = """
import asyncio
import time

async def handle_lease(self, conn, payload):
    await asyncio.sleep(1)

def sync_helper():
    time.sleep(1)  # fine: not on the event loop
"""


def test_r2_flags_blocking_in_daemon_async():
    report = lint_source(R2_BAD, filename=DAEMON_NAME)
    assert rules_of(report) == ["R2", "R2", "R2"]


def test_r2_resolves_import_aliases():
    msgs = [v.message for v in lint_source(R2_BAD, filename=DAEMON_NAME).violations]
    assert any("subprocess.run" in m for m in msgs)
    assert any("time.sleep" in m for m in msgs)


def test_r2_ignores_non_daemon_modules():
    assert rules_of(lint_source(R2_BAD, filename="ray_tpu/util/misc.py")) == []


def test_r2_daemon_marker_comment():
    src = "# graftlint: daemon-module\n" + R2_BAD
    assert "R2" in rules_of(lint_source(src, filename="ray_tpu/util/misc.py"))


def test_r2_passes_async_equivalents():
    assert rules_of(lint_source(R2_GOOD, filename=DAEMON_NAME)) == []


def test_r2_sync_scope_inside_async_module_ok():
    # A nested sync def (executor target) may block.
    src = (
        "import time\n"
        "async def handle_x(self, conn, payload):\n"
        "    def gather():\n"
        "        time.sleep(1)\n"
        "    return gather\n"
    )
    assert rules_of(lint_source(src, filename=DAEMON_NAME)) == []


# ---------------------------------------------------------------------------
# R3: shared-container iteration across await
# ---------------------------------------------------------------------------


R3_BAD = """
class Raylet:
    async def reap(self):
        for wid, w in self._workers.items():
            await w.close()
"""

R3_GOOD = """
class Raylet:
    async def reap(self):
        for wid, w in list(self._workers.items()):
            await w.close()

    async def no_await(self):
        for w in self._workers:
            w.touch()
"""


def test_r3_flags_unsnapshotted_iteration():
    report = lint_source(R3_BAD)
    assert rules_of(report) == ["R3"]
    assert "self._workers.items()" in report.violations[0].message


def test_r3_passes_snapshot_and_awaitless():
    assert rules_of(lint_source(R3_GOOD)) == []


def test_r3_subscripted_container():
    src = (
        "class S:\n"
        "    async def run(self, k):\n"
        "        for item in self._queues[k]:\n"
        "            await item.go()\n"
    )
    assert rules_of(lint_source(src)) == ["R3"]


def test_r3_nested_sync_def_await_not_counted():
    src = (
        "class S:\n"
        "    async def run(self):\n"
        "        for item in self._queues:\n"
        "            async def later():\n"
        "                await item.go()\n"
        "            register(later)\n"
    )
    assert rules_of(lint_source(src)) == []


def test_r3_suppression():
    src = R3_BAD.replace(
        "for wid, w in self._workers.items():",
        "for wid, w in self._workers.items():  # graftlint: disable=R3")
    report = lint_source(src)
    assert rules_of(report) == []
    assert report.suppressed == 1


# ---------------------------------------------------------------------------
# R4: swallowed exceptions in handlers
# ---------------------------------------------------------------------------


R4_BAD = """
class Gcs:
    async def handle_drain_node(self, conn, payload):
        for node in list(self.nodes):
            try:
                await node.evacuate()
            except Exception:
                continue
        try:
            await self.publish()
        except Exception:
            pass
"""

R4_GOOD = """
import logging
logger = logging.getLogger(__name__)

class Gcs:
    async def handle_drain_node(self, conn, payload):
        try:
            await self.publish()
        except Exception:
            logger.warning("publish failed", exc_info=True)
        try:
            await self.touch()
        except ConnectionResetError:
            pass  # narrow except is allowed

    async def not_a_handler(self):
        try:
            await self.publish()
        except Exception:
            pass  # outside handle_*: R4 does not apply
"""


def test_r4_flags_silent_broad_excepts():
    assert rules_of(lint_source(R4_BAD)) == ["R4", "R4"]


def test_r4_passes_logged_narrow_and_non_handler():
    assert rules_of(lint_source(R4_GOOD)) == []


def test_r4_bare_except():
    src = (
        "async def handle_x(self, conn, payload):\n"
        "    try:\n"
        "        await go()\n"
        "    except:\n"
        "        pass\n"
    )
    assert rules_of(lint_source(src)) == ["R4"]


def test_r4_suppression():
    src = R4_BAD.replace("except Exception:\n                continue",
                         "except Exception:  # graftlint: disable=R4\n"
                         "                continue")
    assert rules_of(lint_source(src)) == ["R4"]  # the `pass` one remains


# ---------------------------------------------------------------------------
# R5: unvalidated payload access in handlers
# ---------------------------------------------------------------------------


R5_BAD = """
class Gcs:
    async def handle_kv_put(self, conn, payload):
        self.kv[payload["key"]] = payload["value"]
        return {"ok": True}
"""

R5_GOOD = """
from ray_tpu._private.common import require_fields

class Gcs:
    async def handle_kv_put(self, conn, payload):
        require_fields(payload, "key", "value", method="handle_kv_put")
        self.kv[payload["key"]] = payload["value"]
        return {"ok": True}

    async def handle_kv_get(self, conn, payload):
        if "key" not in payload:
            return {"error": "Malformed"}
        return {"value": self.kv.get(payload["key"])}

    async def handle_stats(self, conn, payload):
        return {"entries": payload.get("entries")}
"""


def test_r5_flags_unvalidated_subscripts():
    report = lint_source(R5_BAD)
    assert rules_of(report) == ["R5", "R5"]
    keys = {v.message.split("'")[1] for v in report.violations}
    assert keys == {"key", "value"}


def test_r5_passes_require_fields_membership_and_get():
    assert rules_of(lint_source(R5_GOOD)) == []


def test_r5_branch_local_require_fields_counts():
    # The validated-set is function-wide: a branch-local require_fields
    # (handle_repin's conditional routes) satisfies the rule.
    src = (
        "async def handle_repin(self, conn, payload):\n"
        "    if payload.get('route') == 'collective':\n"
        "        require_fields(payload, 'tags', method='handle_repin')\n"
        "        return payload['tags']\n"
        "    return None\n"
    )
    assert rules_of(lint_source(src)) == []


def test_r5_non_handler_free_to_subscript():
    src = (
        "async def apply(self, payload):\n"
        "    return payload['key']\n"
    )
    assert rules_of(lint_source(src)) == []


def test_r5_suppression():
    src = R5_BAD.replace(
        'self.kv[payload["key"]] = payload["value"]',
        'self.kv[payload["key"]] = payload["value"]  # graftlint: disable=R5')
    report = lint_source(src)
    assert rules_of(report) == []
    assert report.suppressed == 2


# ---------------------------------------------------------------------------
# R6: ad-hoc connection management outside the session layer
# ---------------------------------------------------------------------------


R6_BAD = """
from ray_tpu._private import rpc

async def attach(host, port):
    conn = await rpc.connect(host, port)
    conn2 = await rpc.connect_retry(host, port)
    try:
        await conn.call("Ping", {})
    except rpc.ConnectionLost:
        pass
"""

R6_GOOD = """
import logging
from ray_tpu._private import rpc

logger = logging.getLogger(__name__)

async def attach(host, port):
    conn = await rpc.dial(host, port)
    sess = await rpc.connect_session(host, port, name="x")
    try:
        await conn.call("Ping", {})
    except rpc.ConnectionLost:
        logger.warning("peer died; treating as node death")
        raise

def tcp(sock, addr):
    sock.connect(addr)  # not rpc.connect: out of scope
"""


def test_r6_flags_raw_connects_and_silent_catch():
    assert rules_of(lint_source(R6_BAD)) == ["R6", "R6", "R6"]


def test_r6_alias_aware():
    src = (
        "from ray_tpu._private import rpc as _r\n"
        "from ray_tpu._private.rpc import connect_retry\n"
        "async def go(h, p):\n"
        "    await _r.connect(h, p)\n"
        "    await connect_retry(h, p)\n"
    )
    assert rules_of(lint_source(src)) == ["R6", "R6"]


def test_r6_session_layer_exempt():
    assert rules_of(lint_source(
        R6_BAD, filename="ray_tpu/_private/rpc.py")) == []
    assert rules_of(lint_source(
        R6_BAD, filename="ray_tpu/_private/fast_rpc.py")) == []


def test_r6_tuple_catch_with_pass():
    src = (
        "import asyncio\n"
        "from ray_tpu._private import rpc\n"
        "async def beat(conn):\n"
        "    try:\n"
        "        await conn.call('Heartbeat', {})\n"
        "    except (rpc.ConnectionLost, asyncio.TimeoutError):\n"
        "        pass\n"
    )
    assert rules_of(lint_source(src)) == ["R6"]


def test_r6_passes_dial_session_and_handled_catch():
    assert rules_of(lint_source(R6_GOOD)) == []


def test_r6_suppression():
    src = R6_BAD.replace(
        "conn = await rpc.connect(host, port)",
        "conn = await rpc.connect(host, port)  # graftlint: disable=R6")
    report = lint_source(src)
    assert rules_of(report) == ["R6", "R6"]
    assert report.suppressed == 1


# ---------------------------------------------------------------------------
# Baseline ratchet
# ---------------------------------------------------------------------------


def test_baseline_is_a_ratchet(tmp_path):
    """Counts above baseline are regressions; at-or-below are not."""
    report = lint_source(R1_BAD)  # two R1 violations at <fixture>.py
    counts = counts_by_rule_path(report.violations)
    assert counts == {"R1": {"<fixture>.py": 2}}

    # Exactly-baselined: no regressions.
    assert regressions(report.violations, {"R1": {"<fixture>.py": 2}}) == []
    # Over-baselined (debt paid down elsewhere): still no regressions.
    assert regressions(report.violations, {"R1": {"<fixture>.py": 5}}) == []
    # One more violation than baselined: exactly one regression, and it
    # is the LAST one (newest line) — the old debt stays allowlisted.
    new = regressions(report.violations, {"R1": {"<fixture>.py": 1}})
    assert len(new) == 1
    assert new[0].line == max(v.line for v in report.violations)
    # Unknown (rule, path): everything is a regression.
    assert len(regressions(report.violations, {})) == 2


def test_checked_in_baseline_total_only_decreases():
    """The checked-in baseline reached zero in this PR; it must never
    grow again. If a future PR must baseline NEW debt, that is exactly
    the situation this gate exists to prevent — fix the violation
    instead."""
    with open(DEFAULT_BASELINE_PATH, encoding="utf-8") as f:
        data = json.load(f)
    total = sum(n for paths in data.get("rules", {}).values()
                for n in paths.values())
    assert total == 0, (
        f"baseline grew to {total} allowlisted violations; the ratchet "
        "only turns one way")


def test_update_baseline_drops_zeroed_entries(tmp_path):
    from ray_tpu._private.lint.baseline import load_baseline as load
    from ray_tpu._private.lint.baseline import save_baseline as save

    path = str(tmp_path / "baseline.json")
    save({"R1": {"a.py": 2, "b.py": 0}, "R4": {}}, path=path)
    assert load(path) == {"R1": {"a.py": 2}}


def test_all_rules_registered():
    assert [r.id for r in ALL_RULES] == ["R1", "R2", "R3", "R4", "R5", "R6"]


# ---------------------------------------------------------------------------
# Engine details that correctness of the gate depends on
# ---------------------------------------------------------------------------


def test_parse_error_is_reported_not_raised():
    report = lint_source("def broken(:\n")
    assert report.parse_errors
    assert report.files_checked == 0


def test_require_fields_runtime_behavior():
    from ray_tpu._private.common import MalformedError, require_fields

    ok = {"key": "k", "value": b"v"}
    assert require_fields(ok, "key", "value", method="KvPut") is ok
    with pytest.raises(MalformedError, match="Malformed request in KvPut"):
        require_fields({"key": "k"}, "key", "value", method="KvPut")
    with pytest.raises(MalformedError, match="payload must be a map"):
        require_fields(["not", "a", "map"], "key", method="KvPut")
