"""Native C++ object-transfer plane tests (reference test model:
python/ray/tests/test_object_manager.py — cross-node object movement)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import native_transfer
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStoreClient


def test_server_fetch_roundtrip(tmp_path):
    """Pure native plane: two arenas, one fetch — no cluster involved."""
    src_path = str(tmp_path / "src_store")
    dst_path = str(tmp_path / "dst_store")
    src = ObjectStoreClient(src_path, create=True, size=8 << 20)
    dst = ObjectStoreClient(dst_path, create=True, size=8 << 20)
    oid = ObjectID.from_random()
    meta = b"M" * 7
    payload = np.random.default_rng(0).bytes(1 << 20)
    buf = src.create(oid, len(meta) + len(payload), len(meta))
    buf[: len(meta)] = meta
    buf[len(meta):] = payload
    src.seal(oid)

    server = native_transfer.TransferServer(src_path)
    assert server.port > 0
    try:
        rc = native_transfer.fetch(dst_path, "127.0.0.1", server.port,
                                   oid.binary())
        assert rc == 0
        got = dst.get_buffer(oid)
        assert got is not None
        got_meta, got_data = got
        assert bytes(got_meta) == meta
        assert bytes(got_data) == payload
        dst.release(oid)
        # Unknown object -> not-found code, connection stays usable.
        rc = native_transfer.fetch(dst_path, "127.0.0.1", server.port,
                                   ObjectID.from_random().binary())
        assert rc == -2
    finally:
        server.stop()
        src.close()
        dst.close()


def test_cross_node_object_pull_uses_native_plane(ray_start_cluster):
    """Objects produced on one node and consumed on another flow through
    the C++ transfer servers (every raylet advertises a transfer_port)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, resources={"remote_node": 1})
    cluster.connect()

    for n in ray_tpu.nodes():
        if n["alive"]:
            assert n.get("transfer_port", 0) >= 0  # field propagated

    @ray_tpu.remote(resources={"remote_node": 0.1})
    def produce():
        return np.arange(300_000, dtype=np.int64)  # 2.4 MB — store path

    @ray_tpu.remote(num_cpus=1)
    def consume(arr):
        return int(arr.sum())

    ref = produce.remote()
    # Consume on the head node: the argument must cross nodes.
    total = ray_tpu.get(consume.remote(ref), timeout=120)
    assert total == sum(range(300_000))
