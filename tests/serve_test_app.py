"""Bound deployment graph imported by declarative-deploy tests
(tests/test_serve_config.py) via import_path."""

from ray_tpu import serve


@serve.deployment
class Doubler:
    def __call__(self, x):
        return x * 2


@serve.deployment
class Pipeline:
    def __init__(self, doubler):
        self.doubler = doubler

    def __call__(self, payload):
        v = payload["v"] if isinstance(payload, dict) else payload
        return self.doubler.remote(v).result(timeout=30) + 1


app = Pipeline.bind(Doubler.bind())
