"""End-to-end basics: init / remote / get / put / wait.

Parity: reference python/ray/tests/test_basic.py family.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_put_get(ray_start_regular):
    ref = ray_tpu.put({"a": 1, "b": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_numpy(ray_start_regular):
    arr = np.random.rand(512, 512)  # 2MB: goes through shm
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_kwargs(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=10, c=20):
        return a + b + c

    assert ray_tpu.get(f.remote(1, c=2)) == 13


def test_many_tasks(ray_start_regular):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(50)]
    assert ray_tpu.get(refs) == [i * i for i in range(50)]


def test_task_chain_dependencies(ray_start_regular):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(9):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 10


def test_large_args_and_returns(ray_start_regular):
    @ray_tpu.remote
    def double(arr):
        return arr * 2

    arr = np.ones((1024, 1024))  # 8MB
    out = ray_tpu.get(double.remote(arr))
    np.testing.assert_array_equal(out, arr * 2)


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_exception(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(exc.TaskError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_exception_propagates_through_dependency(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("kaboom")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(exc.TaskError, match="kaboom"):
        ray_tpu.get(consume.remote(boom.remote()))


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=10)
    assert ready == [f]
    assert not_ready == [s]


def test_wait_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    ref = slow.remote()
    ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=0.5)
    assert ready == []
    assert not_ready == [ref]


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    with pytest.raises(exc.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.5)


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def child(x):
        return x * 10

    @ray_tpu.remote
    def parent():
        return ray_tpu.get(child.remote(4))

    assert ray_tpu.get(parent.remote()) == 40


def test_cluster_resources(ray_start_regular):
    res = ray_tpu.cluster_resources()
    assert res.get("CPU") == 4.0


def test_runtime_context_in_task(ray_start_regular):
    @ray_tpu.remote
    def who():
        ctx = ray_tpu.get_runtime_context()
        return ctx.node_id, ctx.worker_id

    node_id, worker_id = ray_tpu.get(who.remote())
    assert len(node_id) == 40
    assert len(worker_id) == 40


def test_nested_fanout_wider_than_cpus(ray_start_regular):
    """Nested gets release the blocked worker's CPU (reference: raylet
    blocked-worker accounting) — a fan-out wider than the CPU count must
    not deadlock the worker pool."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def leaf(i):
        return i

    @ray_tpu.remote(num_cpus=1)
    def fan(width):
        import ray_tpu as rt

        return sum(rt.get([leaf.remote(i) for i in range(width)], timeout=60))

    # ray_start_regular gives 4 CPUs; two concurrent fan() calls each
    # spawning 6 leaves need blocked-release to make progress.
    out = ray_tpu.get([fan.remote(6), fan.remote(6)], timeout=120)
    assert out == [15, 15]
