"""GCS fault tolerance: kill + restart the control plane mid-run.

Parity: reference python/ray/tests/test_gcs_fault_tolerance.py — the GCS
restarts with persisted state (Redis there, msgpack snapshot here), raylets
re-register under the same node id, live actors keep serving (actor calls
never touch the GCS), and new work schedules after recovery.
"""

import time

import pytest

import ray_tpu
from ray_tpu._private.config import Config


@pytest.fixture
def ft_cluster():
    from ray_tpu.cluster_utils import Cluster

    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.num_heartbeats_timeout = 10
    cfg.gcs_reconnect_timeout_s = 30.0
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 4}, config=cfg)
    yield cluster
    cluster.shutdown()


def test_gcs_restart_preserves_cluster(ft_cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="survivor").remote()
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
    ray_tpu.get(ray_tpu.put("kv-sentinel"))  # exercise the data plane too
    time.sleep(1.0)  # let the persistence loop snapshot the state

    node = ft_cluster._node
    node.kill_gcs()

    # Actor calls go direct worker-to-worker: they keep working with the
    # control plane DOWN (the reference's key resilience property).
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 2

    node.restart_gcs()

    # Raylet re-registers; driver reconnects; new tasks schedule.
    deadline = time.monotonic() + 30
    alive = []
    while time.monotonic() < deadline:
        try:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if alive:
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert alive, "raylet never re-registered after GCS restart"

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(41), timeout=90) == 42
    # Existing actor still reachable AND still findable by name (the actor
    # directory was persisted).
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 3
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            again = ray_tpu.get_actor("survivor")
            break
        except Exception:
            time.sleep(0.5)
    else:
        raise AssertionError("named actor lost after GCS restart")
    assert ray_tpu.get(again.inc.remote(), timeout=60) == 4


def test_gcs_restart_preserves_kv(ft_cluster):
    from ray_tpu._private.api_internal import get_core_worker

    cw = get_core_worker()
    cw._run(cw.gcs.call("KVPut", {"ns": "t", "key": b"k", "value": b"v1"}))
    time.sleep(1.0)  # snapshot interval

    node = ft_cluster._node
    node.kill_gcs()
    node.restart_gcs()

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            got = cw._run(cw.gcs.call("KVGet", {"ns": "t", "key": b"k"}))
            if got.get("value") == b"v1":
                return
        except Exception:
            pass
        time.sleep(0.5)
    raise AssertionError("KV entry lost across GCS restart")


def test_write_through_survives_immediate_kill9(ft_cluster):
    """Per-mutation durability: an acknowledged mutation must survive a
    GCS SIGKILL delivered IMMEDIATELY after the ack — no persistence-
    window sleep (reference: redis store_client gives the GCS
    write-through per mutation, store_client_kv.h). The WAL append runs
    before the RPC reply, so there is nothing left to lose."""
    from ray_tpu._private.api_internal import get_core_worker

    cw = get_core_worker()

    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return "pong"

    # Acked mutations: KVPut + named-actor registration. NO sleep after.
    cw._run(cw.gcs.call("KVPut", {"ns": "wt", "key": b"k", "value": b"v"}))
    a = Pinger.options(name="wt-actor").remote()
    del a  # handle not needed; registration was acknowledged

    node = ft_cluster._node
    node.kill_gcs()  # SIGKILL, immediately after the acks
    node.restart_gcs()

    deadline = time.monotonic() + 60
    kv_ok = actor_ok = False
    while time.monotonic() < deadline and not (kv_ok and actor_ok):
        try:
            if not kv_ok:
                got = cw._run(cw.gcs.call(
                    "KVGet", {"ns": "wt", "key": b"k"}), timeout=5)
                kv_ok = got.get("value") == b"v"
            if not actor_ok:
                # The registration was PENDING at kill time; the restarted
                # GCS must replay it and re-kick scheduling.
                h = ray_tpu.get_actor("wt-actor")
                actor_ok = ray_tpu.get(h.ping.remote(), timeout=30) == "pong"
        except Exception:
            time.sleep(0.5)
    assert kv_ok, "acknowledged KVPut lost across immediate kill -9"
    assert actor_ok, "acknowledged actor registration lost across kill -9"


def test_pg_ready_promise_survives_gcs_restart(ft_cluster):
    """pg.ready() is a GCS-pubsub-backed promise (r5): a CREATED that
    lands while the driver's GCS conn is down must still resolve — the
    reconnect handshake re-queries every armed waiter (worker.py
    _reconnect_gcs). Sequence: PG stays PENDING (infeasible), ready()
    arms, GCS dies and restarts, THEN capacity arrives and the PG
    creates — the promise must fire, not hang."""
    import threading

    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    # Infeasible until a bigger node joins: 16 CPUs on a 4-CPU head.
    pg = placement_group([{"CPU": 16.0}])
    ref = pg.ready()

    got = []
    waiter = threading.Thread(
        target=lambda: got.append(ray_tpu.get(ref, timeout=120)),
        daemon=True)
    waiter.start()
    time.sleep(1.0)
    assert not got, "PG resolved before capacity existed"

    node = ft_cluster._node
    node.kill_gcs()
    time.sleep(0.5)
    node.restart_gcs()

    # New capacity arrives AFTER the restart; the PG schedules and the
    # promise must resolve through the re-subscribed channel (or the
    # reconnect re-query), not hang forever.
    ft_cluster.add_node(num_cpus=16)
    waiter.join(timeout=90)
    assert got == [True], f"pg.ready() promise lost across GCS restart: {got}"
    remove_placement_group(pg)
