"""GCS fault tolerance: kill + restart the control plane mid-run.

Parity: reference python/ray/tests/test_gcs_fault_tolerance.py — the GCS
restarts with persisted state (Redis there, msgpack snapshot here), raylets
re-register under the same node id, live actors keep serving (actor calls
never touch the GCS), and new work schedules after recovery.
"""

import time

import pytest

import ray_tpu
from ray_tpu._private.config import Config


@pytest.fixture
def ft_cluster():
    from ray_tpu.cluster_utils import Cluster

    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.num_heartbeats_timeout = 10
    cfg.gcs_reconnect_timeout_s = 30.0
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 4}, config=cfg)
    yield cluster
    cluster.shutdown()


def test_gcs_restart_preserves_cluster(ft_cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(name="survivor").remote()
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
    ray_tpu.get(ray_tpu.put("kv-sentinel"))  # exercise the data plane too
    time.sleep(1.0)  # let the persistence loop snapshot the state

    node = ft_cluster._node
    node.kill_gcs()

    # Actor calls go direct worker-to-worker: they keep working with the
    # control plane DOWN (the reference's key resilience property).
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 2

    node.restart_gcs()

    # Raylet re-registers; driver reconnects; new tasks schedule.
    deadline = time.monotonic() + 30
    alive = []
    while time.monotonic() < deadline:
        try:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if alive:
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert alive, "raylet never re-registered after GCS restart"

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(41), timeout=90) == 42
    # Existing actor still reachable AND still findable by name (the actor
    # directory was persisted).
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 3
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            again = ray_tpu.get_actor("survivor")
            break
        except Exception:
            time.sleep(0.5)
    else:
        raise AssertionError("named actor lost after GCS restart")
    assert ray_tpu.get(again.inc.remote(), timeout=60) == 4


def test_gcs_restart_preserves_kv(ft_cluster):
    from ray_tpu._private.api_internal import get_core_worker

    cw = get_core_worker()
    cw._run(cw.gcs.call("KVPut", {"ns": "t", "key": b"k", "value": b"v1"}))
    time.sleep(1.0)  # snapshot interval

    node = ft_cluster._node
    node.kill_gcs()
    node.restart_gcs()

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            got = cw._run(cw.gcs.call("KVGet", {"ns": "t", "key": b"k"}))
            if got.get("value") == b"v1":
                return
        except Exception:
            pass
        time.sleep(0.5)
    raise AssertionError("KV entry lost across GCS restart")


def test_write_through_survives_immediate_kill9(ft_cluster):
    """Per-mutation durability: an acknowledged mutation must survive a
    GCS SIGKILL delivered IMMEDIATELY after the ack — no persistence-
    window sleep (reference: redis store_client gives the GCS
    write-through per mutation, store_client_kv.h). The WAL append runs
    before the RPC reply, so there is nothing left to lose."""
    from ray_tpu._private.api_internal import get_core_worker

    cw = get_core_worker()

    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return "pong"

    # Acked mutations: KVPut + named-actor registration. NO sleep after.
    cw._run(cw.gcs.call("KVPut", {"ns": "wt", "key": b"k", "value": b"v"}))
    a = Pinger.options(name="wt-actor").remote()
    del a  # handle not needed; registration was acknowledged

    node = ft_cluster._node
    node.kill_gcs()  # SIGKILL, immediately after the acks
    node.restart_gcs()

    deadline = time.monotonic() + 60
    kv_ok = actor_ok = False
    while time.monotonic() < deadline and not (kv_ok and actor_ok):
        try:
            if not kv_ok:
                got = cw._run(cw.gcs.call(
                    "KVGet", {"ns": "wt", "key": b"k"}), timeout=5)
                kv_ok = got.get("value") == b"v"
            if not actor_ok:
                # The registration was PENDING at kill time; the restarted
                # GCS must replay it and re-kick scheduling.
                h = ray_tpu.get_actor("wt-actor")
                actor_ok = ray_tpu.get(h.ping.remote(), timeout=30) == "pong"
        except Exception:
            time.sleep(0.5)
    assert kv_ok, "acknowledged KVPut lost across immediate kill -9"
    assert actor_ok, "acknowledged actor registration lost across kill -9"


def test_pg_ready_promise_survives_gcs_restart(ft_cluster):
    """pg.ready() is a GCS-pubsub-backed promise (r5): a CREATED that
    lands while the driver's GCS conn is down must still resolve — the
    reconnect handshake re-queries every armed waiter (worker.py
    _reconnect_gcs). Sequence: PG stays PENDING (infeasible), ready()
    arms, GCS dies and restarts, THEN capacity arrives and the PG
    creates — the promise must fire, not hang."""
    import threading

    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    # Infeasible until a bigger node joins: 16 CPUs on a 4-CPU head.
    pg = placement_group([{"CPU": 16.0}])
    ref = pg.ready()

    got = []
    waiter = threading.Thread(
        target=lambda: got.append(ray_tpu.get(ref, timeout=120)),
        daemon=True)
    waiter.start()
    time.sleep(1.0)
    assert not got, "PG resolved before capacity existed"

    node = ft_cluster._node
    node.kill_gcs()
    time.sleep(0.5)
    node.restart_gcs()

    # New capacity arrives AFTER the restart; the PG schedules and the
    # promise must resolve through the re-subscribed channel (or the
    # reconnect re-query), not hang forever.
    ft_cluster.add_node(num_cpus=16)
    waiter.join(timeout=90)
    assert got == [True], f"pg.ready() promise lost across GCS restart: {got}"
    remove_placement_group(pg)


# ---------------------------------------------------------------------------
# Network partitions (PR 10): a raylet whose GCS link flaps inside the
# heartbeat grace window is a NON-EVENT — SUSPECT, then restored, with
# zero reconstructions, zero duplicate actor creations, and the workload
# unbothered. Only an outage that outlives the grace window promotes
# SUSPECT -> DEAD. The link runs through a seeded NetChaos proxy so the
# fault schedule is deterministic.
# ---------------------------------------------------------------------------


def _node_row(node_id):
    return next((n for n in ray_tpu.nodes()
                 if n["node_id"] == node_id), {})


def test_partition_flap_is_a_non_event(ft_cluster):
    """~500 tasks flow while the target raylet's GCS link flaps twice
    (each outage well under the 0.2s x 10 = 2s grace). Every result must
    arrive, the node must end ALIVE with suspect_recoveries bumped, the
    pinned actor must keep its process (no duplicate creation), and the
    driver must count zero lineage reconstructions — the raylet's
    resilient session reconnected instead of the node dying."""
    from ray_tpu._private.api_internal import get_core_worker
    from ray_tpu.test_utils import NetChaos, wait_for_condition
    from ray_tpu.util import state as util_state

    cw = get_core_worker()
    chaos = NetChaos(seed=7).start()
    try:
        gcs_host, gcs_port = ft_cluster.gcs_address.rsplit(":", 1)
        proxy = chaos.link("flap-gcs", gcs_host, int(gcs_port))
        target = ft_cluster.add_node(num_cpus=4, resources={"part": 1},
                                     gcs_addr=proxy)
        ft_cluster.wait_for_nodes()

        @ray_tpu.remote
        class Pinned:
            def __init__(self):
                import os
                self.pid = os.getpid()
                self.n = 0

            def incr(self):
                self.n += 1
                return (self.pid, self.n)

        actor = Pinned.options(max_restarts=5,
                               resources={"part": 0.1}).remote()
        pid0, n0 = ray_tpu.get(actor.incr.remote(), timeout=30)
        assert n0 == 1

        @ray_tpu.remote(resources={"part": 0.01})
        def inc(x):
            return x + 1

        refs = []
        for i in range(500):
            if i in (100, 300):
                chaos.flap("flap-gcs", down_s=0.5)
            refs.append(inc.remote(i))
        assert ray_tpu.get(refs, timeout=180) == [i + 1 for i in range(500)]

        wait_for_condition(
            lambda: _node_row(target.node_id).get("state") == "ALIVE",
            timeout=15)
        row = _node_row(target.node_id)
        assert row.get("suspect_recoveries", 0) >= 1, \
            f"flap never entered the SUSPECT rung: {row}"
        # Same actor process, same counter: no duplicate creation, no
        # restart — the flap was invisible to it.
        pid1, n1 = ray_tpu.get(actor.incr.remote(), timeout=30)
        assert (pid1, n1) == (pid0, 2), "actor restarted across a flap"
        assert cw._num_reconstructions == 0
        # The raylet rode its resilient session through the cuts instead
        # of re-dialing ad hoc.
        stats = util_state.node_stats(node_id=target.node_id)
        sess = stats[0].get("rpc_sessions", {}) if stats else {}
        assert sess.get("reconnects_total", 0) >= 1, sess
        status = util_state.cluster_status()
        assert status.get("suspect_nodes") == 0
    finally:
        chaos.stop()


def test_partition_longer_than_grace_promotes_to_dead(ft_cluster):
    """The other side of the contract: an outage that OUTLIVES the grace
    window must not be forgiven. The node walks ALIVE -> SUSPECT (on
    connection loss) -> DEAD (on grace expiry), observably from the
    driver, while the outage is still in progress."""
    import threading

    from ray_tpu.test_utils import NetChaos, wait_for_condition

    chaos = NetChaos(seed=8).start()
    try:
        gcs_host, gcs_port = ft_cluster.gcs_address.rsplit(":", 1)
        proxy = chaos.link("dead-gcs", gcs_host, int(gcs_port))
        target = ft_cluster.add_node(num_cpus=2, resources={"gone": 1},
                                     gcs_addr=proxy)
        ft_cluster.wait_for_nodes()
        assert _node_row(target.node_id).get("state") == "ALIVE"

        # Outage (6s) > grace (0.2s x 10 = 2s). flap() blocks for the
        # full outage, so run it on the side and watch the ladder.
        flapper = threading.Thread(
            target=lambda: chaos.flap("dead-gcs", down_s=6.0), daemon=True)
        flapper.start()
        wait_for_condition(
            lambda: _node_row(target.node_id).get("state") == "SUSPECT",
            timeout=10)
        wait_for_condition(
            lambda: _node_row(target.node_id).get("state") == "DEAD",
            timeout=10)
        assert _node_row(target.node_id).get("alive") is False
        flapper.join(timeout=15)
    finally:
        chaos.stop()
