"""Native C++ scheduler core (src/scheduler.cc via ctypes).

Parity targets: reference hybrid policy tests
(src/ray/raylet/scheduling/policy/hybrid_scheduling_policy_test.cc) and
bundle policy semantics (policy/bundle_scheduling_policy.h).
"""

import pytest

from ray_tpu._private import native_scheduler
from ray_tpu._private.native_scheduler import ClusterScheduler


@pytest.fixture
def sched():
    assert native_scheduler.available(), "native scheduler failed to build"
    s = ClusterScheduler()
    yield s
    s.close()


def test_basic_feasibility(sched):
    sched.update_node("a", total={"CPU": 4}, available={"CPU": 4})
    sched.update_node("b", total={"CPU": 8, "TPU": 4},
                      available={"CPU": 8, "TPU": 4})
    assert sched.num_nodes() == 2
    # Only b has TPU.
    assert sched.pick_node({"TPU": 1}) == "b"
    # Nothing fits 16 CPUs.
    assert sched.pick_node({"CPU": 16}) is None
    # Fractional demand fits.
    assert sched.pick_node({"CPU": 0.5, "TPU": 0.5}) == "b"


def test_dead_node_excluded(sched):
    sched.update_node("a", total={"CPU": 4}, available={"CPU": 4})
    sched.update_node("b", total={"CPU": 4}, available={"CPU": 4}, alive=False)
    for seed in range(8):
        assert sched.pick_node({"CPU": 1}, seed=seed) == "a"
    sched.update_node("b", alive=True)
    # b kept its resources across the alive flip.
    assert sched.pick_node({"CPU": 1}, strategy="spread") in ("a", "b")


def test_exclude_and_fallback_total(sched):
    sched.update_node("a", total={"CPU": 4}, available={"CPU": 0})
    sched.update_node("b", total={"CPU": 2}, available={"CPU": 2})
    # b fits now; excluding b leaves nothing available — but with
    # fallback_total, a's total capacity qualifies (lease queues there).
    assert sched.pick_node({"CPU": 4}, exclude="b") is None
    assert sched.pick_node({"CPU": 4}, exclude="b",
                           fallback_total=True) == "a"


def test_pack_prefers_most_utilized(sched):
    sched.update_node("a", total={"CPU": 8}, available={"CPU": 8})
    sched.update_node("b", total={"CPU": 8}, available={"CPU": 2})
    assert sched.pick_node({"CPU": 1}, strategy="pack") == "b"
    assert sched.pick_node({"CPU": 1}, strategy="spread") == "a"


def test_hybrid_threshold_and_topk(sched):
    # Node under the 0.5 utilization knee wins over an over-threshold node
    # even when the latter is "more packed".
    sched.update_node("cold", total={"CPU": 10}, available={"CPU": 9})
    sched.update_node("hot", total={"CPU": 10}, available={"CPU": 2})
    for seed in range(8):
        assert sched.pick_node({"CPU": 1}, seed=seed) == "cold"
    # With every node over threshold, least-utilized wins.
    sched.update_node("cold", available={"CPU": 3})
    for seed in range(8):
        assert sched.pick_node({"CPU": 1}, seed=seed) == "cold"


def test_hybrid_spreads_across_topk(sched):
    # 10 identical nodes -> top-k pool of 2; different seeds must not all
    # herd onto one node.
    for i in range(10):
        sched.update_node(f"n{i}", total={"CPU": 4}, available={"CPU": 4})
    picks = {sched.pick_node({"CPU": 1}, seed=s) for s in range(16)}
    assert len(picks) == 2


def test_affinity(sched):
    sched.update_node("a", total={"CPU": 4}, available={"CPU": 4})
    sched.update_node("b", total={"CPU": 4}, available={"CPU": 4})
    assert sched.pick_node({"CPU": 1}, strategy="affinity:b:0") == "b"
    sched.update_node("b", alive=False)
    # Hard affinity to a dead node fails; soft falls back to the policy.
    assert sched.pick_node({"CPU": 1}, strategy="affinity:b:0") is None
    assert sched.pick_node({"CPU": 1}, strategy="affinity:b:1") == "a"


def test_debit(sched):
    sched.update_node("a", total={"CPU": 4}, available={"CPU": 4})
    sched.debit_node("a", {"CPU": 3})
    assert sched.pick_node({"CPU": 2}) is None
    assert sched.pick_node({"CPU": 1}) == "a"


def test_bundles_pack_and_strict_pack(sched):
    sched.update_node("a", total={"CPU": 4}, available={"CPU": 4})
    sched.update_node("b", total={"CPU": 4}, available={"CPU": 4})
    # PACK: both bundles fit on the first node.
    got = sched.schedule_bundles([{"CPU": 2}, {"CPU": 2}], "PACK")
    assert got == ["a", "a"]
    # STRICT_PACK with bundles that exceed any single node -> infeasible.
    assert sched.schedule_bundles([{"CPU": 3}, {"CPU": 3}],
                                  "STRICT_PACK") is None
    assert sched.schedule_bundles([{"CPU": 2}, {"CPU": 2}],
                                  "STRICT_PACK") == ["a", "a"]


def test_bundles_spread_and_strict_spread(sched):
    sched.update_node("a", total={"CPU": 4}, available={"CPU": 4})
    sched.update_node("b", total={"CPU": 4}, available={"CPU": 4})
    got = sched.schedule_bundles([{"CPU": 1}, {"CPU": 1}, {"CPU": 1}],
                                 "SPREAD")
    assert sorted(got[:2]) == ["a", "b"]  # round-robins before reusing
    assert len(got) == 3
    # STRICT_SPREAD needs distinct nodes: 3 bundles on 2 nodes fails.
    assert sched.schedule_bundles([{"CPU": 1}] * 3, "STRICT_SPREAD") is None
    assert sorted(sched.schedule_bundles([{"CPU": 1}] * 2,
                                         "STRICT_SPREAD")) == ["a", "b"]


def test_bundles_strict_ici(sched):
    # Two slices; slice-1 hosts can't fit the gang, slice-2 can.
    sched.update_node("h1", total={"TPU": 4}, available={"TPU": 1},
                      labels={"tpu-slice": "s1"})
    sched.update_node("h2", total={"TPU": 4}, available={"TPU": 1},
                      labels={"tpu-slice": "s1"})
    sched.update_node("h3", total={"TPU": 4}, available={"TPU": 4},
                      labels={"tpu-slice": "s2"})
    sched.update_node("h4", total={"TPU": 4}, available={"TPU": 4},
                      labels={"tpu-slice": "s2"})
    sched.update_node("cpu", total={"CPU": 64}, available={"CPU": 64})
    got = sched.schedule_bundles([{"TPU": 4}, {"TPU": 4}], "STRICT_ICI")
    assert sorted(got) == ["h3", "h4"]
    # A gang too big for any one slice is infeasible.
    assert sched.schedule_bundles([{"TPU": 4}] * 3, "STRICT_ICI") is None


def test_fixed_point_exactness(sched):
    # 0.1 + 0.2-style float drift must not leak capacity (fixed-point math).
    sched.update_node("a", total={"CPU": 1}, available={"CPU": 1})
    for _ in range(10):
        sched.debit_node("a", {"CPU": 0.1})
    assert sched.pick_node({"CPU": 0.0001}) is None
