"""Cloud datasource + optimizer pushdown tests: parquet over a hermetic
mock S3 server (reference model: data/tests/mock_s3_server.py), plus
projection/filter pushdown into the read tasks, plus a
larger-than-object-store streaming run (VERDICT r2 #7)."""

import io
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data
from ray_tpu.data import s3 as s3mod
from ray_tpu.data.dataset import ReadTask, _pushdown_rewrite

from tests.mock_s3_server import MockS3Server

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402


@pytest.fixture(scope="module")
def s3():
    server = MockS3Server()
    os.environ[s3mod.ENDPOINT_ENV] = server.endpoint
    yield server
    os.environ.pop(s3mod.ENDPOINT_ENV, None)
    server.close()


def _put_parquet(s3, bucket, key, table):
    buf = io.BytesIO()
    pq.write_table(table, buf)
    s3.put(bucket, key, buf.getvalue())


def test_s3_client_list_and_get(s3):
    s3.put("b", "pre/x.bin", b"hello")
    s3.put("b", "pre/y.bin", b"world")
    s3.put("b", "other.bin", b"nope")
    from ray_tpu.data.s3 import S3Client

    c = S3Client(s3.endpoint)
    assert c.list_keys("b", "pre/") == ["pre/x.bin", "pre/y.bin"]
    assert c.get_object("b", "pre/x.bin") == b"hello"
    assert c.get_object("b", "pre/x.bin", byte_range=(1, 3)) == b"ell"


def test_read_parquet_from_mock_s3(s3, ray_start_regular):
    t = pa.table({"a": list(range(10)), "b": [f"r{i}" for i in range(10)]})
    _put_parquet(s3, "data", "ds/part-0.parquet", t.slice(0, 5))
    _put_parquet(s3, "data", "ds/part-1.parquet", t.slice(5, 5))
    ds = data.read_parquet("s3://data/ds/")
    rows = sorted(r["a"] for r in ds.iter_rows())
    assert rows == list(range(10))


def test_projection_and_filter_pushdown_plan(s3):
    """The optimizer folds select_columns + filter(expr) INTO the parquet
    ReadTasks and drops the stages from the physical plan."""
    t = pa.table({"a": list(range(8)), "b": list(range(8)),
                  "c": list(range(8))})
    _put_parquet(s3, "data", "pd/f.parquet", t)
    ds = data.read_parquet("s3://data/pd/") \
        .select_columns(["a", "b"]).filter(expr=("a", ">=", 4))
    source, stages = _pushdown_rewrite(list(ds._source), list(ds._stages))
    assert stages == []  # both folded away
    (task,) = source
    assert isinstance(task, ReadTask)
    assert task.meta["columns"] == ["a", "b"]
    assert task.meta["filters"] == [("a", ">=", 4)]


def test_pushdown_results_match_unpushed(s3, ray_start_regular):
    t = pa.table({"a": list(range(20)), "b": [i * 10 for i in range(20)],
                  "c": ["x"] * 20})
    _put_parquet(s3, "data", "eq/f.parquet", t)
    pushed = data.read_parquet("s3://data/eq/") \
        .select_columns(["a", "b"]).filter(expr=("a", "<", 5))
    plain = data.read_parquet("s3://data/eq/") \
        .filter(fn=lambda r: r["a"] < 5)
    got = sorted((r["a"], r["b"]) for r in pushed.iter_rows())
    want = sorted((r["a"], r["b"]) for r in plain.iter_rows())
    assert got == want == [(i, i * 10) for i in range(5)]


def test_arbitrary_filter_fn_not_pushed(s3):
    t = pa.table({"a": [1, 2]})
    _put_parquet(s3, "data", "nf/f.parquet", t)
    ds = data.read_parquet("s3://data/nf/").filter(fn=lambda r: r["a"] > 1)
    _source, stages = _pushdown_rewrite(list(ds._source), list(ds._stages))
    assert [s.name for s in stages] == ["filter"]


def test_read_text_from_mock_s3(s3, ray_start_regular):
    s3.put("data", "txt/a.txt", b"one\ntwo\n")
    s3.put("data", "txt/b.txt", b"three\n")
    ds = data.read_text("s3://data/txt/")
    assert sorted(r["text"] for r in ds.iter_rows()) == \
        ["one", "three", "two"]


def test_streaming_larger_than_object_store(s3):
    """Parquet-on-mock-S3 dataset LARGER than the object-store arena
    streams end-to-end: bounded in-flight + spilling keep it moving
    (reference: streaming executor with resource backpressure)."""
    from ray_tpu._private.config import Config

    n_files, rows_per_file = 6, 120_000
    total_bytes = 0
    for i in range(n_files):
        arr = np.arange(i * rows_per_file, (i + 1) * rows_per_file,
                        dtype=np.int64)
        t = pa.table({"v": arr, "pad": np.random.default_rng(i)
                      .standard_normal(rows_per_file)})
        buf = io.BytesIO()
        pq.write_table(t, buf, compression="none")
        total_bytes += buf.getbuffer().nbytes
        s3.put("big", f"p/part-{i}.parquet", buf.getvalue())

    cfg = Config()
    cfg.object_store_memory = 8 << 20  # smaller than the dataset
    assert total_bytes > cfg.object_store_memory
    ray_tpu.init(num_cpus=4, config=cfg)
    try:
        ds = data.read_parquet("s3://big/p/",
                               endpoint_url=s3.endpoint).select_columns(["v"])
        total = 0
        count = 0
        for batch in ds.iter_batches(batch_size=50_000):
            vs = batch["v"] if isinstance(batch, dict) else batch
            total += int(np.sum(np.asarray(vs)))
            count += len(vs)
        n = n_files * rows_per_file
        assert count == n
        assert total == n * (n - 1) // 2
    finally:
        ray_tpu.shutdown()


def test_tensor_extension_columns_roundtrip(tmp_path):
    """ndarray columns become Arrow fixed-shape tensor extension columns
    (reference: ray.data tensor extensions) and survive arrow->batch and
    parquet round-trips with shape intact."""
    from ray_tpu.data.block import block_to_arrow, block_to_batch

    imgs = np.arange(4 * 2 * 3, dtype=np.float32).reshape(4, 2, 3)
    table = block_to_arrow({"image": imgs, "label": np.arange(4)})
    assert isinstance(table.column("image").type, pa.FixedShapeTensorType)
    batch = block_to_batch(table)
    np.testing.assert_array_equal(batch["image"], imgs)
    np.testing.assert_array_equal(batch["label"], np.arange(4))

    # Parquet round-trip preserves the extension type.
    path = str(tmp_path / "tensors.parquet")
    pq.write_table(table, path)
    back = block_to_batch(pq.read_table(path))
    np.testing.assert_array_equal(back["image"], imgs)

    # Row-of-ndarray blocks batch into tensor columns too.
    rows = [{"x": np.full((2, 2), i, np.int64)} for i in range(3)]
    t2 = block_to_arrow(rows)
    assert isinstance(t2.column("x").type, pa.FixedShapeTensorType)
    np.testing.assert_array_equal(
        block_to_batch(t2)["x"],
        np.stack([np.full((2, 2), i) for i in range(3)]))
