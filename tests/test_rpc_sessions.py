"""Resilient RPC session unit tests (PR 10).

A ResilientConnection is a stable session id over reconnecting sockets:
stamped requests replay across socket death and the server-side
(session_id, rseq) reply cache makes the replay at-most-once. These
tests pin the session layer's own contracts — reconnect, replay, dedup,
per-call deadlines, grace exhaustion, the grace_s=0 fast path — plus
the NetChaos fault injector's frame-level behavior (duplicate frames,
cuts, one-way blackholes) against a live RpcServer.
"""

import asyncio

import pytest

from ray_tpu._private import rpc
from ray_tpu.test_utils import NetChaos


def run(coro):
    return asyncio.run(coro)


def echo_server():
    return rpc.RpcServer({"Echo": lambda c, p: {"v": p["v"]}}, name="t")


def test_session_reconnects_after_server_side_close():
    async def main():
        server = echo_server()
        host, port = await server.start()
        try:
            sess = await rpc.connect_session(host, port, name="s",
                                             grace_s=10.0)
            assert (await sess.call("Echo", {"v": 1}))["v"] == 1
            before = sess.reconnects
            for conn in list(server.connections):
                await conn.close()
            # Same session object keeps answering over a fresh socket.
            assert (await sess.call("Echo", {"v": 2}, timeout=10))["v"] == 2
            assert sess.reconnects >= before + 1
            assert not sess.closed
            await sess.close()
        finally:
            await server.stop()

    run(main())


def test_duplicate_request_frames_execute_once():
    """dup=1.0 duplicates every frame on the wire; the reply cache must
    absorb the duplicate REQUESTs (at-most-once) and the client must
    tolerate duplicate RESPONSEs."""
    async def main():
        counter = {"n": 0}

        def bump(conn, payload):
            counter["n"] += 1
            return {"n": counter["n"]}

        server = rpc.RpcServer({"Bump": bump}, name="t")
        host, port = await server.start()
        chaos = NetChaos(seed=5).start()
        try:
            ph, pp = chaos.link("dup", host, port)
            sess = await rpc.connect_session(ph, pp, name="dup-sess",
                                             grace_s=5.0)
            deduped0 = rpc.session_stats()["deduped_requests_total"]
            chaos.set_faults("dup", dup=1.0)
            for i in range(10):
                assert (await sess.call("Bump", {}, timeout=10))["n"] == i + 1
            assert counter["n"] == 10
            assert chaos.stats("dup")["frames_duplicated"] >= 10
            assert rpc.session_stats()["deduped_requests_total"] > deduped0
            await sess.close()
        finally:
            await server.stop()
            chaos.stop()

    run(main())


def test_cut_midflight_replays_without_second_execution():
    """A socket cut while the handler is running: the replayed request
    must attach to the in-flight execution (or its cached reply), not
    run the handler a second time."""
    async def main():
        calls = {"n": 0}

        async def slow(conn, payload):
            calls["n"] += 1
            await asyncio.sleep(0.5)
            return {"n": calls["n"]}

        server = rpc.RpcServer({"Slow": slow}, name="t")
        host, port = await server.start()
        chaos = NetChaos(seed=9).start()
        try:
            ph, pp = chaos.link("cut", host, port)
            sess = await rpc.connect_session(ph, pp, name="cut-sess",
                                             grace_s=10.0)
            replayed0 = rpc.session_stats()["replayed_requests_total"]
            fut = asyncio.ensure_future(sess.call("Slow", {}, timeout=15))
            await asyncio.sleep(0.1)  # request is in flight server-side
            chaos.cut("cut")
            assert (await fut)["n"] == 1
            assert calls["n"] == 1, "replay re-executed a stamped request"
            assert rpc.session_stats()["replayed_requests_total"] > replayed0
            await sess.close()
        finally:
            await server.stop()
            chaos.stop()

    run(main())


def test_session_stamp_stripped_before_handler():
    async def main():
        seen = {}

        def grab(key):
            def h(conn, payload):
                seen[key] = dict(payload)
                return {"ok": True}
            return h

        server = rpc.RpcServer({"KVGet": grab("exempt"),
                                "Other": grab("stamped")}, name="t")
        host, port = await server.start()
        try:
            sess = await rpc.connect_session(host, port, name="s",
                                             grace_s=5.0)
            await sess.call("KVGet", {"k": 1})
            await sess.call("Other", {"k": 1})
            # Exempt methods are never stamped; stamped methods have the
            # reserved keys stripped by the dispatcher.
            for key in ("exempt", "stamped"):
                assert rpc._SID_KEY not in seen[key]
                assert rpc._RSEQ_KEY not in seen[key]
                assert seen[key]["k"] == 1
            # Only the stamped call opened a server-side session.
            assert rpc.session_stats()["server_sessions"] >= 1
            await sess.close()
        finally:
            await server.stop()

    run(main())


def test_call_timeout_leaves_session_usable():
    async def main():
        async def hang(conn, payload):
            await asyncio.sleep(30)

        server = rpc.RpcServer(
            {"Hang": hang, "Echo": lambda c, p: {"v": p["v"]}}, name="t")
        host, port = await server.start()
        try:
            sess = await rpc.connect_session(host, port, name="s",
                                             grace_s=5.0)
            with pytest.raises(asyncio.TimeoutError):
                await sess.call("Hang", {}, timeout=0.3)
            assert (await sess.call("Echo", {"v": 3}))["v"] == 3
            assert not sess.closed
            await sess.close()
        finally:
            await server.stop()

    run(main())


def test_grace_exhaustion_fails_session_and_fires_on_close():
    async def main():
        server = echo_server()
        host, port = await server.start()
        sess = await rpc.connect_session(host, port, name="s", grace_s=0.5)
        fired = []
        sess.on_close(lambda: fired.append(1))
        await server.stop()  # nothing listening: redial can never succeed
        with pytest.raises(rpc.ConnectionLost):
            await sess.call("Echo", {"v": 1}, timeout=20)
        # The failure may surface via this call or the eager background
        # redial; either way the session is closed and on_close fired
        # exactly once.
        for _ in range(50):
            if fired:
                break
            await asyncio.sleep(0.05)
        assert fired == [1]
        assert sess.closed
        with pytest.raises(rpc.ConnectionLost):
            await sess.call("Echo", {"v": 2})

    run(main())


def test_grace_zero_still_gets_one_redial_attempt():
    """grace_s=0 (pool-worker semantics: die with the peer) still makes
    a single fast redial attempt — an instantly-rebound listener keeps
    the session; a dead one fails it."""
    async def main():
        server = echo_server()
        host, port = await server.start()
        try:
            sess = await rpc.connect_session(host, port, name="s",
                                             grace_s=0.0)
            for conn in list(server.connections):
                await conn.close()
            assert (await sess.call("Echo", {"v": 1}, timeout=10))["v"] == 1
            await sess.close()
        finally:
            await server.stop()

    run(main())


def test_deliberate_close_does_not_fire_on_close():
    async def main():
        server = echo_server()
        host, port = await server.start()
        try:
            sess = await rpc.connect_session(host, port, name="s")
            fired = []
            sess.on_close(lambda: fired.append(1))
            await sess.close()
            assert fired == []
            assert sess.closed
        finally:
            await server.stop()

    run(main())


def test_on_reconnect_runs_before_next_call():
    async def main():
        order = []
        server = rpc.RpcServer(
            {"Echo": lambda c, p: order.append("call") or {}}, name="t")
        host, port = await server.start()
        try:
            async def handshake(conn):
                order.append("handshake")

            sess = await rpc.connect_session(host, port, name="s",
                                             grace_s=10.0,
                                             on_reconnect=handshake)
            await sess.call("Echo", {})
            for conn in list(server.connections):
                await conn.close()
            await sess.call("Echo", {}, timeout=10)
            assert order == ["call", "handshake", "call"]
            await sess.close()
        finally:
            await server.stop()

    run(main())


def test_dial_raises_after_deadline_on_dead_port():
    async def main():
        server = echo_server()
        host, port = await server.start()
        await server.stop()  # port now refuses connections
        with pytest.raises((OSError, asyncio.TimeoutError)):
            await rpc.dial(host, port, timeout=0.5)

    run(main())


def test_one_way_partition_times_out_then_heals():
    """A directional blackhole (sockets open, frames eaten) must look
    like silence — calls time out, the session stays up — and a heal
    restores service on the same session."""
    async def main():
        server = echo_server()
        host, port = await server.start()
        chaos = NetChaos(seed=13).start()
        try:
            ph, pp = chaos.link("bh", host, port)
            sess = await rpc.connect_session(ph, pp, name="bh-sess",
                                             grace_s=10.0)
            assert (await sess.call("Echo", {"v": 1}))["v"] == 1
            chaos.partition("bh", "c2s")
            with pytest.raises(asyncio.TimeoutError):
                await sess.call("Echo", {"v": 2}, timeout=0.5)
            assert not sess.closed
            assert chaos.stats("bh")["frames_blackholed"] >= 1
            chaos.heal("bh")
            assert (await sess.call("Echo", {"v": 3}, timeout=10))["v"] == 3
            await sess.close()
        finally:
            await server.stop()
            chaos.stop()

    run(main())


def test_accept_then_close_peer_does_not_spin_redials():
    """A peer that ACCEPTS and instantly closes (half-up proxy, load
    balancer with no healthy backend) looks like a successful reconnect.
    Without cross-cycle backoff memory the session re-dials at connect
    speed (observed: ~250 reconnects/s against a refusing NetChaos
    link). The streak detector must keep backing off across these fake
    successes — and the session must still recover once a real server
    is back on the port."""
    async def main():
        server = echo_server()
        host, port = await server.start()
        sess = await rpc.connect_session(host, port, name="s",
                                         grace_s=30.0)
        assert (await sess.call("Echo", {"v": 1}))["v"] == 1
        await server.stop()

        accepts = {"n": 0}

        async def accept_close(reader, writer):
            accepts["n"] += 1
            writer.close()

        sick = await asyncio.start_server(accept_close, host, port)
        await asyncio.sleep(1.5)  # let the redial loop run against it
        sick.close()
        await sick.wait_closed()
        # connect-speed spinning would be hundreds of accepts here.
        assert accepts["n"] <= 10, \
            f"redial loop spun {accepts['n']} times in 1.5s"
        assert not sess.closed, "session failed before grace expired"

        server2 = rpc.RpcServer({"Echo": lambda c, p: {"v": p["v"]}},
                                name="t2")
        await server2.start(host=host, port=port)
        try:
            assert (await sess.call("Echo", {"v": 2}, timeout=15))["v"] == 2
            await sess.close()
        finally:
            await server2.stop()

    run(main())


def test_netchaos_deterministic_per_seed():
    """Same seed, same per-direction rng draw sequence — the fault
    schedule replays exactly."""
    from ray_tpu.test_utils import _ChaosLink

    seqs = []
    for _ in range(2):
        lk = _ChaosLink("x", ("127.0.0.1", 1), 42)
        seqs.append([(lk.rng["c2s"].random(), lk.rng["s2c"].random())
                     for _ in range(32)])
    assert seqs[0] == seqs[1]
    other = _ChaosLink("y", ("127.0.0.1", 1), 42)
    assert [other.rng["c2s"].random() for _ in range(32)] != \
        [a for a, _ in seqs[0]]
