"""Object spilling + memory-monitor policy.

Parity: reference python/ray/tests/test_object_spilling*.py (spill when the
store fills, restore on demand) and worker_killing_policy tests.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.config import Config
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStoreClient
from ray_tpu._private.raylet import WorkerHandle, pick_oom_victim


def test_lru_candidates_and_auto_evict(tmp_path):
    store = ObjectStoreClient(str(tmp_path / "arena"), create=True,
                              size=4 * 1024 * 1024, table_capacity=128)
    try:
        ids = []
        for i in range(4):
            oid = ObjectID.from_random()
            store.put_raw(oid, b"x" * 500_000)
            ids.append(oid)
        # Touch id[0] so it becomes most-recently-used.
        got = store.get_buffer(ids[0])
        assert got is not None
        store.release(ids[0])
        cands = store.lru_candidates(needed=600_000)
        assert cands, "expected spill candidates"
        # LRU first: ids[1] (oldest untouched) leads; the freshly-touched
        # ids[0] must not be first.
        assert cands[0].hex() == ids[1].hex()

        # auto_evict off -> create reports OOM instead of evicting.
        store.set_auto_evict(False)
        big = ObjectID.from_random()
        from ray_tpu._private.object_store import ObjectStoreFullError

        with pytest.raises(ObjectStoreFullError):
            store.create(big, 3 * 1024 * 1024, 0)
        for oid in ids:
            assert store.contains(oid)  # nothing was evicted

        # auto_evict on -> same create succeeds by evicting LRU objects.
        store.set_auto_evict(True)
        buf = store.create(big, 3 * 1024 * 1024, 0)
        assert len(buf) == 3 * 1024 * 1024
        store.seal(big)
        assert not store.contains(ids[1])
    finally:
        store.close()


def test_put_spills_and_restores():
    """Fill a tiny store several times over: puts trigger raylet spilling,
    gets restore from disk — no data lost."""
    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.object_store_memory = 8 * 1024 * 1024
    ray_tpu.init(num_cpus=2, config=cfg)
    try:
        blobs = [np.full(1_000_000, i, np.uint8) for i in range(20)]
        refs = [ray_tpu.put(b) for b in blobs]  # ~20 MB into an 8 MB store
        for i, r in enumerate(refs):
            got = ray_tpu.get(r, timeout=60)
            assert got.dtype == np.uint8 and got[0] == i and len(got) == 1_000_000
        # And round 2: restores themselves may need to spill others.
        for i, r in enumerate(reversed(refs)):
            got = ray_tpu.get(r, timeout=60)
            assert got[0] == 19 - i
    finally:
        ray_tpu.shutdown()


def test_task_outputs_spill():
    """Task return values exceed store capacity collectively."""
    cfg = Config()
    cfg.object_store_memory = 8 * 1024 * 1024
    ray_tpu.init(num_cpus=2, config=cfg)
    try:
        @ray_tpu.remote
        def make(i):
            return np.full(900_000, i % 251, np.uint8)

        refs = [make.remote(i) for i in range(16)]
        out = ray_tpu.get(refs, timeout=120)
        for i, arr in enumerate(out):
            assert arr[0] == i % 251
    finally:
        ray_tpu.shutdown()


def _fake_worker(leased, actor_id, leased_at):
    w = WorkerHandle.__new__(WorkerHandle)
    w.leased = leased
    w.actor_id = actor_id
    w.leased_at = leased_at
    w.dead = False
    return w


def test_pick_oom_victim_policy():
    idle = _fake_worker(False, None, 0.0)
    old_task = _fake_worker(True, None, 1.0)
    new_task = _fake_worker(True, None, 2.0)
    actor = _fake_worker(False, "a" * 16, 3.0)
    # Newest-leased retriable task goes first.
    assert pick_oom_victim([idle, old_task, new_task, actor]) is new_task
    # No task workers: actors are last resort.
    assert pick_oom_victim([idle, actor]) is actor
    # Nothing killable.
    assert pick_oom_victim([idle]) is None
    dead = _fake_worker(True, None, 9.0)
    dead.dead = True
    assert pick_oom_victim([idle, dead, old_task]) is old_task
