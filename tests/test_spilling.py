"""Object spilling + memory-monitor policy.

Parity: reference python/ray/tests/test_object_spilling*.py (spill when the
store fills, restore on demand) and worker_killing_policy tests.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.config import Config
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStoreClient
from ray_tpu._private.raylet import WorkerHandle, pick_oom_victim


def test_lru_candidates_and_auto_evict(tmp_path):
    store = ObjectStoreClient(str(tmp_path / "arena"), create=True,
                              size=4 * 1024 * 1024, table_capacity=128)
    try:
        ids = []
        for i in range(4):
            oid = ObjectID.from_random()
            store.put_raw(oid, b"x" * 500_000)
            ids.append(oid)
        # Touch id[0] so it becomes most-recently-used.
        got = store.get_buffer(ids[0])
        assert got is not None
        store.release(ids[0])
        cands = store.lru_candidates(needed=600_000)
        assert cands, "expected spill candidates"
        # LRU first: ids[1] (oldest untouched) leads; the freshly-touched
        # ids[0] must not be first.
        assert cands[0].hex() == ids[1].hex()

        # auto_evict off -> create reports OOM instead of evicting.
        store.set_auto_evict(False)
        big = ObjectID.from_random()
        from ray_tpu._private.object_store import ObjectStoreFullError

        with pytest.raises(ObjectStoreFullError):
            store.create(big, 3 * 1024 * 1024, 0)
        for oid in ids:
            assert store.contains(oid)  # nothing was evicted

        # auto_evict on -> same create succeeds by evicting LRU objects.
        store.set_auto_evict(True)
        buf = store.create(big, 3 * 1024 * 1024, 0)
        assert len(buf) == 3 * 1024 * 1024
        store.seal(big)
        assert not store.contains(ids[1])
    finally:
        store.close()


def test_put_spills_and_restores():
    """Fill a tiny store several times over: puts trigger raylet spilling,
    gets restore from disk — no data lost."""
    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.object_store_memory = 8 * 1024 * 1024
    ray_tpu.init(num_cpus=2, config=cfg)
    try:
        blobs = [np.full(1_000_000, i, np.uint8) for i in range(20)]
        refs = [ray_tpu.put(b) for b in blobs]  # ~20 MB into an 8 MB store
        for i, r in enumerate(refs):
            got = ray_tpu.get(r, timeout=60)
            assert got.dtype == np.uint8 and got[0] == i and len(got) == 1_000_000
        # And round 2: restores themselves may need to spill others.
        for i, r in enumerate(reversed(refs)):
            got = ray_tpu.get(r, timeout=60)
            assert got[0] == 19 - i
    finally:
        ray_tpu.shutdown()


def test_task_outputs_spill():
    """Task return values exceed store capacity collectively."""
    cfg = Config()
    cfg.object_store_memory = 8 * 1024 * 1024
    ray_tpu.init(num_cpus=2, config=cfg)
    try:
        @ray_tpu.remote
        def make(i):
            return np.full(900_000, i % 251, np.uint8)

        refs = [make.remote(i) for i in range(16)]
        # Consume INCREMENTALLY: 16 x 0.9MB of results cannot all be
        # pinned in an 8MB arena at once (zero-copy gets hold shm refs,
        # plasma semantics); dropping each view frees its slot so later
        # writes can spill earlier outputs.
        for i, r in enumerate(refs):
            arr = ray_tpu.get(r, timeout=120)
            assert arr[0] == i % 251
            del arr
    finally:
        ray_tpu.shutdown()


def _fake_worker(leased, actor_id, leased_at):
    w = WorkerHandle.__new__(WorkerHandle)
    w.leased = leased
    w.actor_id = actor_id
    w.leased_at = leased_at
    w.dead = False
    return w


def test_pick_oom_victim_policy():
    idle = _fake_worker(False, None, 0.0)
    old_task = _fake_worker(True, None, 1.0)
    new_task = _fake_worker(True, None, 2.0)
    actor = _fake_worker(False, "a" * 16, 3.0)
    # Newest-leased retriable task goes first.
    assert pick_oom_victim([idle, old_task, new_task, actor]) is new_task
    # No task workers: actors are last resort.
    assert pick_oom_victim([idle, actor]) is actor
    # Nothing killable.
    assert pick_oom_victim([idle]) is None
    dead = _fake_worker(True, None, 9.0)
    dead.dead = True
    assert pick_oom_victim([idle, dead, old_task]) is old_task


def test_external_uri_spilling(tmp_path):
    """Spill to an external URI backend (reference:
    _private/external_storage.py:72 spill-to-URI): objects leave the node
    dir entirely and restore from the backend."""
    spill_root = tmp_path / "ext_spill"
    cfg = Config()
    cfg.object_store_memory = 8 * 1024 * 1024
    cfg.object_spilling_uri = f"file://{spill_root}"
    ray_tpu.init(num_cpus=2, config=cfg)
    try:
        blobs = [np.full(1_000_000, i, np.uint8) for i in range(16)]
        refs = [ray_tpu.put(b) for b in blobs]
        import time

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if spill_root.exists() and any(spill_root.iterdir()):
                break
            time.sleep(0.2)
        assert spill_root.exists() and any(spill_root.iterdir()), \
            "no objects landed in the external store"
        for i, r in enumerate(refs):
            got = ray_tpu.get(r, timeout=60)
            assert got[0] == i and len(got) == 1_000_000
    finally:
        ray_tpu.shutdown()


def test_custom_scheme_registration(tmp_path):
    """Third-party schemes plug in via register_scheme (the reference's
    smart_open/S3 analog)."""
    from ray_tpu._private import external_storage as ext

    calls = []

    class FakeCloud(ext.ExternalStorage):
        def __init__(self, base):
            self.dir = str(tmp_path / "cloud")
            import os

            os.makedirs(self.dir, exist_ok=True)

        def put(self, key, data):
            calls.append(("put", key))
            with open(f"{self.dir}/{key}", "wb") as f:
                f.write(data)
            return f"fakes3://bucket/{key}"

        def get(self, uri):
            key = uri.rsplit("/", 1)[1]
            with open(f"{self.dir}/{key}", "rb") as f:
                return f.read()

        def delete(self, uri):
            calls.append(("delete", uri))

    ext.register_scheme("fakes3", FakeCloud)
    try:
        backend = ext.storage_for("fakes3://bucket/prefix")
        uri = backend.put("k1", b"hello")
        assert backend.get(uri) == b"hello"
        assert calls[0] == ("put", "k1")
    finally:
        ext._SCHEMES.pop("fakes3", None)


def test_spill_churn_under_pressure_no_object_loss():
    """Stress: put/get churn with dropped refs in a small arena — spills,
    restores, and frees interleave; every LIVE ref must stay readable
    (regression net for a once-observed ObjectLostError under exactly
    this pattern)."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private.config import Config

    cfg = Config()
    cfg.object_store_memory = 48 << 20
    ray_tpu.init(num_cpus=2, config=cfg)
    rng = np.random.default_rng(7)
    try:
        live: list = []
        for i in range(30):
            arr = np.full(1 << 20, i, dtype=np.float64)  # 8 MiB
            ref = ray_tpu.put(arr)
            live.append((i, ref))
            # Drop a random live ref ~half the time (free churn).
            if len(live) > 3 and rng.random() < 0.5:
                live.pop(int(rng.integers(0, len(live))))
            # Read a random live ref every iteration (restore churn).
            j, r = live[int(rng.integers(0, len(live)))]
            out = ray_tpu.get(r, timeout=120)
            assert out[0] == j and out[-1] == j
        for j, r in live:
            out = ray_tpu.get(r, timeout=120)
            assert out[0] == j and len(out) == 1 << 20
    finally:
        ray_tpu.shutdown()
