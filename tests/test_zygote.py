"""Fork-server (zygote) protocol tests, no cluster needed: spawn
replies, per-request error isolation (a bad request must NOT kill the
template — its death would SIGTERM every forked worker), and shutdown
child reaping."""

import json
import os
import socket
import subprocess
import sys
import time

import pytest


@pytest.fixture()
def zygote(tmp_path):
    sock_path = str(tmp_path / "zy.sock")
    env = dict(os.environ)
    env["RAY_TPU_ZYGOTE_SOCKET"] = sock_path
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.worker_zygote"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.monotonic() + 120
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    while True:
        try:
            s.connect(sock_path)
            break
        except OSError:
            if time.monotonic() > deadline:
                proc.kill()
                raise TimeoutError("zygote never became ready")
            time.sleep(0.2)
    f = s.makefile("rwb")
    yield proc, f, tmp_path
    try:
        s.close()
    finally:
        proc.kill()
        proc.wait(timeout=10)


def _rpc(f, obj):
    f.write((json.dumps(obj) + "\n").encode())
    f.flush()
    return json.loads(f.readline())


def test_spawn_error_reply_and_shutdown_reaping(zygote):
    proc, f, tmp_path = zygote
    # A malformed request yields an ERROR REPLY, not a dead template.
    f.write(b"this is not json\n")
    f.flush()
    assert "error" in json.loads(f.readline())
    assert proc.poll() is None

    # A real spawn forks a live child (the worker itself will fail to
    # reach its raylet and exit, but the fork + pid reply must work).
    log = str(tmp_path / "w.log")
    resp = _rpc(f, {"env": {
        "RAY_TPU_WORKER_ID": "w" * 40, "RAY_TPU_NODE_ID": "n" * 40,
        "RAY_TPU_RAYLET_HOST": "127.0.0.1", "RAY_TPU_RAYLET_PORT": "1",
        "RAY_TPU_GCS_HOST": "127.0.0.1", "RAY_TPU_GCS_PORT": "1",
        "RAY_TPU_STORE_PATH": str(tmp_path / "store"),
        "RAY_TPU_SESSION_DIR": str(tmp_path),
    }, "log_path": log})
    pid = resp["pid"]
    assert pid > 0
    # Template still healthy after serving errors AND spawns.
    resp2 = _rpc(f, {"env": {"RAY_TPU_WORKER_ID": "x" * 40,
                             "RAY_TPU_NODE_ID": "n" * 40,
                             "RAY_TPU_RAYLET_HOST": "127.0.0.1",
                             "RAY_TPU_RAYLET_PORT": "1",
                             "RAY_TPU_GCS_HOST": "127.0.0.1",
                             "RAY_TPU_GCS_PORT": "1",
                             "RAY_TPU_STORE_PATH": str(tmp_path / "store"),
                             "RAY_TPU_SESSION_DIR": str(tmp_path)},
                    "log_path": log})
    assert resp2["pid"] > 0 and resp2["pid"] != pid

    # Shutdown request: zygote exits and reaps any still-live children.
    f.write((json.dumps({"shutdown": True}) + "\n").encode())
    f.flush()
    proc.wait(timeout=30)
    deadline = time.monotonic() + 30
    for p in (pid, resp2["pid"]):
        while time.monotonic() < deadline:
            try:
                os.kill(p, 0)
            except ProcessLookupError:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"forked child {p} outlived the zygote")
