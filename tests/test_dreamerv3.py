"""DreamerV3 world-model + imagination actor-critic.

Parity: reference rllib/algorithms/dreamerv3/ (the one model-based family
with current relevance — VERDICT r4 missing #4). Learning regression on
the CPU backend with XS-scale nets."""

import numpy as np


def test_dreamerv3_world_model_shapes():
    from ray_tpu.rllib import DreamerV3Config

    algo = (DreamerV3Config().environment("CartPole-v1")
            .training(deter=32, hidden=32, stoch_groups=4, stoch_classes=4,
                      env_steps_per_iter=64, updates_per_iter=1,
                      warmup_steps=32, batch_size=4, batch_length=8,
                      imag_horizon=5)
            .build())
    r = algo.train()
    assert r["timesteps_total"] == 64
    assert r["num_updates"] == 1
    assert np.isfinite(r["wm_loss"])
    assert np.isfinite(r["actor_loss"])
    assert np.isfinite(r["critic_loss"])
    # KL with free bits can never drop below the floor.
    assert r["kl_dyn"] >= algo.config.free_bits - 1e-5
    a = algo.compute_single_action(np.zeros(4, np.float32))
    assert a in (0, 1)


def test_dreamerv3_replay_sequences_respect_episode_starts():
    from ray_tpu.rllib.dreamerv3 import _SeqReplay

    rep = _SeqReplay(100, 4, 2)
    for ep in range(5):
        for t in range(10):
            rep.add(np.full(4, ep, np.float32), 0, 1.0, 1.0,
                    1.0 if t == 0 else 0.0)
    batch = rep.sample(np.random.default_rng(0), 8, 6)
    assert batch["obs"].shape == (8, 6, 4)
    assert batch["is_first"].shape == (8, 6)
    # Episode boundaries appear in sampled windows as is_first flags.
    assert batch["is_first"].sum() >= 1


def test_dreamerv3_improves_cartpole():
    from ray_tpu.rllib import DreamerV3Config

    algo = (DreamerV3Config().environment("CartPole-v1")
            .training(deter=64, hidden=64, stoch_groups=4, stoch_classes=8,
                      env_steps_per_iter=400, updates_per_iter=25,
                      warmup_steps=400, batch_size=8, batch_length=16,
                      imag_horizon=10, model_lr=3e-3, actor_lr=1e-3,
                      critic_lr=1e-3)
            .build())
    hist = []
    for _ in range(10):
        r = algo.train()
        if np.isfinite(r.get("episode_reward_mean", float("nan"))):
            hist.append(r["episode_reward_mean"])
    assert len(hist) >= 4, f"too few reporting iters: {hist}"
    early = np.mean(hist[:2])
    late = np.mean(hist[-2:])
    assert late > early + 5, \
        f"DreamerV3 failed to improve: early={early:.1f} late={late:.1f} " \
        f"({hist})"
