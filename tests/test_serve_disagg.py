"""Disaggregated serving tests: prefix cache semantics, prefill→decode
KV handoff correctness against the one-shot Generator reference, the
two-pool e2e with device-plane route proof, and per-pool autoscaling on
replica-reported metrics (reference model: Serve LLM apps over
vLLM-style disaggregated prefill/decode engine pools)."""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.models.generate import Generator, SamplingParams
from ray_tpu.models.llama import LlamaConfig, LlamaModel
from ray_tpu.serve.llm import LLMEngine, _Prefilled
from ray_tpu.serve.llm_disagg import PrefillEngine, PrefixCache
from ray_tpu.test_utils import wait_for_condition


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    cfg = LlamaConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq_len=128,
                      dtype=jnp.float32, attention="reference", remat=False)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return cfg, params


@pytest.fixture
def collective_env():
    """Force the collective route on the CPU backend — set BEFORE ray
    init so spawned replica workers inherit it."""
    os.environ["RAY_TPU_DEVICE_COLLECTIVE"] = "1"
    yield
    os.environ.pop("RAY_TPU_DEVICE_COLLECTIVE", None)


def _reference_greedy(cfg, params, prompt, n_new):
    gen = Generator(cfg, params, batch=1, max_len=len(prompt) + n_new)
    return gen.generate(np.asarray([prompt], np.int32),
                        SamplingParams(max_new_tokens=n_new))[0].tolist()


# ---------------------------------------------------------------------------
# Prefix cache (pure unit)
# ---------------------------------------------------------------------------


def test_prefix_cache_hit_miss_eviction():
    cache = PrefixCache(max_entries=2)
    kv = [(np.zeros((2, 3, 4)), np.zeros((2, 3, 4)))]
    logits = np.zeros(8)

    hit, _ = cache.lookup([1, 2, 3])
    assert hit == "miss"
    cache.insert([1, 2, 3], kv, logits)
    hit, entry = cache.lookup([1, 2, 3])
    assert hit == "full" and entry["prefix_len"] == 3
    # A cached prompt that is a strict prefix of the query → partial.
    hit, entry = cache.lookup([1, 2, 3, 9, 9])
    assert hit == "partial" and entry["prefix_len"] == 3
    # Longest strict prefix wins.
    cache.insert([1, 2, 3, 9], kv, logits)
    hit, entry = cache.lookup([1, 2, 3, 9, 9])
    assert hit == "partial" and entry["prefix_len"] == 4
    # Sharing a prefix is not enough — the CACHED prompt must be the
    # prefix ([1,2,3,9] is not a prefix of [1,2,4]).
    hit, _ = cache.lookup([1, 2, 4])
    assert hit == "miss"
    # Bounded: inserting a third entry evicts the LRU one.
    cache.insert([7, 7, 7], kv, logits)
    assert cache.stats()["entries"] == 2
    assert cache.stats()["evictions"] == 1
    stats = cache.stats()
    assert stats["hits"] == 3 and stats["misses"] == 2
    assert 0 < stats["hit_rate"] < 1


def test_prefix_cache_full_hit_skips_prefill(tiny_model):
    """A repeated prompt reuses cached KV + last logits: the compiled
    prefill program is NOT invoked, and the handed-off stream still
    matches the reference exactly."""
    cfg, params = tiny_model
    pe = PrefillEngine(cfg, params, max_len=96)
    cache = PrefixCache(8)
    eng = LLMEngine(cfg, params, max_batch=2, max_len=96)
    calls = {"one": 0, "suffix": 0}
    real_one, real_suffix = pe._prefill_one, pe._prefill_suffix

    def count_one(*a):
        calls["one"] += 1
        return real_one(*a)

    def count_suffix(*a):
        calls["suffix"] += 1
        return real_suffix(*a)

    pe._prefill_one, pe._prefill_suffix = count_one, count_suffix
    try:
        prompt = [1, 5, 9, 2, 7]
        sp = SamplingParams(max_new_tokens=12)
        expected = _reference_greedy(cfg, params, prompt, 12)

        def run(expect_hit):
            out = pe.prefill(np.asarray(prompt), sp, cache)
            assert out["prefix_hit"] == expect_hit
            pack = _Prefilled(out["kv"], out["first_token"],
                              out["prompt_len"], out["kv_len"], 0, [],
                              emit_first=True)
            assert eng.submit_prefilled(pack, sp).tokens() == expected

        run("miss")
        assert calls == {"one": 1, "suffix": 0}
        run("full")  # hit: no prefill program ran
        assert calls == {"one": 1, "suffix": 0}
        # Extension of a cached prompt: only the SUFFIX program runs.
        ext = prompt + [3, 8]
        out = pe.prefill(np.asarray(ext), sp, cache)
        assert out["prefix_hit"] == "partial"
        assert calls == {"one": 1, "suffix": 1}
        pack = _Prefilled(out["kv"], out["first_token"], out["prompt_len"],
                          out["kv_len"], 0, [], emit_first=True)
        assert eng.submit_prefilled(pack, sp).tokens() == \
            _reference_greedy(cfg, params, ext, 12)
    finally:
        eng.shutdown()


def test_prefilled_handoff_into_paged_engine(tiny_model):
    """The prefill-pool KV lands in a paged decode engine's pools via
    submit_prefilled and decodes to the exact reference output."""
    cfg, params = tiny_model
    pe = PrefillEngine(cfg, params, max_len=96)
    eng = LLMEngine(cfg, params, max_batch=2, max_len=96, page_size=16,
                    kv_pool_tokens=96 * 4)
    try:
        prompt = [4, 4, 6, 2, 9, 1, 3]
        sp = SamplingParams(max_new_tokens=10)
        out = pe.prefill(np.asarray(prompt), sp, None)
        pack = _Prefilled(out["kv"], out["first_token"], out["prompt_len"],
                          out["kv_len"], 0, [], emit_first=True)
        assert eng.submit_prefilled(pack, sp).tokens() == \
            _reference_greedy(cfg, params, prompt, 10)
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# Two-pool e2e
# ---------------------------------------------------------------------------


@pytest.mark.smoke
def test_disagg_two_pools_collective_route(tiny_model, collective_env,
                                           ray_start_regular):
    """Acceptance scenario: ≥2 prefill + ≥2 decode replicas complete a
    concurrent-stream workload; the decode-side route counters prove the
    KV handoff used the device plane (collective) and NEVER the
    consumer-side host path; prefix-cache hit rate > 0 on repeated
    prompts."""
    from ray_tpu import serve
    from ray_tpu.serve import llm_disagg

    cfg, params = tiny_model
    h = llm_disagg.deploy_disagg(
        cfg, params, prefill_replicas=2, decode_replicas=2,
        max_batch=2, max_len=96,
        prefill_actor_options={"num_cpus": 0},
        decode_actor_options={"num_cpus": 0})
    try:
        prompts = [[1, 5, 9, 2, 7], [4, 4, 6], [1, 5, 9, 2, 7],
                   [1, 5, 9, 2, 7, 3, 8]]  # repeat + extension → cache hits
        expected = [_reference_greedy(cfg, params, p, 10) for p in prompts]
        results = [None] * len(prompts)

        def consume(i):
            results[i] = h.generate({"prompt_tokens": prompts[i],
                                     "max_new_tokens": 10})

        threads = [threading.Thread(target=consume, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert results == expected
        pm = h.pool_metrics()
        hits = sum(m.get("prefix_cache_hits", 0) for m in pm["prefill"])
        assert hits > 0, pm["prefill"]
        collective = sum(m["plane_counters"].get("collective", 0)
                         for m in pm["decode"])
        host = sum(m["plane_counters"].get("host_fallback", 0)
                   for m in pm["decode"])
        assert collective > 0, pm["decode"]
        assert host == 0, pm["decode"]
        assert h.stats["completed"] == len(prompts)
        assert h.stats["resumes"] == 0
    finally:
        serve.shutdown()


@pytest.mark.smoke
def test_disagg_per_pool_autoscaling(tiny_model, ray_start_regular):
    """Each pool scales on ITS OWN replica-reported signal: a burst of
    slow-drained streams pushes prefill TTFT and decode tokens_in_flight
    over their targets, the controller grows both pools independently,
    and once the load drains the decode pool (short downscale delay)
    returns to min while prefill (long delay) stays scaled out."""
    from ray_tpu import serve
    from ray_tpu.serve import llm_disagg

    cfg, params = tiny_model
    h = llm_disagg.deploy_disagg(
        cfg, params, prefill_replicas=1, decode_replicas=1,
        max_batch=4, max_len=96,
        # TTFT includes queue wait + first-touch compile, and the
        # replica's TTFT deque keeps it observable after the burst —
        # queue_depth on a tiny CPU model drains between controller
        # ticks and would flake.
        prefill_autoscaling={"min_replicas": 1, "max_replicas": 2,
                             "metric": "ttft_p99_ms", "target_value": 25.0,
                             "look_back_period_s": 30.0,
                             "upscale_delay_s": 0.0,
                             "downscale_delay_s": 600.0},
        decode_autoscaling={"min_replicas": 1, "max_replicas": 2,
                            "metric": "tokens_in_flight",
                            "target_value": 16.0,
                            "look_back_period_s": 4.0,
                            "upscale_delay_s": 0.0,
                            "downscale_delay_s": 6.0},
        prefill_actor_options={"num_cpus": 0},
        decode_actor_options={"num_cpus": 0})
    try:
        prompt = [1, 5, 9, 2, 7]
        expected = _reference_greedy(cfg, params, prompt, 48)
        outs = [None] * 6

        def consume(i):
            acc = []
            for tok in h.stream({"prompt_tokens": prompt,
                                 "max_new_tokens": 48}):
                acc.append(tok)
                time.sleep(0.05)  # slow drain keeps tokens_in_flight high
            outs[i] = acc

        threads = [threading.Thread(target=consume, args=(i,))
                   for i in range(len(outs))]
        for t in threads:
            t.start()
        wait_for_condition(
            lambda: len(h._prefill._get_replicas()) == 2, timeout=90)
        wait_for_condition(
            lambda: len(h._decode._get_replicas()) == 2, timeout=90)
        for t in threads:
            t.join(timeout=120)
        assert all(o == expected for o in outs)
        # Load gone: decode's signal decays and it scales back to min.
        wait_for_condition(
            lambda: len(h._decode._get_replicas()) == 1, timeout=90)
        # Prefill (600s downscale delay) must still be scaled out —
        # proof the two pools act on independent signals.
        assert len(h._prefill._get_replicas()) == 2
    finally:
        serve.shutdown()
