"""graftgen tier-1 gates (issue 18).

Three layers:

  1. Regenerate-and-diff: `src/generated/contract_gen.h` must be byte-
     identical to what gen.py emits from docs/wire_contract.json, and
     emission must be deterministic.  This is the "generated output is
     checked in" contract — drift fails tier-1, not just `make lint`.
  2. The G1 gate itself: registry-parity hard errors (contract replay
     class / mutating flag vs rpc.SESSION_EXEMPT_METHODS /
     REPLAY_IDEMPOTENT / GCS _MUTATING), hand-edit detection inside the
     `// graftgen: generated` fences (content-sha256 stamp), and
     staleness against a modified contract — all exercised on throwaway
     repo roots so the real tree stays untouched.
  3. The Python<->native differential replay test: the same stamped
     (sid, rseq) CreateActor frame is sent, then replayed byte-for-byte,
     against BOTH the asyncio rpc.RpcServer and the native lease plane
     in sim mode.  Each server must answer the replay from its reply
     cache byte-identically to its original response, execute exactly
     once, and the two servers' response frames must match each other
     byte-for-byte — the generated SessionManager honoring rpc.py's
     replay classes exactly is the tentpole's core safety claim.
"""

import asyncio
import copy
import socket
import struct
import subprocess
import sys

import pytest

from ray_tpu._private import rpc
from ray_tpu._private.lint import gen


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# 1. regenerate-and-diff byte stability
# ---------------------------------------------------------------------------


def test_generated_header_is_byte_fresh():
    """The checked-in header equals a fresh generation, byte for byte."""
    contract = gen.load_contract()
    fresh = gen.generate(contract)
    with open(gen.GENERATED_HEADER, encoding="utf-8") as f:
        checked_in = f.read()
    assert fresh == checked_in, (
        "src/generated/contract_gen.h is stale against "
        "docs/wire_contract.json — run `make gen`")


def test_generation_is_deterministic():
    contract = gen.load_contract()
    assert gen.generate(contract) == gen.generate(gen.load_contract())


def test_gen_check_cli():
    """`python -m ray_tpu._private.lint.gen --check` (the `make gen-check`
    / `make lint` prerequisite) passes on the committed tree."""
    res = subprocess.run(
        [sys.executable, "-m", "ray_tpu._private.lint.gen", "--check"],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert "graftgen: OK" in res.stderr


def test_generated_header_shape():
    """Structural spot-checks: fences, stamp, and the tables the native
    planes compile against."""
    with open(gen.GENERATED_HEADER, encoding="utf-8") as f:
        text = f.read()
    assert gen.FENCE_BEGIN in text and gen.FENCE_END in text
    assert "// graftgen: content-sha256=" in text
    contract = gen.load_contract()
    assert f"kNumMethods = {len(contract['methods'])}" in text
    # Replay classes straight from the contract.
    assert '{"KVPut", kReplayExempt' in text
    assert '{"RegisterActor", kReplayCached, true' in text
    # Required-field table mirrors common.require_fields call sites.
    req = contract["methods"]["RegisterActor"]["required_fields"]
    assert req, "RegisterActor lost its required fields in the contract"
    for field in req:
        assert f'"{field}"' in text


# ---------------------------------------------------------------------------
# 2. the G1 gate: registry parity, hand-edit fences, staleness
# ---------------------------------------------------------------------------


def test_cross_check_clean_on_live_tree():
    assert gen.cross_check(gen.load_contract()) == []


def test_cross_check_rejects_replay_class_flip_to_exempt():
    """A contract claiming a cached method is idempotent-exempt (without
    the registry agreeing) is a hard gen error — codegen would bake
    blind-replay into C++ for a non-idempotent method."""
    bad = copy.deepcopy(gen.load_contract())
    assert bad["methods"]["RegisterActor"]["replay"] == "cached"
    bad["methods"]["RegisterActor"]["replay"] = "idempotent-exempt"
    errors = gen.cross_check(bad)
    assert any("RegisterActor" in e and "SESSION_EXEMPT_METHODS" in e
               for e in errors), errors


def test_cross_check_rejects_dropped_exemption():
    bad = copy.deepcopy(gen.load_contract())
    assert bad["methods"]["KVPut"]["replay"] == "idempotent-exempt"
    bad["methods"]["KVPut"]["replay"] = "cached"
    errors = gen.cross_check(bad)
    assert any("KVPut" in e for e in errors), errors


def test_cross_check_rejects_mutating_flip():
    bad = copy.deepcopy(gen.load_contract())
    orig = bool(bad["methods"]["RegisterActor"].get("mutating"))
    bad["methods"]["RegisterActor"]["mutating"] = not orig
    errors = gen.cross_check(bad)
    assert any("RegisterActor" in e and "mutating" in e
               for e in errors), errors


def test_cross_check_rejects_unknown_replay_class():
    bad = copy.deepcopy(gen.load_contract())
    bad["methods"]["GetActorInfo"]["replay"] = "best-effort"
    errors = gen.cross_check(bad)
    assert any("unknown replay class" in e for e in errors), errors


def _tmp_tree(tmp_path, header_text, contract=None):
    """Build a throwaway repo root for lint_generated()."""
    gen_dir = tmp_path / "src" / "generated"
    gen_dir.mkdir(parents=True)
    (gen_dir / "contract_gen.h").write_text(header_text, encoding="utf-8")
    if contract is not None:
        docs = tmp_path / "docs"
        docs.mkdir()
        import json

        (docs / "wire_contract.json").write_text(
            json.dumps(contract, indent=2, sort_keys=True) + "\n",
            encoding="utf-8")
    return str(tmp_path)


def test_fence_hand_edit_is_detected(tmp_path):
    """One byte edited inside the generated fences breaks the sha256
    stamp: the graftlint G1 rule that forbids hand-edits."""
    with open(gen.GENERATED_HEADER, encoding="utf-8") as f:
        text = f.read()
    edited = text.replace("kReplayCached = 0", "kReplayCached = 7")
    assert edited != text
    errors = gen.lint_generated(_tmp_tree(tmp_path, edited))
    assert any("edited by hand" in e and "sha256" in e
               for e in errors), errors


def test_missing_stamp_is_detected(tmp_path):
    with open(gen.GENERATED_HEADER, encoding="utf-8") as f:
        lines = f.read().splitlines(keepends=True)
    stripped = "".join(l for l in lines
                       if not l.startswith("// graftgen: content-sha256="))
    errors = gen.lint_generated(_tmp_tree(tmp_path, stripped))
    assert any("missing its content-sha256 stamp" in e
               for e in errors), errors


def test_stale_header_is_detected(tmp_path):
    """A header generated from YESTERDAY'S contract fails the
    regenerate-and-diff gate once the contract moves (here: a required
    field added to RegisterActor) even though the stamp is internally
    consistent."""
    old = copy.deepcopy(gen.load_contract())
    old["methods"]["RegisterActor"]["required_fields"] = list(
        old["methods"]["RegisterActor"]["required_fields"]) + ["extra"]
    stale_header = gen.generate(old)
    # The stamp itself is fine — only the diff against the (unmodified)
    # contract catches it.
    root = _tmp_tree(tmp_path, stale_header, contract=gen.load_contract())
    errors = gen.lint_generated(root)
    assert not any("edited by hand" in e for e in errors), errors
    assert any("stale" in e for e in errors), errors


def test_clean_tree_lints_clean(tmp_path):
    with open(gen.GENERATED_HEADER, encoding="utf-8") as f:
        text = f.read()
    root = _tmp_tree(tmp_path, text, contract=gen.load_contract())
    assert gen.lint_generated(root) == []


# ---------------------------------------------------------------------------
# 3. Python <-> native differential replay
# ---------------------------------------------------------------------------

def _native_available():
    try:
        from ray_tpu._private import native_fastpath

        return native_fastpath.available()
    except Exception:
        return False


def _frame(body: bytes) -> bytes:
    return struct.pack(">I", len(body)) + body


def _create_actor_frame(seq: int, sid: str, rseq: int,
                        epoch: int | None = None) -> bytes:
    """One stamped CreateActor request, bytes fixed across both servers
    and across the original send and the replay. `epoch` mimics the
    client echoing a learned incarnation epoch on a REPLAYED send."""
    payload = {
        "actor_id": "diff-actor-1",
        "spec": b"\x01spec-bytes",
        "_session": sid,
        "_rseq": rseq,
        "_acked": 0,
    }
    if epoch is not None:
        payload["_epoch"] = epoch
    return _frame(rpc.pack([rpc.MSG_REQUEST, seq, "CreateActor", payload]))


async def _python_exchange(frames: list[bytes], n_responses: int):
    """Send raw frames to a live rpc.RpcServer; return the raw response
    bodies (length prefix stripped) in arrival order."""
    calls = {"n": 0}

    def create_actor(conn, payload):
        calls["n"] += 1
        return {"ok": True}

    server = rpc.RpcServer({"CreateActor": create_actor}, name="diff-py")
    host, port = await server.start()
    try:
        reader, writer = await asyncio.open_connection(host, port)
        try:
            for f in frames:
                writer.write(f)
            await writer.drain()
            out = []
            for _ in range(n_responses):
                hdr = await asyncio.wait_for(reader.readexactly(4), 10)
                (n,) = struct.unpack(">I", hdr)
                out.append(await asyncio.wait_for(reader.readexactly(n), 10))
            return out, calls["n"]
        finally:
            writer.close()
    finally:
        await server.stop()


def _native_exchange(frames: list[bytes], n_responses: int,
                     epoch: int | None = None):
    """Same exchange against the native lease plane (sim mode) riding a
    real FastPump.  The plane emits its own outbound ActorReady REQUEST
    (seq >= 1<<40) interleaved with responses — filtered out here, as
    fast_rpc does in production."""
    from ray_tpu._private import native_fastpath
    from ray_tpu._private.native_lease_plane import RayletLeasePlane

    pump = native_fastpath.FastPump()
    plane = RayletLeasePlane(pump, inject_token=3)
    try:
        plane.set_sim(True)
        if epoch is not None:
            plane.set_epoch(epoch)
        plane.install()
        port = pump.listen("127.0.0.1", 0)
        with socket.create_connection(("127.0.0.1", port), timeout=10) as sk:
            sk.settimeout(10)
            for f in frames:
                sk.sendall(f)
            out = []
            while len(out) < n_responses:
                hdr = b""
                while len(hdr) < 4:
                    hdr += sk.recv(4 - len(hdr))
                (n,) = struct.unpack(">I", hdr)
                body = b""
                while len(body) < n:
                    body += sk.recv(n - len(body))
                env = rpc.unpack(body)
                if env[0] == rpc.MSG_REQUEST:
                    continue  # the plane's own ActorReady ladder step
                out.append(body)
        handled, fallthrough, deduped = plane.counters()
        return out, handled, deduped
    finally:
        plane.close()
        pump.close()


@pytest.mark.skipif(not _native_available(),
                    reason="native fastpath unavailable")
def test_differential_replay_python_vs_native():
    """Replay the SAME (sid, rseq) CreateActor frame against both
    servers: each answers the replay byte-identically from its reply
    cache, executes once, and the two implementations' response frames
    are byte-identical to each other."""
    seq, rseq = 11, 1
    py_frame = _create_actor_frame(seq, "diff-sess-py", rseq)
    nat_frame = _create_actor_frame(seq, "diff-sess-nat", rseq)

    py_before = rpc.session_stats()["deduped_requests_total"]
    py_out, py_calls = run(_python_exchange([py_frame, py_frame], 2))
    py_deduped = rpc.session_stats()["deduped_requests_total"] - py_before

    # The Python server advertises its process-wide incarnation epoch in
    # every stamped reply; the native plane is installed with the SAME
    # value (gcs/raylet do this at service-factory time), so the reply
    # bytes stay identical.
    epoch = rpc._server_sessions.epoch
    nat_out, nat_handled, nat_deduped = _native_exchange(
        [nat_frame, nat_frame], 2, epoch=epoch)

    # Within each server: the replay is answered byte-identically.
    assert py_out[0] == py_out[1]
    assert nat_out[0] == nat_out[1]
    # At-most-once on both sides.
    assert py_calls == 1
    assert py_deduped == 1
    assert nat_handled == 1
    assert nat_deduped == 1
    # Across servers: identical envelope + result bytes (the sid differs
    # only inside the REQUEST; responses carry none of it).
    assert py_out[0] == nat_out[0], (
        f"python={py_out[0]!r} native={nat_out[0]!r}")
    env = rpc.unpack(py_out[0])
    assert env == [rpc.MSG_RESPONSE, seq, "CreateActor",
                   {"ok": True, "_epoch": epoch}]


@pytest.mark.skipif(not _native_available(),
                    reason="native fastpath unavailable")
def test_differential_replay_across_restart():
    """A replay that crosses a server restart: the frame carries the
    DEAD incarnation's epoch and the restarted server's reply cache has
    no (sid, rseq) entry — both implementations reject it with the SAME
    stale-epoch error bytes instead of wrongly deduping or silently
    re-executing. A replay stamped with the LIVE epoch still executes
    (the restart rehydrated nothing for this sid, so it is new work)."""
    seq, rseq = 31, 5
    dead_epoch = rpc._new_epoch() ^ 0x5A5A  # some other incarnation

    # -- Python: fresh SessionManager = restarted process state. --
    saved = rpc._server_sessions
    rpc._server_sessions = rpc.SessionManager()
    try:
        live_epoch = rpc._server_sessions.epoch
        assert live_epoch != dead_epoch
        stale = _create_actor_frame(seq, "restart-py", rseq,
                                    epoch=dead_epoch)
        fresh = _create_actor_frame(seq + 1, "restart-py", rseq + 1,
                                    epoch=live_epoch)
        py_before = rpc.session_stats()["stale_epoch_rejections_total"]
        py_out, py_calls = run(_python_exchange([stale, fresh], 2))
        py_stale = (rpc.session_stats()["stale_epoch_rejections_total"]
                    - py_before)
    finally:
        rpc._server_sessions = saved

    nat_stale_f = _create_actor_frame(seq, "restart-nat", rseq,
                                      epoch=dead_epoch)
    nat_fresh_f = _create_actor_frame(seq + 1, "restart-nat", rseq + 1,
                                      epoch=live_epoch)
    nat_out, nat_handled, _ = _native_exchange(
        [nat_stale_f, nat_fresh_f], 2, epoch=live_epoch)

    # The Python rejection rides a scheduled task while the executed
    # reply sends inline, so arrival order is not FIFO — pair replies by
    # their wire seq before comparing.
    py_out.sort(key=lambda b: rpc.unpack(b)[1])
    nat_out.sort(key=lambda b: rpc.unpack(b)[1])

    # The pre-restart replay executed NOWHERE; the live-epoch one did.
    assert py_calls == 1
    assert py_stale == 1
    assert nat_handled == 1
    err_py = rpc.unpack(py_out[0])
    assert err_py[0] == rpc.MSG_ERROR and err_py[1] == seq
    assert err_py[3] == rpc.STALE_EPOCH_ERROR
    # Byte-identical rejection and execution across implementations.
    assert py_out[0] == nat_out[0], (
        f"python={py_out[0]!r} native={nat_out[0]!r}")
    assert py_out[1] == nat_out[1], (
        f"python={py_out[1]!r} native={nat_out[1]!r}")
    assert rpc.unpack(py_out[1]) == [
        rpc.MSG_RESPONSE, seq + 1, "CreateActor",
        {"ok": True, "_epoch": live_epoch}]


@pytest.mark.skipif(not _native_available(),
                    reason="native fastpath unavailable")
def test_differential_distinct_rseq_executes_twice():
    """Control for the replay test: bumping rseq (a genuinely new call
    from the same session) executes on both sides — the caches key on
    (sid, rseq), not on the socket or wire seq."""
    f1 = _create_actor_frame(21, "diff2-py", 1)
    f2 = _create_actor_frame(22, "diff2-py", 2)
    py_out, py_calls = run(_python_exchange([f1, f2], 2))
    assert py_calls == 2
    assert rpc.unpack(py_out[0])[1] == 21
    assert rpc.unpack(py_out[1])[1] == 22

    n1 = _create_actor_frame(21, "diff2-nat", 1)
    n2 = _create_actor_frame(22, "diff2-nat", 2)
    nat_out, nat_handled, nat_deduped = _native_exchange([n1, n2], 2)
    assert nat_handled == 2
    assert nat_deduped == 0
    assert {rpc.unpack(b)[1] for b in nat_out} == {21, 22}
