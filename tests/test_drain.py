"""Preemption-aware graceful node drain (raylet._run_drain +
gcs.handle_drain_node): the DRAINING→DRAINED ladder, lease respill,
proactive actor migration, object + pinned-HBM evacuation, the
relocation directory that replaces lineage reconstruction for foreseen
deaths, and the failure-propagation / retry-elsewhere satellites.

Smoke-marked tier-1 gates; each test keeps its cluster small and its
deadlines short so the suite stays inside the tier-1 budget.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.api_internal import get_core_worker
from ray_tpu._private.config import Config
from ray_tpu.cluster_utils import Cluster
from ray_tpu.test_utils import NodePreempter, wait_for_condition

pytestmark = pytest.mark.smoke


def _drain_config() -> Config:
    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.num_heartbeats_timeout = 5
    cfg.worker_lease_timeout_s = 10.0
    cfg.object_store_memory = 64 * 1024 * 1024
    # Idle-pool trimming must not reap a worker holding device pins
    # between task end and the drain (the drain itself pauses trimming,
    # but the pin exists before the drain starts).
    cfg.num_workers_soft_limit = 16
    return cfg


@pytest.fixture
def drain_cluster():
    cluster = Cluster(initialize_head=True, connect=True,
                      head_node_args={"num_cpus": 2},
                      config=_drain_config())
    yield cluster
    cluster.shutdown()


@ray_tpu.remote(resources={"pin": 0.1})
def _slow(x):
    time.sleep(0.5)
    return x * 2


@ray_tpu.remote(resources={"pin": 0.1})
def _blob(i):
    return bytes(bytearray([i & 0xFF])) * (1 << 19)


@ray_tpu.remote(resources={"pin": 0.1})
class _Counter:
    def __init__(self):
        self.n = 0

    def incr(self):
        self.n += 1
        return self.n


def _node_info(node_id):
    return next((n for n in ray_tpu.nodes()
                 if n["node_id"] == node_id), None)


def test_drain_e2e_evacuates_everything(drain_cluster):
    """The acceptance scenario: a 3-node cluster with queued + running
    tasks, a restartable named actor, primary object copies, and an
    HBM-pinned device object all on the drain target. After
    drain(deadline=10) + kill: everything completes with ZERO lineage
    reconstructions and zero client-visible actor errors, and the drain
    stats account for every evacuated item."""
    jnp = pytest.importorskip("jax.numpy")  # noqa: F841
    cluster = drain_cluster
    target = cluster.add_node(num_cpus=4, resources={"pin": 2})
    cluster.wait_for_nodes()
    cw = get_core_worker()

    @ray_tpu.remote(resources={"pin": 0.1}, tensor_transport="device")
    def dev():
        import jax.numpy as jnp

        return jnp.arange(64, dtype=jnp.float32).reshape(8, 8)

    actor = _Counter.options(name="drain-e2e", max_restarts=4).remote()
    assert ray_tpu.get(actor.incr.remote(), timeout=30) == 1
    # Three primary copies on the target: with two surviving peers the
    # round-robin evacuation lands at least one object on the non-head
    # peer, which forces the GCS relocation-directory recovery path.
    blob_refs = [_blob.remote(i) for i in range(3)]
    dev_ref = dev.remote()
    ray_tpu.wait(blob_refs, num_returns=len(blob_refs), timeout=30)
    ray_tpu.wait([dev_ref], timeout=30)
    # Queued + running work that outlives the drain trigger.
    task_refs = [_slow.remote(i) for i in range(8)]

    peer = cluster.add_node(num_cpus=4, resources={"pin": 2})  # noqa: F841
    cluster.wait_for_nodes()

    preempter = NodePreempter(cluster, deadline_s=10, reason="preemption")
    result = preempter.preempt(target)
    assert result.get("ok") and result.get("state") == "DRAINED", result

    info = _node_info(target.node_id)
    stats = info["drain_stats"]
    assert info["state"] == "DRAINED"
    assert info["drain_reason"] == "preemption"
    # Every evacuated item is accounted for.
    assert stats["evacuated_objects"] >= 3, stats
    assert stats["evacuated_bytes"] >= 3 * (1 << 19), stats
    assert stats["evacuated_device_objects"] == 1, stats
    assert stats["migrated_actors"] == 1, stats
    assert stats["unevacuated_objects"] == 0, stats
    assert stats["duration_s"] <= 10 + 5, stats

    # All work completes; no lineage storm, no actor errors.
    assert ray_tpu.get(task_refs, timeout=60) == [i * 2 for i in range(8)]
    for i, ref in enumerate(blob_refs):
        got = ray_tpu.get(ref, timeout=30)
        assert len(got) == 1 << 19 and got[0] == i
    val = ray_tpu.get(dev_ref, timeout=30)
    assert float(np.asarray(val).sum()) == float(np.arange(64).sum())
    assert ray_tpu.get(actor.incr.remote(), timeout=30) >= 1
    assert cw._num_reconstructions == 0
    # With 3 objects round-robined over 2 peers, at least one landed on
    # the non-head peer — recovered through the relocation directory.
    assert cw._num_relocation_recoveries >= 1


def test_drain_deadline_fails_running_lease_retryable(drain_cluster):
    """Work that exceeds the deadline is failed RETRYABLE (killed lease
    → owner retries elsewhere), never infeasible."""
    cluster = drain_cluster
    target = cluster.add_node(num_cpus=2, resources={"pin": 1})
    cluster.wait_for_nodes()
    cw = get_core_worker()

    @ray_tpu.remote(resources={"pin": 0.1}, max_retries=3)
    def stuck(x):
        time.sleep(20.0)
        return x + 1

    ref = stuck.remote(1)
    time.sleep(1.5)  # running on target by now
    cluster.add_node(num_cpus=2, resources={"pin": 1})
    cluster.wait_for_nodes()

    t0 = time.monotonic()
    resp = cluster.drain_node(target, deadline_s=2, reason="preemption")
    assert resp.get("state") == "DRAINED", resp
    assert time.monotonic() - t0 < 15
    stats = _node_info(target.node_id)["drain_stats"]
    assert stats["killed_leases"] == 1, stats
    cluster.remove_node(target)
    assert ray_tpu.get(ref, timeout=90) == 2
    assert cw._num_reconstructions == 0


def test_drain_rejection_is_retry_elsewhere(drain_cluster):
    """Regression (satellite): a lease that races the drain flag used to
    be failed INFEASIBLE by the owner ({"error": "node draining"} with
    no retry classification → _fail_queued_infeasible). It must stay
    pending and complete once capacity exists elsewhere."""
    cluster = drain_cluster
    target = cluster.add_node(num_cpus=1, resources={"pin": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"pin": 0.1})
    def hold(x):
        time.sleep(3.0)
        return x

    @ray_tpu.remote(resources={"pin": 0.1})
    def quick(x):
        return x * 10

    # One running lease occupies the node; the next requests queue at
    # the target raylet (no other node offers "pin").
    running = hold.remote(0)
    time.sleep(1.0)
    queued = [quick.remote(i) for i in range(3)]
    time.sleep(0.5)
    # Drain with nowhere to respill: the queued leases get the
    # {"error": "node draining", "draining": True} rejection.
    resp = cluster.drain_node(target, deadline_s=4, reason="manual",
                              wait=False)
    assert resp.get("ok"), resp
    # New capacity arrives while the owner is in its drain-retry loop.
    cluster.add_node(num_cpus=2, resources={"pin": 1})
    cluster.wait_for_nodes()
    assert ray_tpu.get(queued, timeout=60) == [0, 10, 20]
    assert ray_tpu.get(running, timeout=60) == 0


def test_drain_node_failure_propagates(drain_cluster):
    """Satellite: DrainNode must NOT swallow failures — a caller about
    to terminate a VM needs to know the node never evacuated."""
    cluster = drain_cluster
    cw = get_core_worker()
    resp = cw._run(cw.gcs.call(
        "DrainNode", {"node_id": "deadbeef" * 8, "deadline_s": 5},
        timeout=30))
    assert resp.get("ok") is False
    assert "unknown node" in resp.get("error", "")

    # A dead node is reported as such, not silently "drained".
    doomed = cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes()
    cluster.remove_node(doomed)
    wait_for_condition(
        lambda: not (_node_info(doomed.node_id) or {}).get("alive", True),
        timeout=30)
    resp = cw._run(cw.gcs.call(
        "DrainNode", {"node_id": doomed.node_id, "deadline_s": 5},
        timeout=30))
    assert resp.get("ok") is False
    assert "not alive" in resp.get("error", "")

    # Bad reason is rejected up front.
    resp = cw._run(cw.gcs.call(
        "DrainNode", {"node_id": doomed.node_id, "reason": "because"},
        timeout=30))
    assert resp.get("ok") is False and "reason" in resp.get("error", "")


def test_preemption_sigterm_watcher(drain_cluster, monkeypatch):
    """The preemption-notice path: SIGTERM to a raylet self-initiates a
    GCS-coordinated drain with the platform deadline
    (RAY_TPU_PREEMPTION_DEADLINE_S), reaches DRAINED, evacuates the
    node's objects, and exits 0 — the spot-reclaim lifecycle end to
    end, no operator in the loop."""
    cluster = drain_cluster
    # Inherited by the raylet spawned next — the platform's grace window.
    monkeypatch.setenv("RAY_TPU_PREEMPTION_DEADLINE_S", "5")
    target = cluster.add_node(num_cpus=2, resources={"sig": 1})
    cluster.wait_for_nodes()
    cw = get_core_worker()

    @ray_tpu.remote(resources={"sig": 0.1})
    def payload():
        return bytes(bytearray(1 << 18))

    ref = payload.remote()
    ray_tpu.wait([ref], timeout=30)

    target.preempt()  # the platform's SIGTERM notice
    wait_for_condition(
        lambda: (_node_info(target.node_id) or {}).get("state")
        == "DRAINED", timeout=30)
    info = _node_info(target.node_id)
    assert info["drain_reason"] == "preemption"
    assert info["drain_stats"]["evacuated_objects"] >= 1
    # The raylet exits 0 by itself once DRAINED.
    wait_for_condition(lambda: target.proc.poll() is not None, timeout=30)
    assert target.proc.poll() == 0
    cluster.remove_node(target)  # reap the handle
    assert len(ray_tpu.get(ref, timeout=30)) == 1 << 18
    assert cw._num_reconstructions == 0


def test_drained_death_is_a_non_event(drain_cluster):
    """A DRAINED node's removal must not produce ERROR node-death
    events; the node table keeps the DRAINED state and drain stats
    after death (visible in state.list_nodes / the dashboard)."""
    cluster = drain_cluster
    target = cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    resp = cluster.drain_node(target, deadline_s=5, reason="idle")
    assert resp.get("state") == "DRAINED", resp
    cluster.remove_node(target)
    wait_for_condition(
        lambda: not (_node_info(target.node_id) or {}).get("alive", True),
        timeout=30)
    info = _node_info(target.node_id)
    assert info["state"] == "DRAINED"  # not DEAD: the death was planned
    assert info["drain_reason"] == "idle"
    assert "duration_s" in info["drain_stats"]
    # events: the removal is recorded as INFO, never ERROR.
    from ray_tpu.util import events as events_api

    evs = events_api.list_events(cluster._node.session_dir,
                                 min_severity="ERROR")
    assert not [e for e in evs
                if (e.get("fields") or {}).get("node_id")
                == target.node_id], evs
