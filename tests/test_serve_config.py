"""Declarative serve config deploy (reference: serve/schema.py +
`serve deploy` REST/CLI path)."""

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture()
def serve_cleanup(ray_start_regular):
    yield
    serve.shutdown()


def test_deploy_config_with_overrides(serve_cleanup):
    handles = serve.deploy_config({
        "applications": [{
            "name": "calc",
            "import_path": "tests.serve_test_app:app",
            "route_prefix": "/calc",
            "deployments": [{"name": "Doubler", "num_replicas": 2}],
        }],
    })
    assert set(handles) == {"calc"}
    assert handles["calc"].remote({"v": 20}).result(timeout=60) == 41
    status = serve.status()
    assert status["Doubler"]["target"] == 2
    assert "Pipeline" in status


def test_deploy_config_bad_import(serve_cleanup):
    with pytest.raises((ImportError, AttributeError, ModuleNotFoundError)):
        serve.deploy_config({"applications": [
            {"import_path": "tests.serve_test_app:nope"}]})


def test_deploy_config_validation_and_prune(serve_cleanup):
    base = {"applications": [{
        "name": "calc", "import_path": "tests.serve_test_app:app"}]}
    # Typo'd deployment name errors instead of silently deploying defaults.
    bad = {"applications": [{
        "import_path": "tests.serve_test_app:app",
        "deployments": [{"name": "doubler", "num_replicas": 8}]}]}
    with pytest.raises(ValueError, match="unknown deployment"):
        serve.deploy_config(bad)
    # Unknown key errors too.
    bad2 = {"applications": [{
        "import_path": "tests.serve_test_app:app",
        "deployments": [{"name": "Doubler", "replicas": 8}]}]}
    with pytest.raises(ValueError, match="unknown config keys"):
        serve.deploy_config(bad2)
    # Goal-state semantics: a stray deployment vanishes on re-deploy.
    serve.deploy_config(base)

    @serve.deployment
    def stray(p):
        return p

    serve.run(stray.bind())
    assert "stray" in serve.status()
    serve.deploy_config(base)
    assert "stray" not in serve.status()
    assert "Doubler" in serve.status()


def test_status_does_not_spawn_controller(ray_start_regular):
    assert serve.status() == {}
    import ray_tpu

    with pytest.raises(ValueError):
        ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")


def test_proxies_on_every_node(ray_start_cluster):
    """serve.start_proxies runs an HTTP ingress on each node (reference:
    proxies on every node); requests through either reach replicas."""
    import json
    import urllib.request

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        @serve.deployment
        def echo(p):
            return {"v": p["v"] * 2}

        serve.run(echo.bind())
        proxies = serve.start_proxies(port=0)
        assert len(proxies) == 2
        for node_id, (host, port) in proxies.items():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/echo",
                data=json.dumps({"v": 21}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                body = json.loads(r.read())
            assert body["result"]["v"] == 42, (node_id, body)
    finally:
        serve.shutdown()


def test_start_proxies_idempotent(ray_start_regular):
    """Re-invoking start_proxies keeps the existing healthy proxy rather
    than stacking a duplicate."""
    try:
        @serve.deployment
        def noop(p):
            return p

        serve.run(noop.bind())
        first = serve.start_proxies(port=0)
        second = serve.start_proxies(port=0)
        assert first == second  # same actor, same port
    finally:
        serve.shutdown()


def test_serve_run_cli(ray_start_regular, tmp_path, capsys):
    """`ray_tpu serve run module:deployment` — import, deploy, report
    (reference: the serve CLI's main dev entry), non-blocking mode."""
    import os
    import sys

    from ray_tpu import scripts, serve

    (tmp_path / "my_serve_app.py").write_text(
        "import ray_tpu.serve as serve\n"
        "@serve.deployment\n"
        "class Hello:\n"
        "    def __call__(self, name):\n"
        "        return f'hi {name}'\n"
        "app = Hello.bind()\n")
    old_cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        class _A:
            serve_cmd = "run"
            target = "my_serve_app:app"
            non_blocking = True
            address = None

        rc = scripts.cmd_serve(_A())
        assert rc == 0
        out = capsys.readouterr().out
        assert "running" in out
        h = serve.get_deployment_handle("Hello")
        assert h.remote("x").result() == "hi x"
    finally:
        os.chdir(old_cwd)
        sys.path.remove(str(tmp_path)) if str(tmp_path) in sys.path else None
        sys.modules.pop("my_serve_app", None)
        serve.shutdown()
