"""Declarative serve config deploy (reference: serve/schema.py +
`serve deploy` REST/CLI path)."""

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture()
def serve_cleanup(ray_start_regular):
    yield
    serve.shutdown()


def test_deploy_config_with_overrides(serve_cleanup):
    handles = serve.deploy_config({
        "applications": [{
            "name": "calc",
            "import_path": "tests.serve_test_app:app",
            "route_prefix": "/calc",
            "deployments": [{"name": "Doubler", "num_replicas": 2}],
        }],
    })
    assert set(handles) == {"calc"}
    assert handles["calc"].remote({"v": 20}).result(timeout=60) == 41
    status = serve.status()
    assert status["Doubler"]["target"] == 2
    assert "Pipeline" in status


def test_deploy_config_bad_import(serve_cleanup):
    with pytest.raises((ImportError, AttributeError, ModuleNotFoundError)):
        serve.deploy_config({"applications": [
            {"import_path": "tests.serve_test_app:nope"}]})


def test_deploy_config_validation_and_prune(serve_cleanup):
    base = {"applications": [{
        "name": "calc", "import_path": "tests.serve_test_app:app"}]}
    # Typo'd deployment name errors instead of silently deploying defaults.
    bad = {"applications": [{
        "import_path": "tests.serve_test_app:app",
        "deployments": [{"name": "doubler", "num_replicas": 8}]}]}
    with pytest.raises(ValueError, match="unknown deployment"):
        serve.deploy_config(bad)
    # Unknown key errors too.
    bad2 = {"applications": [{
        "import_path": "tests.serve_test_app:app",
        "deployments": [{"name": "Doubler", "replicas": 8}]}]}
    with pytest.raises(ValueError, match="unknown config keys"):
        serve.deploy_config(bad2)
    # Goal-state semantics: a stray deployment vanishes on re-deploy.
    serve.deploy_config(base)

    @serve.deployment
    def stray(p):
        return p

    serve.run(stray.bind())
    assert "stray" in serve.status()
    serve.deploy_config(base)
    assert "stray" not in serve.status()
    assert "Doubler" in serve.status()


def test_status_does_not_spawn_controller(ray_start_regular):
    assert serve.status() == {}
    import ray_tpu

    with pytest.raises(ValueError):
        ray_tpu.get_actor("SERVE_CONTROLLER", namespace="serve")
