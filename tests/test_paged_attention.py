"""Paged decode attention kernel + page allocator (vLLM block-table idea,
TPU pallas scalar-prefetch kernel; reference serves LLMs through
vLLM-style engines whose core mechanism this is)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.ops.paged_attention import (  # noqa: E402
    PageAllocator, paged_decode_attention)


def _ref_attention(q, keys, values, groups):
    """Dense single-query attention reference (numpy)."""
    H, D = q.shape
    Hkv = keys.shape[1]
    out = np.zeros((H, D), np.float32)
    for h in range(H):
        kvh = h // groups
        scores = (keys[:, kvh, :] @ q[h]) / np.sqrt(D)
        p = np.exp(scores - scores.max())
        p /= p.sum()
        out[h] = p @ values[:, kvh, :]
    return out


@pytest.mark.parametrize("length", [1, 7, 16, 37])
def test_paged_matches_dense(length):
    H, Hkv, D, page = 8, 4, 32, 16
    rng = np.random.default_rng(0)
    q = rng.standard_normal((H, D)).astype(np.float32)
    keys = rng.standard_normal((length, Hkv, D)).astype(np.float32)
    values = rng.standard_normal((length, Hkv, D)).astype(np.float32)

    # Scatter the sequence into a shuffled page pool (P, Hkv, page, D).
    npages = -(-length // page)
    pool_pages = 8
    order = rng.permutation(pool_pages)[:npages]
    k_pool = np.zeros((pool_pages, Hkv, page, D), np.float32)
    v_pool = np.zeros((pool_pages, Hkv, page, D), np.float32)
    for i, pg in enumerate(order):
        chunk = keys[i * page:(i + 1) * page]
        k_pool[pg, :, :len(chunk)] = chunk.transpose(1, 0, 2)
        v_pool[pg, :, :len(chunk)] = \
            values[i * page:(i + 1) * page].transpose(1, 0, 2)
    table = np.concatenate([order, np.full(4 - npages, order[-1])]) \
        if npages < 4 else order[:4]

    out = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(table, jnp.int32), jnp.asarray(length))
    ref = _ref_attention(q, keys, values, groups=H // Hkv)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_paged_batch_vmap():
    """vmap over sequences with DIFFERENT lengths/page tables — the
    continuous-batching decode shape."""
    H, Hkv, D, page = 4, 4, 16, 8
    B, pool_pages, npages = 3, 12, 3
    rng = np.random.default_rng(1)
    lengths = np.array([5, 17, 24], np.int32)
    k_pool = rng.standard_normal((pool_pages, Hkv, page, D)).astype(np.float32)
    v_pool = rng.standard_normal((pool_pages, Hkv, page, D)).astype(np.float32)
    tables = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8]], np.int32)
    qs = rng.standard_normal((B, H, D)).astype(np.float32)

    batched = jax.vmap(paged_decode_attention,
                       in_axes=(0, None, None, 0, 0))
    out = batched(jnp.asarray(qs), jnp.asarray(k_pool), jnp.asarray(v_pool),
                  jnp.asarray(tables), jnp.asarray(lengths))
    assert out.shape == (B, H, D)
    for b in range(B):
        ln = int(lengths[b])
        keys = k_pool[tables[b]].transpose(0, 2, 1, 3).reshape(
            -1, Hkv, D)[:ln]
        values = v_pool[tables[b]].transpose(0, 2, 1, 3).reshape(
            -1, Hkv, D)[:ln]
        ref = _ref_attention(qs[b], keys, values, groups=1)
        np.testing.assert_allclose(np.asarray(out[b]), ref,
                                   rtol=2e-4, atol=2e-4)


def test_page_allocator_lifecycle():
    alloc = PageAllocator(num_pages=8, page_size=16)
    assert alloc.free_pages == 8
    a = alloc.allocate("a", 40)   # 3 pages
    assert len(a) == 3 and alloc.free_pages == 5
    a2 = alloc.allocate("a", 70)  # grow to 5 pages
    assert len(a2) == 5 and a2[:3] == a and alloc.free_pages == 3
    t = alloc.table("a", 8)
    assert list(t[:5]) == a2 and t.shape == (8,)
    with pytest.raises(MemoryError):
        alloc.allocate("b", 16 * 4)  # only 3 free
    alloc.free("a")
    assert alloc.free_pages == 8
    b = alloc.allocate("b", 16 * 4)
    assert len(b) == 4


@pytest.mark.parametrize("fused_heads", [True, False])
def test_paged_batch_kernel_matches_dense(fused_heads):
    """The grid-batched kernel (batch as leading grid axis, per-row
    scratch reset) against the dense reference, with mixed lengths and
    shuffled page tables — the exact shape the paged LLM engine uses.
    Covers BOTH grid strategies: fused all-heads-per-page-step and the
    default head-on-grid (the fused variant becomes the default once it
    passes on-chip Mosaic validation)."""
    H, Hkv, D, page = 8, 4, 32, 8
    B, NP, pool_pages = 3, 5, 32
    rng = np.random.default_rng(1)
    lengths = np.array([3, 17, 40], np.int32)
    q = rng.standard_normal((B, H, D)).astype(np.float32)
    k_pool = np.zeros((pool_pages, Hkv, page, D), np.float32)
    v_pool = np.zeros((pool_pages, Hkv, page, D), np.float32)
    tables = np.zeros((B, NP), np.int32)
    seqs = []
    free = list(rng.permutation(pool_pages))
    for b in range(B):
        L = int(lengths[b])
        keys = rng.standard_normal((L, Hkv, D)).astype(np.float32)
        values = rng.standard_normal((L, Hkv, D)).astype(np.float32)
        seqs.append((keys, values))
        npg = -(-L // page)
        own = [free.pop() for _ in range(npg)]
        for i, pg in enumerate(own):
            chunk = keys[i * page:(i + 1) * page]
            k_pool[pg, :, :len(chunk)] = chunk.transpose(1, 0, 2)
            v_pool[pg, :, :len(chunk)] = \
                values[i * page:(i + 1) * page].transpose(1, 0, 2)
        tables[b] = (own + [own[-1]] * NP)[:NP]

    from ray_tpu.ops.paged_attention import paged_decode_attention_batch

    out = paged_decode_attention_batch(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(lengths),
        fused_heads=fused_heads)
    for b in range(B):
        ref = _ref_attention(q[b], seqs[b][0], seqs[b][1],
                             groups=H // Hkv)
        np.testing.assert_allclose(np.asarray(out)[b], ref,
                                   rtol=2e-4, atol=2e-4)
