"""Build and run the C++ unit tests (src/*_test.cc).

Sanitizer variants (`make test-asan` / `make test-tsan`) are the
race-detection CI story (reference: .bazelrc tsan/asan configs); they run
here only when RAY_TPU_SANITIZE=1 to keep the default suite fast.
"""

import os
import shutil
import subprocess

import pytest

SRC = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", "src"))


def _make(target: str):
    return subprocess.run(["make", target], cwd=SRC, capture_output=True,
                          text=True, timeout=300)


@pytest.mark.skipif(shutil.which("make") is None or shutil.which("g++") is None,
                    reason="native toolchain unavailable")
def test_cpp_unit_tests():
    res = _make("test")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "store_test: OK" in res.stdout
    assert "scheduler_test: OK" in res.stdout
    assert "raylet_core_test: all passed" in res.stdout
    assert "gcs_store_test: all passed" in res.stdout


@pytest.mark.skipif(os.environ.get("RAY_TPU_SANITIZE") != "1",
                    reason="set RAY_TPU_SANITIZE=1 to run sanitizer builds")
@pytest.mark.parametrize("target", ["test-asan", "test-tsan"])
def test_cpp_sanitizers(target):
    res = _make(target)
    assert res.returncode == 0, res.stdout + res.stderr
