"""Build and run the C++ unit tests (src/*_test.cc).

Sanitizer variants (`make test-asan` / `make test-tsan`) are the
race-detection CI story (reference: .bazelrc tsan/asan configs): the
pthread-using libs (object_store, transfer, fastpath, raylet_core) and
the in-pump GCS service — including the malformed-frame robustness test
in gcs_service_test.cc — run under ASan/UBSan and TSan. They are
`slow`-marked (a sanitizer rebuild + run takes minutes), so the tier-1
gate (`-m 'not slow'`) skips them while `pytest -m slow
tests/test_native_units.py` or plain `make test-asan` runs them on
demand.
"""

import os
import shutil
import subprocess

import pytest

SRC = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", "src"))

_toolchain = pytest.mark.skipif(
    shutil.which("make") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable")


def _make(target: str, timeout: int = 300):
    return subprocess.run(["make", target], cwd=SRC, capture_output=True,
                          text=True, timeout=timeout)


@_toolchain
def test_cpp_unit_tests():
    res = _make("test")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "store_test: OK" in res.stdout
    assert "scheduler_test: OK" in res.stdout
    assert "raylet_core_test: all passed" in res.stdout
    assert "gcs_store_test: all passed" in res.stdout
    assert "gcs_service_test: all OK" in res.stdout
    # Native control plane (graftgen, issue 18): the actor-creation
    # ladder and the lease grant/return state machines, including the
    # per-validator malformed-frame fuzz over contractgen::kMethods.
    assert "gcs_actor_test: all OK" in res.stdout
    assert "raylet_lease_test: all OK" in res.stdout


@pytest.mark.slow
@_toolchain
@pytest.mark.parametrize("target", ["test-asan", "test-tsan"])
def test_cpp_sanitizers(target):
    # Separate build dirs (build-asan/build-tsan), so this never
    # poisons the plain `make test` objects. 600s: sanitizer builds
    # compile every test from scratch and run ~4x slower.
    res = _make(target, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    # A sanitizer report aborts the failing test binary (non-zero exit
    # fails the assert above), but be explicit about the big two so a
    # future `halt_on_error=0` environment still fails loudly.
    assert "ERROR: AddressSanitizer" not in res.stdout + res.stderr
    assert "WARNING: ThreadSanitizer" not in res.stdout + res.stderr
