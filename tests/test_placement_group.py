"""Placement group tests (parity: reference
python/ray/tests/test_placement_group*.py tier — creation, ready(),
bundle-scoped scheduling, strategies, removal, and the TPU-first
STRICT_ICI gang strategy)."""

import pytest

import ray_tpu
import ray_tpu.exceptions as exc
from ray_tpu.util.placement_group import (
    placement_group,
    placement_group_table,
    remove_placement_group,
)


def test_pg_ready_and_table(ray_start_regular):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    # Reference-shaped API: ready() returns an ObjectRef resolved once the
    # bundle is reserved (python/ray/util/placement_group.py ready()).
    assert ray_tpu.get(pg.ready(), timeout=30) is True
    assert pg.wait(timeout=10)
    states = {row["pg_id"]: row["state"] for row in placement_group_table()}
    assert states[pg.id.hex()] == "CREATED"
    remove_placement_group(pg)


def test_pg_task_and_actor_in_bundle(ray_start_regular):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert ray_tpu.get(pg.ready(), timeout=30)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return ray_tpu.get_runtime_context().node_id

    @ray_tpu.remote(num_cpus=1)
    class A:
        def node(self):
            return ray_tpu.get_runtime_context().node_id

    nid = ray_tpu.get(
        where.options(placement_group=pg,
                      placement_group_bundle_index=0).remote(),
        timeout=30)
    a = A.options(placement_group=pg, placement_group_bundle_index=1).remote()
    assert ray_tpu.get(a.node.remote(), timeout=30) == nid
    remove_placement_group(pg)


def test_pg_capacity_isolation(ray_start_regular):
    # The PG reserves its bundles: a second PG demanding more CPUs than
    # remain must stay pending, then schedule after the first is removed.
    total = int(ray_tpu.cluster_resources().get("CPU", 0))
    pg1 = placement_group([{"CPU": total}], strategy="PACK")
    assert ray_tpu.get(pg1.ready(), timeout=30)
    pg2 = placement_group([{"CPU": 1}], strategy="PACK")
    assert not pg2.wait(timeout=2)
    remove_placement_group(pg1)
    assert pg2.wait(timeout=30)
    remove_placement_group(pg2)


def test_pg_strict_spread_infeasible(ray_start_regular):
    # One node: STRICT_SPREAD over two bundles can never be satisfied.
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert not pg.wait(timeout=3)
    remove_placement_group(pg)


def test_pg_invalid_args(ray_start_regular):
    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], strategy="DIAGONAL")
    with pytest.raises(ValueError):
        placement_group([])


def test_pg_spread_two_nodes(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.connect()
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes(2)

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert ray_tpu.get(pg.ready(), timeout=60)
    assert len(set(pg.bundle_node_ids())) == 2
    remove_placement_group(pg)


def test_pg_strict_ici(ray_start_cluster):
    """TPU-first: STRICT_ICI places every bundle on ONE ICI-connected
    slice (nodes sharing a tpu-slice label) — the gang-lease unit for
    multi-host SPMD (SURVEY.md §7 stage 3)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, labels={"tpu-slice": "slice-a"})
    cluster.connect()
    cluster.add_node(num_cpus=1, labels={"tpu-slice": "slice-a"})
    cluster.add_node(num_cpus=1, labels={"tpu-slice": "slice-b"})
    cluster.wait_for_nodes(3)

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_ICI")
    assert ray_tpu.get(pg.ready(), timeout=60)
    nodes = pg.bundle_node_ids()
    assert len(set(nodes)) == 2  # two hosts, one slice

    # Three 1-CPU bundles cannot fit on any single slice (slice-a has 2).
    pg_big = placement_group([{"CPU": 1}] * 3, strategy="STRICT_ICI")
    assert not pg_big.wait(timeout=3)
    remove_placement_group(pg_big)
    remove_placement_group(pg)
