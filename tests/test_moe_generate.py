"""MoE model + generation-path tests (CPU, tiny configs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.generate import Generator, SamplingParams, sample_logits
from ray_tpu.models.llama import TINY, LlamaModel
from ray_tpu.models.moe import (
    MOE_RULES,
    TINY_MOE,
    MoEModel,
    count_flops_per_token,
    moe_aux_loss,
)


@pytest.fixture(scope="module")
def tiny_moe():
    cfg = TINY_MOE
    model = MoEModel(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    return cfg, model, params


def test_moe_forward_shape(tiny_moe):
    cfg, model, params = tiny_moe
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert jnp.isfinite(logits).all()


def test_moe_aux_loss_sown(tiny_moe):
    cfg, model, params = tiny_moe
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0,
                                cfg.vocab_size)
    logits, state = model.apply(params, tokens, mutable=["intermediates"])
    aux = moe_aux_loss(state["intermediates"])
    # Perfectly balanced top-k routing gives aux ≈ k * coef; any routing
    # is ≥ coef (Switch eq. 4 lower bound is 1 for f==p uniform).
    assert float(aux) > 0.0
    assert np.isfinite(float(aux))


def test_moe_grads_flow_to_experts(tiny_moe):
    cfg, model, params = tiny_moe
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0,
                                cfg.vocab_size)

    def loss(p):
        logits, state = model.apply(p, tokens, mutable=["intermediates"])
        lp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(lp, tokens[..., None], axis=-1).mean()
        return nll + moe_aux_loss(state["intermediates"])

    grads = jax.grad(loss)(params)
    g = grads["params"]["layers_0"]["moe"]
    # Router and at least some experts must receive gradient.
    assert float(jnp.abs(g["router"]["kernel"]).max()) > 0
    assert float(jnp.abs(g["w_gate"]).max()) > 0
    assert float(jnp.abs(g["w_down"]).max()) > 0


def test_moe_sharded_train_step_on_mesh(tiny_moe):
    """Expert weights shard over ep; one jitted step runs on the 8-dev mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel import MeshConfig, make_mesh

    cfg, model, params = tiny_moe
    mesh = make_mesh(MeshConfig(dp=2, ep=4))
    shardings = MOE_RULES.tree_shardings(mesh, params)
    sharded = jax.tree_util.tree_map(jax.device_put, params, shardings)
    # Expert tensors are actually split over ep.
    wg = sharded["params"]["layers_0"]["moe"]["w_gate"]
    assert wg.sharding.spec[0] == "ep"

    tokens = jax.random.randint(jax.random.PRNGKey(4), (4, 8), 0,
                                cfg.vocab_size)
    tokens = jax.device_put(
        tokens, NamedSharding(mesh, P(("dp", "fsdp"), None)))

    @jax.jit
    def step(p, t):
        logits, state = model.apply(p, t, mutable=["intermediates"])
        lp = jax.nn.log_softmax(logits)
        return (-jnp.take_along_axis(lp, t[..., None], axis=-1).mean()
                + moe_aux_loss(state["intermediates"]))

    val = step(sharded, tokens)
    assert np.isfinite(float(val))


def test_moe_flops_counts_active_params_only():
    dense_ish = count_flops_per_token(TINY_MOE)
    assert dense_ish > 0
    # 2-of-4 routing must cost less than hypothetically running 4 experts.
    all_experts = TINY_MOE.n_experts / TINY_MOE.experts_per_token
    assert dense_ish * all_experts > count_flops_per_token(TINY_MOE)


# ---------------------------------------------------------------------------
# Generation
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = TINY
    model = LlamaModel(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    return cfg, model, params


def test_greedy_generation_matches_full_forward(tiny_llama):
    """Incremental KV-cache decode must equal argmax of full forwards."""
    cfg, model, params = tiny_llama
    prompt = np.array([[5, 9, 2, 7]], np.int32)
    gen = Generator(cfg, params, batch=1, max_len=16)
    out = gen.generate(prompt, SamplingParams(max_new_tokens=4))
    assert out.shape == (1, 4)

    # Reference: grow the sequence, full forward each step, take argmax.
    seq = prompt.copy()
    expected = []
    for _ in range(4):
        logits = model.apply(params, jnp.asarray(seq))
        nxt = int(jnp.argmax(logits[0, -1]))
        expected.append(nxt)
        seq = np.concatenate([seq, [[nxt]]], axis=1)
    assert out[0].tolist() == expected


def test_generation_eos_stops_early(tiny_llama):
    cfg, model, params = tiny_llama
    prompt = np.array([[1, 2]], np.int32)
    gen = Generator(cfg, params, batch=1, max_len=32)
    # Force eos = whatever greedy emits first → stops after 1 token.
    first = gen.generate(prompt, SamplingParams(max_new_tokens=1))[0, 0]
    gen2 = Generator(cfg, params, batch=1, max_len=32)
    out = gen2.generate(prompt, SamplingParams(max_new_tokens=8,
                                               eos_token=int(first)))
    assert out.shape[1] == 1


def test_sample_logits_top_k_and_top_p():
    rng = jax.random.PRNGKey(0)
    logits = jnp.array([[0.0, 1.0, 2.0, 10.0]])
    # Greedy
    assert int(sample_logits(logits, rng, SamplingParams())[0]) == 3
    # top_k=1 always picks argmax even at high temperature.
    sp = SamplingParams(temperature=5.0, top_k=1)
    for i in range(5):
        assert int(sample_logits(logits, jax.random.PRNGKey(i), sp)[0]) == 3
    # top_p tiny → nucleus is just the argmax.
    sp = SamplingParams(temperature=2.0, top_p=0.05)
    for i in range(5):
        assert int(sample_logits(logits, jax.random.PRNGKey(i), sp)[0]) == 3


def test_batched_generation(tiny_llama):
    cfg, model, params = tiny_llama
    prompts = np.array([[5, 9, 2, 7], [1, 1, 1, 1]], np.int32)
    gen = Generator(cfg, params, batch=2, max_len=16)
    out = gen.generate(prompts, SamplingParams(max_new_tokens=3,
                                               temperature=0.7, top_k=8))
    assert out.shape == (2, 3)
    assert (out >= 0).all() and (out < cfg.vocab_size).all()
