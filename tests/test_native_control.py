"""Native control plane (graftgen, issue 18) e2e tests.

Under RAY_TPU_NATIVE_CONTROL=1 the GCS installs the actor plane
(src/gcs_actor.cc) and every raylet installs the lease plane
(src/raylet_lease.cc) into their fastpath pumps: the hot actor-creation
ladder (RegisterActor -> CreateActor -> ActorReady) and the hot lease
grant/return execute on the C++ loop threads, while Python stays the
policy/IO shell — named actors, placement groups, empty worker pools
and every other complex shape fall through per-method to the Python
handlers.

These tests drive a REAL GcsServer (pump transport) with real
rpc.connect_session clients acting as driver and raylet, then the full
stack through ray_tpu.init, asserting (a) the ladder end-state matches
the Python path (actor ALIVE, address mirrored), (b) the frames really
were handled natively (plane counters, stats surface), and (c) the
fallthrough shapes still work.
"""

import asyncio
import os

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu._private.gcs import ACTOR_ALIVE, GcsServer


def _native_control_available() -> bool:
    try:
        from ray_tpu._private import (native_actor_plane, native_fastpath,
                                      native_lease_plane)

        if not native_fastpath.available():
            return False
        native_actor_plane._load()
        native_lease_plane._load()
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(
    not _native_control_available(),
    reason="native control plane unavailable")


def run(coro):
    return asyncio.run(coro)


async def _wait_for(predicate, timeout=10.0, what="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        if predicate():
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(0.02)


NODE_ID = "aa" * 16


async def _fake_raylet(host, port):
    """A connect_session client that registers a node and answers the
    plane's CreateActor ladder: reply ok, then (once the test releases
    it) call ActorReady — the exact raylet-side protocol."""
    created = asyncio.Event()
    create_payloads = []
    sess_box = {}

    def on_create(conn, payload):
        create_payloads.append(payload)
        created.set()
        return {"ok": True}

    sess = await rpc.connect_session(host, port,
                                     handlers={"CreateActor": on_create},
                                     name="fake-raylet")
    sess_box["sess"] = sess
    r = await sess.call("RegisterNode", {
        "host": "127.0.0.1", "node_id": NODE_ID, "raylet_port": 47001,
        "total_resources": {"CPU": 4.0}})
    assert r["ok"]
    return sess, created, create_payloads


def test_actor_ladder_native(tmp_path, monkeypatch):
    """RegisterActor for a simple (nameless) actor runs the native
    ladder: driver acked from C++, CreateActor reaches the raylet with
    the spec bytes intact, ActorReady flips the Python mirror to ALIVE
    — and the Python RegisterActor handler never runs."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            assert gcs._actor_plane is not None, \
                "actor plane should install under RAY_TPU_NATIVE_CONTROL=1"
            raylet, created, create_payloads = await _fake_raylet(host, port)

            driver = await rpc.connect_session(host, port, name="driver")
            r = await driver.call("RegisterActor", {
                "actor_id": "nat-a1", "spec": b"\x01spec-bytes",
                "max_restarts": 0, "class_name": "Counter",
                "job_id": "job-1"})
            assert r["ok"]

            await asyncio.wait_for(created.wait(), 10)
            assert create_payloads[0]["actor_id"] == "nat-a1"
            assert create_payloads[0]["spec"] == b"\x01spec-bytes"

            # Python mirrored the registration off the inject events.
            await _wait_for(lambda: "nat-a1" in gcs.actors,
                            what="actor mirror")
            assert gcs.actors["nat-a1"]["native"] is True

            # ActorReady completes the ladder natively.
            await raylet.call("ActorReady", {
                "actor_id": "nat-a1", "address": ["127.0.0.1", 47002]})
            await _wait_for(
                lambda: gcs.actors["nat-a1"]["state"] == ACTOR_ALIVE,
                what="actor ALIVE")
            a = gcs.actors["nat-a1"]
            assert a["node_id"] == NODE_ID
            assert a["address"] == ["127.0.0.1", 47002]

            # The frames were handled in C++ (RegisterActor + ActorReady
            # at minimum) and surfaced through GetClusterStatus.
            handled, fallthrough, deduped = gcs._actor_plane.counters()
            assert handled >= 2
            assert gcs._actor_plane.proto_errors() == 0
            status = await driver.call("GetClusterStatus", {})
            nc = status["native_control"]
            assert nc["handled_total"] >= 2
            assert "native_fallthrough_total" in nc
            assert nc["actors"] >= 1

            # GetActorInfo (a Python handler) answers from the mirror.
            info = await driver.call("GetActorInfo",
                                     {"actor_id": "nat-a1"})
            assert info["state"] == ACTOR_ALIVE

            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()

    run(main())


def test_named_actor_falls_through_to_python(tmp_path, monkeypatch):
    """A NAMED actor is a complex shape the plane does not own: the
    frame must fall through (counted) and the Python handler must still
    complete the registration."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            raylet, created, create_payloads = await _fake_raylet(host, port)
            driver = await rpc.connect_session(host, port, name="driver")

            _, fb_before, _ = gcs._actor_plane.counters()
            r = await driver.call("RegisterActor", {
                "actor_id": "named-b1", "spec": b"\x02spec",
                "max_restarts": 0, "class_name": "Named",
                "name": "bob", "namespace": "default", "job_id": "job-1"})
            assert r["ok"]
            _, fb_after, _ = gcs._actor_plane.counters()
            assert fb_after > fb_before, \
                "named RegisterActor should fall through to Python"
            # The PYTHON path registered it (no native flag).
            await _wait_for(lambda: "named-b1" in gcs.actors,
                            what="python-side registration")
            assert not gcs.actors["named-b1"].get("native")

            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()

    run(main())


def test_malformed_register_actor_errors_natively(tmp_path, monkeypatch):
    """A RegisterActor missing a generated-validator required field
    ("spec") must come back as a Malformed RpcError from C++ — not
    crash the plane, not silently pass through."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            raylet, _, _ = await _fake_raylet(host, port)
            driver = await rpc.connect_session(host, port, name="driver")
            with pytest.raises(rpc.RpcError, match="malformed"):
                await driver.call("RegisterActor", {"actor_id": "no-spec"})
            assert gcs._actor_plane.proto_errors() == 1
            # The plane still works afterwards.
            r = await driver.call("RegisterActor", {
                "actor_id": "ok-after", "spec": b"\x03s",
                "max_restarts": 0})
            assert r["ok"]
            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()

    run(main())


def test_replay_dedup_across_session(tmp_path, monkeypatch):
    """The same (sid, rseq) RegisterActor replayed over a FRESH socket
    (session rebind, what a reconnect does) must be answered from the
    native reply cache — at-most-once across rebinds."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            raylet, created, _ = await _fake_raylet(host, port)
            driver = await rpc.connect_session(host, port, name="driver")
            assert (await driver.call("RegisterActor", {
                "actor_id": "dup-a1", "spec": b"\x04s",
                "max_restarts": 0}))["ok"]
            await asyncio.wait_for(created.wait(), 10)

            # Kill the driver's socket; the session layer replays over a
            # new connection on the next call after reconnecting — but
            # here we replay the SAME stamped request by hand to pin the
            # server side: same sid, same rseq, fresh socket.
            sid = driver.session_id
            frame = rpc.pack([rpc.MSG_REQUEST, 99, "RegisterActor", {
                "actor_id": "dup-a1", "spec": b"\x04s", "max_restarts": 0,
                "_session": sid, "_rseq": 1, "_acked": 0}])
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(len(frame).to_bytes(4, "big") + frame)
            await writer.drain()
            hdr = await asyncio.wait_for(reader.readexactly(4), 10)
            resp = rpc.unpack(await asyncio.wait_for(
                reader.readexactly(int.from_bytes(hdr, "big")), 10))
            assert resp[0] == rpc.MSG_RESPONSE and resp[3]["ok"]
            writer.close()

            handled, _, deduped = gcs._actor_plane.counters()
            assert deduped >= 1, "replay must hit the native reply cache"
            # Exactly one CreateActor ever reached the raylet.
            await asyncio.sleep(0.2)
            assert gcs._actor_plane.actor_count() == 1

            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()

    run(main())


def test_full_stack_native_control(monkeypatch):
    """ray_tpu.init under RAY_TPU_NATIVE_CONTROL=1: tasks and actors
    (plain + named) behave exactly as under the Python control plane,
    and both daemons report an installed plane that saw the traffic."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")
    from ray_tpu._private.config import Config

    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.num_heartbeats_timeout = 5
    cfg.worker_lease_timeout_s = 10.0
    cfg.object_store_memory = 64 * 1024 * 1024
    ray_tpu.init(num_cpus=2, config=cfg)
    try:
        @ray_tpu.remote
        def double(x):
            return x * 2

        assert ray_tpu.get([double.remote(i) for i in range(8)]) == \
            [i * 2 for i in range(8)]

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote()) == 1
        assert ray_tpu.get(c.inc.remote()) == 2

        named = Counter.options(name="nc-named").remote()
        assert ray_tpu.get(named.inc.remote()) == 1

        # More plain tasks after workers exist: the idle-worker pool is
        # populated, so the lease plane gets grantable shapes.
        assert ray_tpu.get([double.remote(i) for i in range(8)]) == \
            [i * 2 for i in range(8)]

        cw = ray_tpu._private.api_internal.get_core_worker()
        status = cw._run(cw.gcs.call("GetClusterStatus", {}))
        nc = status["native_control"]
        assert nc is not None, "GCS actor plane not installed"
        # Two RegisterActors flowed through the plane's frame hook —
        # handled natively or routed, never invisible.
        assert nc["handled_total"] + nc["native_fallthrough_total"] >= 2
        assert nc["proto_errors"] == 0

        state = cw._run(cw.raylet.call("GetState", {}))
        rnc = state["native_control"]
        assert rnc is not None, "raylet lease plane not installed"
        assert rnc["handled_total"] + rnc["native_fallthrough_total"] >= 1
        assert rnc["proto_errors"] == 0
    finally:
        ray_tpu.shutdown()
