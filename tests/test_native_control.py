"""Native control plane (graftgen, issue 18) e2e tests.

Under RAY_TPU_NATIVE_CONTROL=1 the GCS installs the actor plane
(src/gcs_actor.cc) and every raylet installs the lease plane
(src/raylet_lease.cc) into their fastpath pumps: the hot actor-creation
ladder (RegisterActor -> CreateActor -> ActorReady) and the hot lease
grant/return execute on the C++ loop threads, while Python stays the
policy/IO shell — named actors, placement groups, empty worker pools
and every other complex shape fall through per-method to the Python
handlers.

These tests drive a REAL GcsServer (pump transport) with real
rpc.connect_session clients acting as driver and raylet, then the full
stack through ray_tpu.init, asserting (a) the ladder end-state matches
the Python path (actor ALIVE, address mirrored), (b) the frames really
were handled natively (plane counters, stats surface), and (c) the
fallthrough shapes still work.
"""

import asyncio
import os

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu._private.gcs import ACTOR_ALIVE, GcsServer


def _native_control_available() -> bool:
    try:
        from ray_tpu._private import (native_actor_plane, native_fastpath,
                                      native_lease_plane)

        if not native_fastpath.available():
            return False
        native_actor_plane._load()
        native_lease_plane._load()
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(
    not _native_control_available(),
    reason="native control plane unavailable")


def run(coro):
    return asyncio.run(coro)


async def _wait_for(predicate, timeout=10.0, what="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        if predicate():
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(0.02)


NODE_ID = "aa" * 16


async def _fake_raylet(host, port):
    """A connect_session client that registers a node and answers the
    plane's CreateActor ladder: reply ok, then (once the test releases
    it) call ActorReady — the exact raylet-side protocol."""
    created = asyncio.Event()
    create_payloads = []
    sess_box = {}

    def on_create(conn, payload):
        create_payloads.append(payload)
        created.set()
        return {"ok": True}

    sess = await rpc.connect_session(host, port,
                                     handlers={"CreateActor": on_create},
                                     name="fake-raylet")
    sess_box["sess"] = sess
    r = await sess.call("RegisterNode", {
        "host": "127.0.0.1", "node_id": NODE_ID, "raylet_port": 47001,
        "total_resources": {"CPU": 4.0}})
    assert r["ok"]
    return sess, created, create_payloads


def test_actor_ladder_native(tmp_path, monkeypatch):
    """RegisterActor for a simple (nameless) actor runs the native
    ladder: driver acked from C++, CreateActor reaches the raylet with
    the spec bytes intact, ActorReady flips the Python mirror to ALIVE
    — and the Python RegisterActor handler never runs."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            assert gcs._actor_plane is not None, \
                "actor plane should install under RAY_TPU_NATIVE_CONTROL=1"
            raylet, created, create_payloads = await _fake_raylet(host, port)

            driver = await rpc.connect_session(host, port, name="driver")
            r = await driver.call("RegisterActor", {
                "actor_id": "nat-a1", "spec": b"\x01spec-bytes",
                "max_restarts": 0, "class_name": "Counter",
                "job_id": "job-1"})
            assert r["ok"]

            await asyncio.wait_for(created.wait(), 10)
            assert create_payloads[0]["actor_id"] == "nat-a1"
            assert create_payloads[0]["spec"] == b"\x01spec-bytes"

            # Python mirrored the registration off the inject events.
            await _wait_for(lambda: "nat-a1" in gcs.actors,
                            what="actor mirror")
            assert gcs.actors["nat-a1"]["native"] is True

            # ActorReady completes the ladder natively.
            await raylet.call("ActorReady", {
                "actor_id": "nat-a1", "address": ["127.0.0.1", 47002]})
            await _wait_for(
                lambda: gcs.actors["nat-a1"]["state"] == ACTOR_ALIVE,
                what="actor ALIVE")
            a = gcs.actors["nat-a1"]
            assert a["node_id"] == NODE_ID
            assert a["address"] == ["127.0.0.1", 47002]

            # The frames were handled in C++ (RegisterActor + ActorReady
            # at minimum) and surfaced through GetClusterStatus.
            handled, fallthrough, deduped = gcs._actor_plane.counters()
            assert handled >= 2
            assert gcs._actor_plane.proto_errors() == 0
            status = await driver.call("GetClusterStatus", {})
            nc = status["native_control"]
            assert nc["handled_total"] >= 2
            assert "native_fallthrough_total" in nc
            assert nc["actors"] >= 1

            # GetActorInfo (a Python handler) answers from the mirror.
            info = await driver.call("GetActorInfo",
                                     {"actor_id": "nat-a1"})
            assert info["state"] == ACTOR_ALIVE

            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()

    run(main())


def test_named_actor_falls_through_to_python(tmp_path, monkeypatch):
    """A NAMED actor is a complex shape the plane does not own: the
    frame must fall through (counted) and the Python handler must still
    complete the registration."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            raylet, created, create_payloads = await _fake_raylet(host, port)
            driver = await rpc.connect_session(host, port, name="driver")

            _, fb_before, _ = gcs._actor_plane.counters()
            r = await driver.call("RegisterActor", {
                "actor_id": "named-b1", "spec": b"\x02spec",
                "max_restarts": 0, "class_name": "Named",
                "name": "bob", "namespace": "default", "job_id": "job-1"})
            assert r["ok"]
            _, fb_after, _ = gcs._actor_plane.counters()
            assert fb_after > fb_before, \
                "named RegisterActor should fall through to Python"
            # The PYTHON path registered it (no native flag).
            await _wait_for(lambda: "named-b1" in gcs.actors,
                            what="python-side registration")
            assert not gcs.actors["named-b1"].get("native")

            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()

    run(main())


def test_malformed_register_actor_errors_natively(tmp_path, monkeypatch):
    """A RegisterActor missing a generated-validator required field
    ("spec") must come back as a Malformed RpcError from C++ — not
    crash the plane, not silently pass through."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            raylet, _, _ = await _fake_raylet(host, port)
            driver = await rpc.connect_session(host, port, name="driver")
            with pytest.raises(rpc.RpcError, match="malformed"):
                await driver.call("RegisterActor", {"actor_id": "no-spec"})
            assert gcs._actor_plane.proto_errors() == 1
            # The plane still works afterwards.
            r = await driver.call("RegisterActor", {
                "actor_id": "ok-after", "spec": b"\x03s",
                "max_restarts": 0})
            assert r["ok"]
            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()

    run(main())


def test_replay_dedup_across_session(tmp_path, monkeypatch):
    """The same (sid, rseq) RegisterActor replayed over a FRESH socket
    (session rebind, what a reconnect does) must be answered from the
    native reply cache — at-most-once across rebinds."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            raylet, created, _ = await _fake_raylet(host, port)
            driver = await rpc.connect_session(host, port, name="driver")
            assert (await driver.call("RegisterActor", {
                "actor_id": "dup-a1", "spec": b"\x04s",
                "max_restarts": 0}))["ok"]
            await asyncio.wait_for(created.wait(), 10)

            # Kill the driver's socket; the session layer replays over a
            # new connection on the next call after reconnecting — but
            # here we replay the SAME stamped request by hand to pin the
            # server side: same sid, same rseq, fresh socket.
            sid = driver.session_id
            frame = rpc.pack([rpc.MSG_REQUEST, 99, "RegisterActor", {
                "actor_id": "dup-a1", "spec": b"\x04s", "max_restarts": 0,
                "_session": sid, "_rseq": 1, "_acked": 0}])
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(len(frame).to_bytes(4, "big") + frame)
            await writer.drain()
            hdr = await asyncio.wait_for(reader.readexactly(4), 10)
            resp = rpc.unpack(await asyncio.wait_for(
                reader.readexactly(int.from_bytes(hdr, "big")), 10))
            assert resp[0] == rpc.MSG_RESPONSE and resp[3]["ok"]
            writer.close()

            handled, _, deduped = gcs._actor_plane.counters()
            assert deduped >= 1, "replay must hit the native reply cache"
            # Exactly one CreateActor ever reached the raylet.
            await asyncio.sleep(0.2)
            assert gcs._actor_plane.actor_count() == 1

            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()

    run(main())


async def _fake_raylet_ex(host, port, node_id, on_create=None,
                          handlers=None, reconnect_register=False):
    """Configurable fake raylet: custom CreateActor behavior, extra
    handlers (e.g. Drain), and optional re-registration on session
    reconnect (what the real raylet's _gcs_handshake does)."""
    created = asyncio.Event()
    create_payloads = []

    def default_create(conn, payload):
        create_payloads.append(payload)
        created.set()
        return {"ok": True}

    table = {"CreateActor": on_create or default_create}
    table.update(handlers or {})
    reg_payload = {
        "host": "127.0.0.1", "node_id": node_id, "raylet_port": 47001,
        "total_resources": {"CPU": 4.0}}

    async def _handshake(conn):
        r = await conn.call("RegisterNode", reg_payload, timeout=10)
        assert r["ok"]

    sess = await rpc.connect_session(
        host, port, handlers=table, name=f"fake-raylet-{node_id[:4]}",
        on_reconnect=_handshake if reconnect_register else None)
    r = await sess.call("RegisterNode", reg_payload)
    assert r["ok"]
    return sess, created, create_payloads


def test_create_replay_across_netchaos_flap(tmp_path, monkeypatch):
    """NetChaos flap mid-flight on a native CreateActor: the raylet
    executes the create but its reply is eaten, the link dies, the
    session rebinds and re-registers — the plane resends the SAME
    (sid, rseq) frame and the raylet's reply cache answers it. Exactly
    one actor, exactly one CreateActor execution."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")
    from ray_tpu.test_utils import NetChaos

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        chaos = NetChaos(seed=7).start()
        try:
            phost, pport = chaos.link("gcs", host, port)
            loop = asyncio.get_event_loop()
            executions = []

            def on_create(conn, payload):
                executions.append(payload)
                if len(executions) == 1:
                    # Eat the reply, then drop the link shortly after so
                    # the session redials and the plane replays the
                    # frame over the rebound connection.
                    chaos.partition("gcs")

                    def _flap():
                        chaos.heal("gcs")
                        chaos.cut("gcs")
                    loop.call_later(0.3, _flap)
                return {"ok": True}

            raylet, _, _ = await _fake_raylet_ex(
                phost, pport, NODE_ID, on_create=on_create,
                reconnect_register=True)
            driver = await rpc.connect_session(host, port, name="driver")
            r = await driver.call("RegisterActor", {
                "actor_id": "flap-a1", "spec": b"\x05s",
                "max_restarts": 0, "class_name": "Flap"})
            assert r["ok"]

            # The flap promotes the node to SUSPECT, the rebind restores
            # it, and the replayed CreateActor is answered from the
            # raylet's reply cache — never executed twice.
            await _wait_for(
                lambda: gcs.nodes[NODE_ID].suspect_recoveries >= 1,
                timeout=20, what="suspect recovery")
            await asyncio.sleep(0.5)  # window for a wrong re-execution
            assert len(executions) == 1, \
                f"CreateActor forked: {len(executions)} executions"
            assert gcs._actor_plane.actor_count() == 1

            await raylet.call("ActorReady", {
                "actor_id": "flap-a1", "address": ["127.0.0.1", 47002]})
            await _wait_for(
                lambda: gcs.actors["flap-a1"]["state"] == ACTOR_ALIVE,
                what="actor ALIVE after flap")
            await driver.close()
            await raylet.close()
        finally:
            chaos.stop()
            await gcs.stop()

    run(main())


def test_node_killed_mid_ladder_fails_over(tmp_path, monkeypatch):
    """The CreateActor target dies mid-ladder (no reply ever): on the
    death certificate the plane fails the create over to the surviving
    node — one restart consumed, no fork, no lost actor."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")
    node_a, node_b = "bb" * 16, "cc" * 16

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            a_creates, b_creates = [], []
            got_create = asyncio.Event()

            async def a_create(conn, payload):
                a_creates.append(payload)
                got_create.set()
                await asyncio.Event().wait()  # never replies: dies first

            def b_create(conn, payload):
                b_creates.append(payload)
                got_create.set()
                return {"ok": True}

            ra, _, _ = await _fake_raylet_ex(host, port, node_a,
                                             on_create=a_create)
            rb, _, _ = await _fake_raylet_ex(host, port, node_b,
                                             on_create=b_create)
            driver = await rpc.connect_session(host, port, name="driver")
            r = await driver.call("RegisterActor", {
                "actor_id": "kill-a1", "spec": b"\x06s",
                "max_restarts": 1, "class_name": "Kill"})
            assert r["ok"]
            await asyncio.wait_for(got_create.wait(), 10)
            first = node_a if a_creates else node_b
            survivor_sess = rb if first == node_a else ra
            survivor_creates = b_creates if first == node_a else a_creates
            got_create.clear()

            # Death certificate for the in-flight target: the plane
            # fails over (restart bookkeeping) and re-drives the ladder
            # at the survivor.
            await driver.call("NotifyNodeDead", {"node_id": first})
            await asyncio.wait_for(got_create.wait(), 10)
            assert len(survivor_creates) == 1
            assert survivor_creates[0]["actor_id"] == "kill-a1"

            await survivor_sess.call("ActorReady", {
                "actor_id": "kill-a1",
                "address": ["127.0.0.1", 47003]})
            await _wait_for(
                lambda: gcs.actors["kill-a1"]["state"] == ACTOR_ALIVE,
                what="actor ALIVE on survivor")
            assert gcs.actors["kill-a1"]["node_id"] != first
            assert gcs.actors["kill-a1"]["restarts"] == 1
            assert gcs._actor_plane.actor_count() == 1

            await driver.close()
            for s in (ra, rb):
                try:
                    await s.close()
                except Exception:
                    pass
        finally:
            await gcs.stop()

    run(main())


def test_draining_node_excluded_from_native_picks(tmp_path, monkeypatch):
    """Satellite of tests/test_drain.py drain-rejection: once a node is
    DRAINING, the native ladder must stop picking it — every new native
    create lands on the other node."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")
    node_a, node_b = "dd" * 16, "ee" * 16

    async def main():
        gcs = GcsServer(persistence_path=str(tmp_path / "gcs_state"))
        host, port = await gcs.start()
        try:
            a_creates, b_creates = [], []

            def mk_create(sink):
                def h(conn, payload):
                    sink.append(payload)
                    return {"ok": True}
                return h

            def drain_ok(conn, payload):
                return {"ok": True}

            ra, _, _ = await _fake_raylet_ex(
                host, port, node_a, on_create=mk_create(a_creates),
                handlers={"Drain": drain_ok})
            rb, _, _ = await _fake_raylet_ex(
                host, port, node_b, on_create=mk_create(b_creates),
                handlers={"Drain": drain_ok})
            driver = await rpc.connect_session(host, port, name="driver")

            r = await driver.call("DrainNode", {
                "node_id": node_a, "reason": "manual",
                "deadline_s": 30.0})
            assert r["ok"], r

            for i in range(4):
                r = await driver.call("RegisterActor", {
                    "actor_id": f"drain-a{i}", "spec": b"\x07s",
                    "max_restarts": 0, "class_name": "D"})
                assert r["ok"]
            await _wait_for(lambda: len(b_creates) == 4, timeout=10,
                            what="creates on the non-draining node")
            assert not a_creates, \
                "native ladder picked a DRAINING node"

            await driver.close()
            await ra.close()
            await rb.close()
        finally:
            await gcs.stop()

    run(main())


def test_gcs_restart_rehydrates_native_plane(tmp_path, monkeypatch):
    """Crash rehydration: a restarted GCS replays the persisted node
    and actor tables into a fresh native plane — the ALIVE actor is
    ALIVE natively, the in-flight PENDING one is re-driven (exactly one
    CreateActor) when its node re-registers."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")
    path = str(tmp_path / "gcs_state")

    async def phase1():
        gcs = GcsServer(persistence_path=path)
        host, port = await gcs.start()
        try:
            raylet, created, payloads = await _fake_raylet(host, port)
            driver = await rpc.connect_session(host, port, name="driver")
            assert (await driver.call("RegisterActor", {
                "actor_id": "re-alive", "spec": b"\x08alive",
                "max_restarts": 0}))["ok"]
            await asyncio.wait_for(created.wait(), 10)
            await raylet.call("ActorReady", {
                "actor_id": "re-alive", "address": ["127.0.0.1", 47002]})
            await _wait_for(
                lambda: gcs.actors["re-alive"]["state"] == ACTOR_ALIVE,
                what="actor ALIVE pre-restart")
            # Second actor: created at the raylet but NEVER ActorReady —
            # in-flight at "crash" time, restored as PENDING.
            assert (await driver.call("RegisterActor", {
                "actor_id": "re-pending", "spec": b"\x09pend",
                "max_restarts": 0}))["ok"]
            await _wait_for(lambda: len(payloads) >= 2,
                            what="second CreateActor")
            await driver.close()
            await raylet.close()
        finally:
            await gcs.stop()  # final flush + compact

    async def phase2():
        gcs = GcsServer(persistence_path=path)
        host, port = await gcs.start()
        try:
            plane = gcs._actor_plane
            assert plane is not None
            # Rehydrated straight from the snapshot, before any node
            # re-registered.
            assert plane.actor_state("re-alive") == "ALIVE"
            assert plane.actor_state("re-pending") == "PENDING"
            assert plane.actor_count() == 2

            raylet, created, payloads = await _fake_raylet(host, port)
            # Node re-registration re-drives ONLY the pending ladder.
            await asyncio.wait_for(created.wait(), 10)
            await asyncio.sleep(0.3)
            assert [p["actor_id"] for p in payloads] == ["re-pending"]
            assert payloads[0]["spec"] == b"\x09pend"
            await raylet.call("ActorReady", {
                "actor_id": "re-pending",
                "address": ["127.0.0.1", 47005]})
            await _wait_for(
                lambda: gcs.actors["re-pending"]["state"] == ACTOR_ALIVE,
                what="re-driven actor ALIVE")
            assert gcs.actors["re-alive"]["state"] == ACTOR_ALIVE
            await raylet.close()
        finally:
            await gcs.stop()

    run(phase1())
    run(phase2())


def test_full_stack_native_control(monkeypatch):
    """ray_tpu.init under RAY_TPU_NATIVE_CONTROL=1: tasks and actors
    (plain + named) behave exactly as under the Python control plane,
    and both daemons report an installed plane that saw the traffic."""
    monkeypatch.setenv("RAY_TPU_NATIVE_CONTROL", "1")
    from ray_tpu._private.config import Config

    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.num_heartbeats_timeout = 5
    cfg.worker_lease_timeout_s = 10.0
    cfg.object_store_memory = 64 * 1024 * 1024
    ray_tpu.init(num_cpus=2, config=cfg)
    try:
        @ray_tpu.remote
        def double(x):
            return x * 2

        assert ray_tpu.get([double.remote(i) for i in range(8)]) == \
            [i * 2 for i in range(8)]

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote()) == 1
        assert ray_tpu.get(c.inc.remote()) == 2

        named = Counter.options(name="nc-named").remote()
        assert ray_tpu.get(named.inc.remote()) == 1

        # More plain tasks after workers exist: the idle-worker pool is
        # populated, so the lease plane gets grantable shapes.
        assert ray_tpu.get([double.remote(i) for i in range(8)]) == \
            [i * 2 for i in range(8)]

        cw = ray_tpu._private.api_internal.get_core_worker()
        status = cw._run(cw.gcs.call("GetClusterStatus", {}))
        nc = status["native_control"]
        assert nc is not None, "GCS actor plane not installed"
        # Two RegisterActors flowed through the plane's frame hook —
        # handled natively or routed, never invisible.
        assert nc["handled_total"] + nc["native_fallthrough_total"] >= 2
        assert nc["proto_errors"] == 0

        state = cw._run(cw.raylet.call("GetState", {}))
        rnc = state["native_control"]
        assert rnc is not None, "raylet lease plane not installed"
        assert rnc["handled_total"] + rnc["native_fallthrough_total"] >= 1
        assert rnc["proto_errors"] == 0
    finally:
        ray_tpu.shutdown()
