"""Model correctness smoke tests (CPU, tiny configs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.llama import (
    TINY,
    LlamaConfig,
    LlamaModel,
    count_flops_per_token,
    cross_entropy_loss,
    init_kv_caches,
)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = TINY
    model = LlamaModel(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    return cfg, model, params


def test_forward_shape(tiny_model):
    cfg, model, params = tiny_model
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_loss_decreases_with_training(tiny_model):
    cfg, model, params = tiny_model
    import optax

    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, cfg.vocab_size)
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            return cross_entropy_loss(model.apply(p, inp), tgt)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_param_names_match_sharding_rules(tiny_model):
    from ray_tpu.parallel import TRANSFORMER_RULES, P

    cfg, model, params = tiny_model
    specs = TRANSFORMER_RULES.tree_specs(params)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    by_path = {"/".join(str(getattr(k, "key", k)) for k in path): spec
               for path, spec in flat}
    qs = [s for p, s in by_path.items() if "q_proj/kernel" in p]
    assert qs and all(s == P("fsdp", "tp") for s in qs)
    downs = [s for p, s in by_path.items() if "down_proj/kernel" in p]
    assert downs and all(s == P("tp", "fsdp") for s in downs)


def test_kv_cache_decode_matches_full_forward(tiny_model):
    cfg, model, params = tiny_model
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0, cfg.vocab_size)
    full_logits = model.apply(params, tokens)

    caches = init_kv_caches(cfg, 1, 16)
    # Prefill first 4 tokens, then decode one at a time.
    logits, caches = model.apply(params, tokens[:, :4],
                                 positions=jnp.arange(4), kv_caches=caches)
    outs = [logits]
    for i in range(4, 8):
        logits, caches = model.apply(
            params, tokens[:, i:i + 1],
            positions=jnp.array([i]), kv_caches=caches)
        outs.append(logits)
    stitched = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(stitched), np.asarray(full_logits),
                               atol=2e-4, rtol=2e-4)


def test_gqa_config():
    cfg = LlamaConfig(vocab_size=64, d_model=64, n_layers=1, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq_len=64,
                      dtype=jnp.float32, attention="reference", remat=False)
    model = LlamaModel(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (1, 8, 64)


def test_flops_estimate_7b():
    from ray_tpu.models.llama import LLAMA2_7B

    flops = count_flops_per_token(LLAMA2_7B)
    # ~6 * 6.7B params
    assert 3.5e10 < flops < 4.5e10


def test_vit_forward_and_train_step():
    import optax
    from ray_tpu.models import VIT_TINY, ViT, vit_loss
    from ray_tpu.parallel import MeshConfig, TRANSFORMER_RULES, make_mesh
    from ray_tpu.train.spmd import (init_sharded_state, make_train_step,
                                    shard_train_step)
    from jax.sharding import NamedSharding, PartitionSpec as P

    model = ViT(VIT_TINY)
    imgs = jnp.zeros((4, 32, 32, 3), jnp.float32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), imgs)
    logits = jax.jit(model.apply)(params, imgs)
    assert logits.shape == (4, VIT_TINY.num_classes)
    assert np.isfinite(np.asarray(logits)).all()

    # The same transformer sharding rules cover ViT params (q/o/up/down
    # names align), so the sharded train step compiles over a dp x tp mesh.
    mesh = make_mesh(MeshConfig(dp=-1, tp=2))
    opt = optax.adam(1e-3)
    state, specs = init_sharded_state(
        mesh, lambda im: model.init(jax.random.PRNGKey(0), im),
        TRANSFORMER_RULES, opt, imgs)

    def loss_fn(p, batch):
        return vit_loss(model.apply(p, batch[0]), batch[1])

    step = make_train_step(loss_fn, opt)
    bs = (P(("dp", "fsdp"), None, None, None), P(("dp", "fsdp")))
    sstep = shard_train_step(step, mesh, specs, bs)
    labels = jnp.zeros((4,), jnp.int32)
    ex = jax.device_put((imgs, labels), jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), bs,
        is_leaf=lambda x: isinstance(x, P)))
    state, metrics = sstep(state, ex)
    assert np.isfinite(float(metrics["loss"]))


def test_dit_forward_and_loss():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.dit import DiT, DiTConfig, ddpm_loss

    cfg = DiTConfig(image_size=8, patch_size=2, d_model=32, n_layers=2,
                    n_heads=2, num_classes=4, timesteps=50,
                    dtype=jnp.float32, attention="reference")
    model = DiT(cfg)
    imgs = jnp.zeros((2, 8, 8, 3))
    t = jnp.zeros((2,), jnp.float32)
    labels = jnp.zeros((2,), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), imgs, t, labels)
    out = jax.jit(model.apply)(params, imgs, t, labels)
    assert out.shape == (2, 8, 8, 3)
    # adaLN-Zero: zero-init final proj => initial prediction is exactly 0.
    assert float(jnp.abs(out).max()) == 0.0

    loss_fn = jax.jit(lambda p, b, l, r: ddpm_loss(model, p, b, l, r))
    loss = loss_fn(params, jnp.ones((2, 8, 8, 3)), labels,
                   jax.random.PRNGKey(1))
    # Prediction 0 vs unit gaussian noise target -> MSE ~ 1.
    assert 0.5 < float(loss) < 2.0
    grads = jax.grad(lambda p: ddpm_loss(model, p, jnp.ones((2, 8, 8, 3)),
                                         labels, jax.random.PRNGKey(1)))(params)
    gnorm = sum(float(jnp.abs(g).sum())
                for g in jax.tree_util.tree_leaves(grads))
    assert np.isfinite(gnorm) and gnorm > 0


def test_dit_ddim_sampler():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.dit import DiT, DiTConfig, ddim_sample

    cfg = DiTConfig(image_size=8, patch_size=2, d_model=32, n_layers=1,
                    n_heads=2, num_classes=4, timesteps=20,
                    dtype=jnp.float32, attention="reference")
    model = DiT(cfg)
    imgs = jnp.zeros((1, 8, 8, 3))
    params = model.init(jax.random.PRNGKey(0), imgs, jnp.zeros((1,)),
                        jnp.zeros((1,), jnp.int32))
    out = jax.jit(lambda p, r: ddim_sample(
        model, p, r, num=2, steps=5,
        labels=jnp.zeros((2,), jnp.int32), guidance=1.0))(
        params, jax.random.PRNGKey(2))
    assert out.shape == (2, 8, 8, 3)
    import numpy as np

    assert np.isfinite(np.asarray(out)).all()


def test_dit_param_count_matches():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.dit import DiT, DiTConfig, count_dit_params

    cfg = DiTConfig(image_size=8, patch_size=2, d_model=32, n_layers=2,
                    n_heads=2, num_classes=4, timesteps=10,
                    dtype=jnp.float32, attention="reference")
    model = DiT(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)),
                        jnp.zeros((1,)), jnp.zeros((1,), jnp.int32))
    actual = sum(int(np.prod(x.shape))
                 for x in jax.tree_util.tree_leaves(params))
    assert count_dit_params(cfg) == actual
