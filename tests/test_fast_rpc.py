"""FastRpcServer (daemon RPC over the native pump) unit tests.

The daemons exercise this end-to-end constantly; these tests pin the
module's own contracts — wire compatibility with rpc.Connection
clients, sync/async handler dispatch, error frames, server->client
calls, close semantics, and the >512-events-per-wake drain (whose
strand bug was review-caught in r5: fpump_drain caps a batch and
nothing re-bumps the eventfd for the remainder)."""

import asyncio

import pytest

from ray_tpu._private import rpc
from ray_tpu._private.fast_rpc import FastRpcServer
from ray_tpu._private.native_fastpath import available

pytestmark = pytest.mark.skipif(not available(),
                                reason="native pump unavailable")


def run(coro):
    return asyncio.run(coro)


def test_sync_and_async_handlers_roundtrip():
    async def main():
        calls = []

        def sync_echo(conn, payload):
            calls.append("sync")
            return {"echo": payload["x"]}

        async def async_add(conn, payload):
            await asyncio.sleep(0.01)
            return payload["a"] + payload["b"]

        server = FastRpcServer({"Echo": sync_echo, "Add": async_add},
                               name="t")
        host, port = await server.start()
        try:
            conn = await rpc.connect(host, port)
            assert await conn.call("Echo", {"x": 7}) == {"echo": 7}
            assert await conn.call("Add", {"a": 2, "b": 3}) == 5
            assert calls == ["sync"]
            with pytest.raises(rpc.RpcError, match="no handler"):
                await conn.call("Nope", {})
            await conn.close()
        finally:
            await server.stop()

    run(main())


def test_handler_exception_becomes_error_frame():
    async def main():
        def boom(conn, payload):
            raise ValueError("kapow")

        server = FastRpcServer({"Boom": boom}, name="t")
        host, port = await server.start()
        try:
            conn = await rpc.connect(host, port)
            with pytest.raises(rpc.RpcError, match="kapow"):
                await conn.call("Boom", {})
            # The connection survives an error frame.
            with pytest.raises(rpc.RpcError, match="kapow"):
                await conn.call("Boom", {})
            await conn.close()
        finally:
            await server.stop()

    run(main())


def test_server_initiated_call_to_client():
    async def main():
        accepted = []
        server = FastRpcServer({}, name="t",
                               on_connect=accepted.append)
        host, port = await server.start()
        try:
            conn = await rpc.connect(
                host, port, handlers={"Ping": lambda c, p: {"pong": p}})
            # Wait for the accept event to surface server-side.
            for _ in range(100):
                if accepted:
                    break
                await asyncio.sleep(0.01)
            sconn = accepted[0]
            out = await sconn.call("Ping", 42, timeout=5)
            assert out == {"pong": 42}
            await conn.close()
        finally:
            await server.stop()

    run(main())


def test_burst_beyond_drain_cap():
    """>512 notifies in one burst: every one must dispatch even though
    fpump_drain caps a batch at 512 and pops do not re-bump the eventfd
    (the r5 review-caught strand)."""
    async def main():
        seen = []

        def note(conn, payload):
            seen.append(payload)

        server = FastRpcServer({"N": note}, name="t")
        host, port = await server.start()
        try:
            conn = await rpc.connect(host, port)
            n = 1500
            for i in range(n):
                await conn.notify("N", i)
            deadline = asyncio.get_running_loop().time() + 15
            while len(seen) < n and \
                    asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.02)
            assert len(seen) == n, f"stranded events: {len(seen)}/{n}"
            assert seen == list(range(n))  # FIFO preserved
            await conn.close()
        finally:
            await server.stop()

    run(main())


def test_close_fails_pending_calls():
    async def main():
        async def hang(conn, payload):
            await asyncio.sleep(30)

        server = FastRpcServer({"Hang": hang}, name="t")
        host, port = await server.start()
        conn = await rpc.connect(host, port)
        fut = asyncio.ensure_future(conn.call("Hang", {}, timeout=20))
        await asyncio.sleep(0.1)
        await server.stop()  # cancels in-flight dispatch, drops conns
        with pytest.raises((rpc.ConnectionLost, rpc.RpcError,
                            asyncio.TimeoutError)):
            await asyncio.wait_for(fut, 5)
        await conn.close()

    run(main())
