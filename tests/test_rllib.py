"""PPO tests (parity: reference rllib/algorithms/ppo tests — learning
regression on CartPole)."""

import numpy as np
import pytest

from ray_tpu.rllib.env import CartPole
from ray_tpu.rllib.ppo import PPO, PPOConfig, init_policy_params, numpy_forward


def test_cartpole_env_contract():
    env = CartPole()
    obs = env.reset(seed=0)
    assert obs.shape == (4,)
    total = 0.0
    done = False
    while not done:
        obs, r, done, _ = env.step(1)
        total += r
    assert 1 <= total < 500


def test_numpy_forward_shapes():
    params = init_policy_params(4, 2)
    logits, value = numpy_forward(params, np.zeros((3, 4), np.float32))
    assert logits.shape == (3, 2)
    assert value.shape == (3,)


def test_ppo_learns_cartpole(ray_start_regular):
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(train_batch_size=1024, num_sgd_iter=4,
                      sgd_minibatch_size=256, lr=1e-3)
            .build())
    try:
        first = algo.train()
        reward_first = first["episode_reward_mean"]
        last = first
        for _ in range(4):
            last = algo.train()
        assert last["training_iteration"] == 5
        assert last["timesteps_this_iter"] >= 1024
        # Learning signal: reward improves over the run.
        assert last["episode_reward_mean"] > reward_first
    finally:
        algo.stop()
