"""PPO tests (parity: reference rllib/algorithms/ppo tests — learning
regression on CartPole)."""

import numpy as np
import pytest

from ray_tpu.rllib.env import CartPole
from ray_tpu.rllib.ppo import PPO, PPOConfig, init_policy_params, numpy_forward


def test_cartpole_env_contract():
    env = CartPole()
    obs = env.reset(seed=0)
    assert obs.shape == (4,)
    total = 0.0
    done = False
    while not done:
        obs, r, done, _ = env.step(1)
        total += r
    assert 1 <= total < 500


def test_numpy_forward_shapes():
    params = init_policy_params(4, 2)
    logits, value = numpy_forward(params, np.zeros((3, 4), np.float32))
    assert logits.shape == (3, 2)
    assert value.shape == (3,)


def test_ppo_learns_cartpole(ray_start_regular):
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(train_batch_size=1024, num_sgd_iter=4,
                      sgd_minibatch_size=256, lr=1e-3)
            .build())
    try:
        first = algo.train()
        reward_first = first["episode_reward_mean"]
        last = first
        for _ in range(4):
            last = algo.train()
        assert last["training_iteration"] == 5
        assert last["timesteps_this_iter"] >= 1024
        # Learning signal: reward improves over the run.
        assert last["episode_reward_mean"] > reward_first
    finally:
        algo.stop()


def test_replay_buffer():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=10, obs_size=2, seed=0)
    batch = {"obs": np.ones((6, 2), np.float32),
             "next_obs": np.zeros((6, 2), np.float32),
             "actions": np.arange(6, dtype=np.int32),
             "rewards": np.ones(6, np.float32),
             "dones": np.zeros(6, np.float32)}
    buf.add_batch(batch)
    assert buf.size == 6
    buf.add_batch(batch)  # wraps the ring
    assert buf.size == 10
    sample = buf.sample(4)
    assert sample["obs"].shape == (4, 2)
    assert set(sample["actions"]) <= set(range(6))


def test_dqn_learns_cartpole(ray_start_regular):
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2)
            .training(rollout_fragment_length=512, learning_starts=512,
                      num_sgd_iter=64, train_batch_size=128,
                      epsilon_decay_iters=6, target_network_update_freq=2)
            .build())
    try:
        first = algo.train()
        last = first
        for _ in range(7):
            last = algo.train()
        assert last["training_iteration"] == 8
        assert last["buffer_size"] > 1000
        assert last["num_updates"] > 0
        # Learning signal: reward improves over the greedy-annealed run.
        assert last["episode_reward_mean"] > first["episode_reward_mean"]
    finally:
        algo.stop()
