"""ray_tpu.data tests (parity: reference python/ray/data/tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


def test_range_count_take(ray_start_regular):
    ds = rd.range(100)
    assert ds.count() == 100
    assert ds.take(5) == [0, 1, 2, 3, 4]


def test_map_and_filter(ray_start_regular):
    ds = rd.range(20).map(lambda x: x * 2).filter(lambda x: x % 4 == 0)
    assert ds.take_all() == [x * 2 for x in range(20) if (x * 2) % 4 == 0]


def test_map_batches_numpy(ray_start_regular):
    ds = rd.from_items([{"x": float(i)} for i in range(32)])
    out = ds.map_batches(lambda b: {"y": b["x"] * 10}).take_all()
    assert out[3]["y"] == 30.0
    assert len(out) == 32


def test_flat_map(ray_start_regular):
    ds = rd.from_items([1, 2, 3]).flat_map(lambda x: [x] * x)
    assert sorted(ds.take_all()) == [1, 2, 2, 3, 3, 3]


def test_iter_batches(ray_start_regular):
    ds = rd.from_items([{"v": i} for i in range(10)])
    batches = list(ds.iter_batches(batch_size=4))
    assert [len(b["v"]) for b in batches] == [4, 4, 2]
    np.testing.assert_array_equal(batches[0]["v"], [0, 1, 2, 3])


def test_random_shuffle_preserves_elements(ray_start_regular):
    ds = rd.range(50).random_shuffle(seed=42)
    out = ds.take_all()
    assert sorted(out) == list(range(50))
    assert out != list(range(50))


def test_repartition(ray_start_regular):
    ds = rd.range(30, override_num_blocks=2).repartition(5)
    assert ds.materialize().num_blocks() == 5
    assert ds.count() == 30


def test_sort(ray_start_regular):
    ds = rd.from_items([5, 3, 9, 1]).sort(key=lambda x: x)
    assert ds.take_all() == [1, 3, 5, 9]


def test_aggregates(ray_start_regular):
    ds = rd.from_items([{"a": i} for i in range(10)])
    assert ds.sum(on="a") == 45
    assert ds.min(on="a") == 0
    assert ds.max(on="a") == 9
    assert ds.mean(on="a") == 4.5


def test_split_for_workers(ray_start_regular):
    shards = rd.range(12).split(3)
    assert [s.count() for s in shards] == [4, 4, 4]
    all_rows = sorted(sum((s.take_all() for s in shards), []))
    assert all_rows == list(range(12))


def test_chained_lazy_stages_distributed(ray_start_regular):
    """Stages execute as remote tasks over blocks."""
    ds = (rd.range(64, override_num_blocks=8)
          .map(lambda x: x + 1)
          .map_batches(lambda b: {"item": b["item"] * 2})
          .filter(lambda r: r["item"] <= 64))
    out = [r["item"] for r in ds.take_all()]
    assert out == [(x + 1) * 2 for x in range(64) if (x + 1) * 2 <= 64]


def test_read_text_json_csv(ray_start_regular, tmp_path):
    (tmp_path / "a.txt").write_text("hello\nworld\n")
    ds = rd.read_text(str(tmp_path / "a.txt"))
    assert ds.take_all() == [{"text": "hello"}, {"text": "world"}]

    (tmp_path / "b.jsonl").write_text('{"x": 1}\n{"x": 2}\n')
    assert rd.read_json(str(tmp_path / "b.jsonl")).sum(on="x") == 3

    (tmp_path / "c.csv").write_text("a,b\n1,2\n3,4\n")
    rows = rd.read_csv(str(tmp_path / "c.csv")).take_all()
    assert rows == [{"a": "1", "b": "2"}, {"a": "3", "b": "4"}]


def test_iter_jax_batches(ray_start_regular):
    import jax

    ds = rd.from_items([{"x": np.float32(i)} for i in range(16)])
    batches = list(ds.iter_jax_batches(batch_size=8))
    assert len(batches) == 2
    assert isinstance(batches[0]["x"], jax.Array)
    assert float(batches[0]["x"].sum()) == sum(range(8))


def test_iter_jax_batches_device_landing(ray_start_regular):
    """The device-transport path lands each block's host→HBM copy on a
    worker and this consumer resolves the arrays over the device plane:
    batches are value-identical to the host path (including rebatching
    across block boundaries and the drop_last tail) and the plane's
    transfer counters actually tick."""
    from ray_tpu._private import device_objects

    ds = rd.from_items([{"x": np.float32(i)} for i in range(24)])
    host = list(ds.iter_jax_batches(batch_size=10, drop_last=False,
                                    device_transport=False))
    before = device_objects.counters()
    dev = list(ds.iter_jax_batches(batch_size=10, drop_last=False,
                                   device_transport=True))
    after = device_objects.counters()
    assert [len(b["x"]) for b in dev] == [len(b["x"]) for b in host] \
        == [10, 10, 4]
    for hb, db in zip(host, dev):
        assert np.allclose(np.asarray(hb["x"]), np.asarray(db["x"]))
    moved = sum(after.get(k, 0) - before.get(k, 0)
                for k in ("in_process", "collective", "host_fallback"))
    assert moved > 0


def test_data_context_controls_execution(ray_start_regular):
    """DataContext knobs flow into plan execution (reference:
    data/context.py DataContext.get_current())."""
    from ray_tpu import data

    ctx = data.DataContext.get_current()
    assert ctx is data.DataContext.get_current()  # process singleton
    old_blocks, old_inflight = ctx.default_block_count, ctx.max_in_flight_blocks
    try:
        ctx.default_block_count = 3
        ds = data.from_items(list(range(30)))
        assert ds.num_blocks() == 3
        ctx.max_in_flight_blocks = 2
        assert ds.map(lambda x: x + 1).sum() == sum(range(1, 31))
    finally:
        ctx.default_block_count = old_blocks
        ctx.max_in_flight_blocks = old_inflight
