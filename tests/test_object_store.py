"""Tests for the native shared-memory object store.

Parity model: reference plasma store tests
(reference: src/ray/object_manager/plasma/test/).
"""

import multiprocessing
import os

import numpy as np
import pytest

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import (
    ObjectStoreClient,
    ObjectStoreFullError,
)


@pytest.fixture
def store(tmp_path):
    path = str(tmp_path / "arena")
    s = ObjectStoreClient(path, create=True, size=16 * 1024 * 1024, table_capacity=1024)
    yield s
    s.close()


def test_put_get_roundtrip(store):
    oid = ObjectID.from_random()
    store.put_raw(oid, b"hello world", meta=b"M")
    meta, data = store.get_buffer(oid)
    assert meta == b"M"
    assert bytes(data) == b"hello world"
    store.release(oid)


def test_zero_copy_numpy(store):
    oid = ObjectID.from_random()
    arr = np.arange(1000, dtype=np.float32)
    buf = store.create(oid, arr.nbytes)
    np.frombuffer(buf, dtype=np.float32)[:] = arr
    store.seal(oid)
    meta, data = store.get_buffer(oid)
    out = np.frombuffer(data, dtype=np.float32)
    np.testing.assert_array_equal(out, arr)
    store.release(oid)


def test_missing_object(store):
    assert store.get_buffer(ObjectID.from_random()) is None
    assert not store.contains(ObjectID.from_random())


def test_unsealed_not_visible(store):
    oid = ObjectID.from_random()
    store.create(oid, 10)
    assert store.get_buffer(oid) is None
    assert not store.contains(oid)
    store.seal(oid)
    assert store.contains(oid)


def test_delete_and_reuse_space(store):
    oids = []
    for _ in range(10):
        oid = ObjectID.from_random()
        store.put_raw(oid, b"x" * 100_000)
        oids.append(oid)
    stats = store.stats()
    assert stats["num_objects"] == 10
    for oid in oids:
        assert store.delete(oid)
    assert store.stats()["num_objects"] == 0
    # Space is reusable.
    big = ObjectID.from_random()
    store.put_raw(big, b"y" * 1_000_000)
    assert store.contains(big)


def test_lru_eviction(store):
    # Fill the 16MB store with 1MB objects; unreferenced ones get evicted.
    oids = []
    for _ in range(30):
        oid = ObjectID.from_random()
        store.put_raw(oid, b"z" * (1024 * 1024))
        oids.append(oid)
    assert store.stats()["num_evictions"] > 0
    # Most recent object is present.
    assert store.contains(oids[-1])
    # Oldest got evicted.
    assert not store.contains(oids[0])


def test_pinned_objects_not_evicted(store):
    pinned = ObjectID.from_random()
    store.put_raw(pinned, b"p" * (1024 * 1024))
    assert store.get_buffer(pinned) is not None  # hold a reference
    with pytest.raises(ObjectStoreFullError):
        # Pinned object survives; the rest of the arena (~15MB usable)
        # can't fit this in one piece.
        big = ObjectID.from_random()
        store.put_raw(big, b"q" * (16 * 1024 * 1024))
    assert store.contains(pinned)


def _child_reader(path, oid_bytes, q):
    s = ObjectStoreClient(path)
    got = s.get_buffer(ObjectID(oid_bytes))
    q.put(bytes(got[1]) if got else None)
    s.close()


def test_cross_process_read(store, tmp_path):
    oid = ObjectID.from_random()
    store.put_raw(oid, b"shared-data")
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_child_reader, args=(store.path, oid.binary(), q))
    p.start()
    assert q.get(timeout=30) == b"shared-data"
    p.join(timeout=10)


def test_abort(store):
    oid = ObjectID.from_random()
    store.create(oid, 1000)
    store.abort(oid)
    assert store.get_buffer(oid) is None
    assert store.stats()["num_objects"] == 0


def test_list_objects(store):
    oids = {ObjectID.from_random() for _ in range(5)}
    for oid in oids:
        store.put_raw(oid, b"v")
    assert set(store.list_objects()) == oids
