"""Tune tests (parity: reference python/ray/tune/tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import session
from ray_tpu.tune.search import generate_variants


def test_generate_variants_grid_and_samples():
    space = {"lr": tune.grid_search([0.1, 0.01]), "wd": tune.uniform(0, 1)}
    variants = generate_variants(space, num_samples=3, seed=0)
    assert len(variants) == 6
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert all(0 <= v["wd"] <= 1 for v in variants)


def test_basic_tune_run(ray_start_regular):
    def trainable(config):
        session.report({"score": config["x"] ** 2})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    ).fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 9


def test_trial_error_captured(ray_start_regular):
    def trainable(config):
        if config["x"] == 2:
            raise ValueError("bad trial")
        session.report({"score": config["x"]})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    ).fit()
    assert len(grid.errors) == 1
    assert grid.get_best_result().config["x"] == 1


def test_asha_early_stops(ray_start_regular):
    def trainable(config):
        import time

        for i in range(12):
            session.report({"acc": config["quality"] * (i + 1)})
            # Slow enough that the controller's poll loop can early-stop
            # weak trials before they finish on their own.
            time.sleep(0.25)

    sched = tune.ASHAScheduler(metric="acc", mode="max", max_t=12,
                               grace_period=2, reduction_factor=2)
    # Strong trials first: ASHA is asynchronous, so a weak trial that
    # reaches every rung before any strong result is recorded never stops.
    grid = tune.Tuner(
        trainable,
        param_space={"quality": tune.grid_search([1.0, 0.9, 0.2, 0.1])},
        tune_config=tune.TuneConfig(metric="acc", mode="max", scheduler=sched,
                                    max_concurrent_trials=4),
    ).fit()
    best = grid.get_best_result()
    assert best.config["quality"] in (0.9, 1.0)
    # The weakest trial should have been stopped before 12 iterations.
    histories = sorted(len(r.metrics_history) for r in grid)
    assert histories[0] < 12


def test_pbt_exploits_checkpoint(ray_start_regular, tmp_path):
    def trainable(config):
        import os

        from ray_tpu.train.checkpoint import Checkpoint

        # Restore cloned weight if PBT gave us a checkpoint.
        w = 0.0
        if config.get("_checkpoint_path"):
            w = float(np.asarray(
                Checkpoint(config["_checkpoint_path"]).to_pytree()["w"]))
        for i in range(10):
            w += config["lr"]
            ck = Checkpoint.from_pytree(
                {"w": np.float64(w)},
                os.path.join(config["dir"], f"ck_{session.get_world_rank()}_"
                                            f"{os.getpid()}_{i}"))
            session.report({"w": w}, checkpoint=ck)

    sched = tune.PopulationBasedTraining(
        metric="w", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 1.0]}, quantile_fraction=0.5,
        seed=0)
    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.1, 1.0]),
                     "dir": str(tmp_path)},
        tune_config=tune.TuneConfig(metric="w", mode="max", scheduler=sched,
                                    max_concurrent_trials=2),
    ).fit()
    best = grid.get_best_result()
    assert best.metrics["w"] >= 3.0  # the strong trial made progress
    assert len(grid) == 2


def test_tuner_experiment_resume(ray_start_regular, tmp_path):
    """Experiment state persists; Tuner.restore re-runs only unfinished
    trials, restoring them from their last checkpoint (reference:
    Tuner.restore + experiment_state.py)."""
    import json
    import os

    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.train.config import RunConfig

    marker = tmp_path / "ran.jsonl"

    def trainable(config):
        w = 0.0
        if config.get("_checkpoint_path"):
            w = float(np.asarray(
                Checkpoint(config["_checkpoint_path"]).to_pytree()["w"]))
        with open(marker, "a") as f:
            f.write(json.dumps({"lr": config["lr"], "start_w": w}) + "\n")
        for i in range(3):
            w += config["lr"]
            ck = Checkpoint.from_pytree(
                {"w": np.float64(w)},
                os.path.join(config["dir"],
                             f"r_{config['lr']}_{os.getpid()}_{i}"))
            session.report({"w": w}, checkpoint=ck)

    exp_dir = str(tmp_path / "exp")
    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([1.0, 2.0]),
                     "dir": str(tmp_path)},
        tune_config=tune.TuneConfig(metric="w", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path), name="exp"),
    ).fit()
    assert len(grid) == 2
    state_file = os.path.join(exp_dir, "experiment_state.json")
    assert os.path.exists(state_file)

    # Simulate an interruption: mark one finished trial as RUNNING.
    with open(state_file) as f:
        state = json.load(f)
    assert all(t["status"] == "TERMINATED" for t in state["trials"])
    state["trials"][1]["status"] = "RUNNING"
    with open(state_file, "w") as f:
        json.dump(state, f)

    runs_before = len(marker.read_text().splitlines())
    grid2 = tune.Tuner.restore(exp_dir, trainable).fit()
    runs_after = len(marker.read_text().splitlines())
    # Only the interrupted trial re-ran...
    assert runs_after == runs_before + 1
    # ...and it resumed from its checkpoint, not from zero.
    last = json.loads(marker.read_text().splitlines()[-1])
    assert last["start_w"] > 0.0
    assert len(grid2) == 2
    best = grid2.get_best_result()
    assert best.metrics["w"] >= 6.0
