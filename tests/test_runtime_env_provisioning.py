"""Runtime-env provisioning: pip envs, package URIs, ref-counted GC.

Parity: reference python/ray/runtime_env/ARCHITECTURE.md (URI-keyed
caching + ref-counted GC), _private/runtime_env/{pip,packaging}.py.
Offline-friendly: pip tests install a locally-built wheel with
--no-index --find-links (the image has no egress).
"""

import os
import zipfile

import pytest

import ray_tpu
from ray_tpu._private.runtime_env_manager import (
    RuntimeEnvManager, package_local_dir, package_uri_for, pip_uri_for)


def _make_wheel(dirpath, name="rtenv_testpkg", version="1.0"):
    """Hand-roll a minimal PEP-427 wheel (no network, no build deps)."""
    whl = os.path.join(dirpath, f"{name}-{version}-py3-none-any.whl")
    di = f"{name}-{version}.dist-info"
    files = {
        f"{name}/__init__.py": f"__version__ = {version!r}\n",
        f"{di}/METADATA": (f"Metadata-Version: 2.1\nName: {name}\n"
                           f"Version: {version}\n"),
        f"{di}/WHEEL": ("Wheel-Version: 1.0\nGenerator: test\n"
                        "Root-Is-Purelib: true\nTag: py3-none-any\n"),
    }
    record = "".join(f"{p},,\n" for p in files) + f"{di}/RECORD,,\n"
    files[f"{di}/RECORD"] = record
    with zipfile.ZipFile(whl, "w") as zf:
        for p, content in files.items():
            zf.writestr(p, content)
    return whl


def test_pip_env_isolated_package(tmp_path):
    """A task imports a package version the driver does not have at all:
    installed into an isolated node-cached env dir."""
    _make_wheel(str(tmp_path), version="2.5")
    os.environ["RAY_TPU_PIP_ARGS"] = f"--no-index --find-links {tmp_path}"
    try:
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote(runtime_env={"pip": ["rtenv_testpkg"]})
        def probe():
            import rtenv_testpkg

            return rtenv_testpkg.__version__

        with pytest.raises(ImportError):
            import rtenv_testpkg  # noqa: F401  driver must NOT have it

        assert ray_tpu.get(probe.remote(), timeout=120) == "2.5"

        # Second call reuses the cached env (fast path; same answer).
        assert ray_tpu.get(probe.remote(), timeout=60) == "2.5"
    finally:
        os.environ.pop("RAY_TPU_PIP_ARGS", None)
        ray_tpu.shutdown()


def test_working_dir_packed_to_uri(ray_start_regular, tmp_path):
    """A local working_dir is packed + uploaded at submission and
    extracted node-side; the task reads files relative to it."""
    (tmp_path / "data.txt").write_text("packaged-content")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def read():
        with open("data.txt") as f:
            return f.read(), os.getcwd()

    content, cwd = ray_tpu.get(read.remote(), timeout=60)
    assert content == "packaged-content"
    # The task ran in the EXTRACTED package dir, not the original.
    assert os.path.realpath(cwd) != os.path.realpath(str(tmp_path))


def test_py_modules_zip_uri(ray_start_regular, tmp_path):
    """py_modules given as a zip archive URI extracts and imports."""
    mod_dir = tmp_path / "modsrc"
    mod_dir.mkdir()
    (mod_dir / "zipped_mod.py").write_text("VALUE = 77\n")
    zip_path = tmp_path / "mod.zip"
    with zipfile.ZipFile(zip_path, "w") as zf:
        zf.write(mod_dir / "zipped_mod.py", "zipped_mod.py")

    @ray_tpu.remote(runtime_env={"py_modules": [f"file://{zip_path}"]})
    def use():
        import zipped_mod

        return zipped_mod.VALUE

    assert ray_tpu.get(use.remote(), timeout=60) == 77


def test_manager_refcount_gc(tmp_path):
    """Unit: URIs cache across ensures, and GC removes the materialized
    dir when the last referencing job releases."""
    import asyncio

    async def main():
        mgr = RuntimeEnvManager(str(tmp_path))
        pkg_dir = tmp_path / "wd"
        pkg_dir.mkdir()
        (pkg_dir / "f.txt").write_text("x")
        data = package_local_dir(str(pkg_dir))
        zip_path = tmp_path / "wd.zip"
        zip_path.write_bytes(data)
        uri = f"file://{zip_path}"

        ctx1 = await mgr.ensure({"working_dir": uri}, "job1")
        ctx2 = await mgr.ensure({"working_dir": uri}, "job2")
        assert ctx1["working_dir"] == ctx2["working_dir"]  # cached
        path = ctx1["working_dir"]
        assert os.path.isfile(os.path.join(path, "f.txt"))

        mgr.release_job("job1")
        assert os.path.isdir(path)  # job2 still references it
        mgr.release_job("job2")
        assert not os.path.exists(path)  # GC at zero refs
        assert mgr.uris_in_use() == {}

    asyncio.run(main())


def test_package_uri_is_content_addressed(tmp_path):
    d = tmp_path / "t"
    d.mkdir()
    (d / "a.py").write_text("A = 1\n")
    u1 = package_uri_for(package_local_dir(str(d)))
    u2 = package_uri_for(package_local_dir(str(d)))
    assert u1 == u2
    (d / "a.py").write_text("A = 2\n")
    assert package_uri_for(package_local_dir(str(d))) != u1
    assert pip_uri_for(["x", "y"]) == pip_uri_for(["y", "x"])  # order-free
