"""Nested fan-out scheduling under lease contention.

Regression coverage for the r4 release-gate deadlock (`nested_tasks`
width 8 depth 3): an owner blocked in ray.get whose lease requests hit
the raylet's lease timeout used to burn one spillback hop per retry and
silently give up after 8 — with the owner blocked, nothing re-pumped its
queue and the whole subtree wedged (reference behavior: lease requests
stay pending until schedulable, node_manager.cc HandleRequestWorkerLease
+ ClusterTaskManager queue revisits).

The test provokes the same signature fast: a sub-second lease timeout
plus a 2-CPU node guarantees retry storms; with the old code each
mid-tree owner's lease pump died ~4s in and the fan-out hung forever.
"""

import pytest

import ray_tpu
from ray_tpu._private.config import Config


@pytest.fixture
def contended_cluster():
    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.num_heartbeats_timeout = 5
    # Aggressively small: every queued lease wait times out quickly, so
    # the owner-side retry path (the deadlocked one) is exercised many
    # times within seconds.
    cfg.worker_lease_timeout_s = 0.5
    cfg.worker_startup_timeout_s = 120.0
    cfg.object_store_memory = 64 * 1024 * 1024
    ray_tpu.init(num_cpus=2, config=cfg)
    yield
    ray_tpu.shutdown()


def test_nested_fanout_survives_lease_retry_storm(contended_cluster):
    @ray_tpu.remote
    def spawn(width, d):
        if d == 0:
            return 1
        import ray_tpu as rt

        return sum(rt.get([spawn.remote(width, d - 1) for _ in range(width)],
                          timeout=240))

    # width 4 depth 3 = 85 tasks, ~21 concurrently blocked owners on a
    # 2-CPU node: mid-tree owners spend most of their life waiting on
    # leases that time out and must be re-requested indefinitely.
    total = ray_tpu.get(spawn.remote(4, 3), timeout=240)
    assert total == 4 ** 3


def test_persistent_spawn_failure_fails_queue_with_cause(monkeypatch):
    """Worker-spawn failures are BUDGETED (5 consecutive -> fail the
    queued tasks with the cause) instead of retrying forever: a broken
    worker environment must surface as an error, not an infinite hang
    (r5 review finding on the deadlock fix). Forced here by a startup
    timeout no real spawn can meet."""
    import pytest

    import ray_tpu.exceptions as exc

    # BEFORE Config(): the driver's config (env-overridden here) is what
    # the GCS serves to the raylet at boot — every spawn's registration
    # window then expires instantly and each lease grant reports
    # spawn_failure.
    monkeypatch.setenv("RAY_TPU_WORKER_STARTUP_TIMEOUT_S", "0.05")
    cfg = Config()
    cfg.health_check_period_s = 0.2
    cfg.worker_lease_timeout_s = 5.0
    cfg.use_worker_zygote = False
    cfg.prestart_workers = 0
    ray_tpu.init(num_cpus=2, config=cfg)
    try:
        @ray_tpu.remote
        def f():
            return 1

        with pytest.raises(exc.RayTpuError, match="unschedulable|startup"):
            ray_tpu.get(f.remote(), timeout=120)
    finally:
        monkeypatch.delenv("RAY_TPU_WORKER_STARTUP_TIMEOUT_S")
        ray_tpu.shutdown()
